"""Flight recorder: a bounded ring of the last N span/event records.

Crash forensics for long runs.  ``telemetry.jsonl`` (export.py) is the
full flight log you opt into per run; the flight recorder is the cheap
always-on black box — a ``deque(maxlen=N)`` of the same record dicts,
kept in memory and dumped to ``flight.jsonl`` only when something goes
wrong (SIGTERM, unhandled exception) or when an operator asks
(``GET /debugz/flight`` on the ops server).

"Always-on" means: enabling it (``flight.enable()``, or implicitly via
``start_ops_server``) turns span *collection* on (``spans.enable()``)
and installs the ring as an extra sink, WITHOUT requiring a
``RunTelemetry`` artifact — telemetry export stays otherwise off.  The
per-record cost is one deque append under a lock; the bit-identity
guarantee holds because span collection itself never touches RNG state
(asserted by ``tests/test_telemetry.py``).

Dump triggers:

- ``SIGTERM`` — dump, then chain to the previously installed handler
  (or re-raise the default die).  Installed only from the main thread
  (``signal.signal`` raises elsewhere); worker threads still get the
  excepthook.
- unhandled exception — ``sys.excepthook`` wrapper dumps, then chains.
- explicit :meth:`FlightRecorder.dump` / ``/debugz/flight``.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from . import spans as _spans

__all__ = ["FlightRecorder", "enable", "disable", "active", "DEFAULT_CAPACITY"]

#: Ring size: at master span rates (a handful of records per generation
#: plus per-job broker spans) 2048 records cover the last several
#: generations of even a large fleet — enough tail to reconstruct what
#: the run was doing when it died, at <10 MB worst case.
DEFAULT_CAPACITY = 2048

_active: Optional["FlightRecorder"] = None
_hooks_installed = False
_prev_excepthook = None
_prev_sigterm = None


class FlightRecorder:
    """Thread-safe bounded ring of telemetry record dicts."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 path: str = "flight.jsonl"):
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self.capacity = int(capacity)
        self.path = path
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)
        self._total = 0
        self._t_start = time.time()

    def record(self, rec: Dict[str, Any]) -> None:
        """Append one record (called from spans._emit on every finished
        span/event while the recorder is installed)."""
        with self._lock:
            self._ring.append(rec)
            self._total += 1

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    @property
    def total(self) -> int:
        """Records ever seen (total - len = records the ring dropped)."""
        with self._lock:
            return self._total

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def render_jsonl(self, reason: str = "request") -> str:
        """Header line + one record per line (same schema as
        ``telemetry.jsonl`` minus the summary)."""
        with self._lock:
            records = list(self._ring)
            total = self._total
        head = {
            "type": "flight",
            "reason": reason,
            "t_wall": time.time(),
            "pid": os.getpid(),
            "capacity": self.capacity,
            "recorded": len(records),
            "dropped": total - len(records),
        }
        lines = [json.dumps(head, separators=(",", ":"), default=str)]
        lines.extend(
            json.dumps(r, separators=(",", ":"), default=str) for r in records)
        return "\n".join(lines) + "\n"

    def dump(self, path: Optional[str] = None, reason: str = "request") -> str:
        """Write the ring to ``path`` (default: ctor path).  Returns the
        path written.  Overwrites — the newest dump is the one that
        matters after a crash."""
        out = path or self.path
        data = self.render_jsonl(reason=reason)
        with open(out, "w", encoding="utf-8") as fh:
            fh.write(data)
            fh.flush()
        return out


def active() -> Optional[FlightRecorder]:
    return _active


def enable(path: str = "flight.jsonl",
           capacity: int = DEFAULT_CAPACITY) -> FlightRecorder:
    """Install a flight recorder: enables span collection, routes every
    record through the ring, and arms the SIGTERM/excepthook dumpers.
    Idempotent-ish: a second call replaces the active recorder."""
    global _active
    rec = FlightRecorder(capacity=capacity, path=path)
    _active = rec
    _spans.set_flight_sink(rec)
    _spans.enable()
    _install_hooks()
    return rec


def disable() -> None:
    """Detach the recorder.  Span collection stays enabled only if a run
    sink (RunTelemetry) is still installed — the recorder was the only
    consumer otherwise, so collecting would be pure overhead."""
    global _active
    _active = None
    _spans.set_flight_sink(None)
    if not _spans.has_run_sink():
        _spans.disable()


def _dump_active(reason: str) -> Optional[str]:
    rec = _active
    if rec is None:
        return None
    try:
        return rec.dump(reason=reason)
    except Exception:  # pragma: no cover - a dying process must still die
        return None


def _excepthook(exc_type, exc, tb):
    rec = _active
    if rec is not None:
        rec.record({
            "type": "event",
            "name": "unhandled_exception",
            "t_wall": time.time(),
            "pid": os.getpid(),
            "data": {"exc_type": exc_type.__name__, "exc": str(exc)},
        })
    _dump_active("unhandled_exception")
    hook = _prev_excepthook or sys.__excepthook__
    hook(exc_type, exc, tb)


def _sigterm_handler(signum, frame):
    _dump_active("sigterm")
    prev = _prev_sigterm
    if callable(prev):
        prev(signum, frame)
    else:
        # Restore the default disposition and re-deliver so the process
        # still dies with the conventional SIGTERM exit status.
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        os.kill(os.getpid(), signal.SIGTERM)


def _install_hooks() -> None:
    """Chain our dumpers in front of whatever is installed.  Once per
    process; the handlers are no-ops while no recorder is active, so
    disable() doesn't need to unwind them."""
    global _hooks_installed, _prev_excepthook, _prev_sigterm
    if _hooks_installed:
        return
    _prev_excepthook = sys.excepthook
    sys.excepthook = _excepthook
    try:
        _prev_sigterm = signal.getsignal(signal.SIGTERM)
        signal.signal(signal.SIGTERM, _sigterm_handler)
    except ValueError:
        # Not the main thread (e.g. ops server started from a worker
        # thread): excepthook still armed, signal dump unavailable.
        _prev_sigterm = None
    _hooks_installed = True
