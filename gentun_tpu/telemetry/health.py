"""Liveness plane: heartbeat registry, status providers, stall watchdog.

The telemetry plane (registry/spans/export) records *what happened*;
this module answers *"is the search healthy right now?"* for the ops
endpoints (``ops_server.py``).  Three pieces:

- a **heartbeat registry** the long-running loops beat into (master
  engine loop, broker poll loop, worker consume/evaluate loops).  A
  source registered with a ``timeout`` *gates* ``/healthz``: silence
  longer than the timeout flips it to 503.  A source registered without
  one is advisory — shown in ``/statusz``, never a 503.
- **status providers** — named callables (broker fleet snapshot, engine
  progress) polled lazily when ``/statusz`` is scraped.  Registration is
  a dict write; nothing is called until someone asks.
- :class:`StallWatchdog` — flags any dispatched job in flight longer
  than ``max(floor_s, k × rolling-p95(dispatch RTT))``, bumps the
  ``stragglers_detected_total`` counter, emits a ``straggler_detected``
  telemetry event, and (opt-in) invokes a requeue hook.  Flagged jobs
  also gate ``/healthz``.

Same contract as ``spans.py``: **off by default**, and the off path is
one module-level bool read (:func:`beat` returns immediately).  Nothing
here touches RNG state, so enabling the plane cannot perturb a search
trajectory.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import spans as _spans
from .registry import get_registry

__all__ = [
    "enabled",
    "enable",
    "disable",
    "beat",
    "register_source",
    "unregister_source",
    "heartbeats",
    "register_status_provider",
    "unregister_status_provider",
    "register_engine_status",
    "unregister_engine_status",
    "status_snapshot",
    "register_watchdog",
    "unregister_watchdog",
    "check_health",
    "reset",
    "StallWatchdog",
]

# Module-level switch, mirroring spans._ENABLED: one bool read is the
# entire disabled-path cost of every beat() call site.
_ENABLED = False

_lock = threading.Lock()
# name -> [last_beat_monotonic | None, timeout_s | None]
_sources: Dict[str, List[Optional[float]]] = {}
# name -> zero-arg callable returning a JSON-native snapshot
_providers: Dict[str, Callable[[], Any]] = {}
# Watchdogs whose flagged stragglers gate /healthz (brokers register
# theirs on start(), unregister on stop()).
_watchdogs: List["StallWatchdog"] = []
# session -> engine snapshot callable.  A keyed registry instead of the
# last-wins "engine" provider slot: two engines sharing a broker (multi-
# tenant sessions) each get a row in /statusz instead of overwriting
# each other.
_engines: Dict[str, Callable[[], Any]] = {}


def enabled() -> bool:
    """The one guard every beat site checks."""
    return _ENABLED


def enable() -> None:
    """Turn the plane on.  Every known source gets a fresh stamp: beats
    only flow while enabled, so ages accrued before this moment are
    meaningless and must not trip an instant 503."""
    global _ENABLED
    now = time.monotonic()
    with _lock:
        for src in _sources.values():
            src[0] = now
    _ENABLED = True


def disable() -> None:
    global _ENABLED
    _ENABLED = False


def reset() -> None:
    """Drop every source, provider, and watchdog (tests)."""
    with _lock:
        _sources.clear()
        _providers.clear()
        _engines.clear()
        del _watchdogs[:]


def beat(name: str) -> None:
    """Stamp ``name``'s heartbeat.  No-op (one bool read) when disabled.

    Unknown names auto-register as advisory sources so a loop can beat
    before anyone declared it — gating requires an explicit
    :func:`register_source` with a timeout.
    """
    if not _ENABLED:
        return
    now = time.monotonic()
    with _lock:
        src = _sources.get(name)
        if src is None:
            _sources[name] = [now, None]
        else:
            src[0] = now


def register_source(name: str, timeout: Optional[float] = None) -> None:
    """Declare a heartbeat source.  ``timeout`` seconds of silence flips
    ``/healthz`` to 503; ``timeout=None`` makes it advisory (statusz
    only).  Registration stamps an initial beat so a freshly registered
    source is not instantly stale."""
    now = time.monotonic()
    with _lock:
        _sources[name] = [now, timeout]


def unregister_source(name: str) -> None:
    with _lock:
        _sources.pop(name, None)


def heartbeats() -> Dict[str, Dict[str, Any]]:
    """Per-source {age_s, timeout_s, stale} snapshot."""
    now = time.monotonic()
    with _lock:
        items = {k: (v[0], v[1]) for k, v in _sources.items()}
    out: Dict[str, Dict[str, Any]] = {}
    for name, (last, timeout) in sorted(items.items()):
        age = None if last is None else now - last
        stale = timeout is not None and age is not None and age > timeout
        out[name] = {
            "age_s": None if age is None else round(age, 3),
            "timeout_s": timeout,
            "stale": stale,
        }
    return out


def register_status_provider(name: str, fn: Callable[[], Any]) -> None:
    """Install a named snapshot callable for ``/statusz``.  Last-wins on
    name collision (a re-started broker re-claims "fleet")."""
    with _lock:
        _providers[name] = fn


def unregister_status_provider(name: str, fn: Optional[Callable[[], Any]] = None) -> None:
    """Remove a provider.  With ``fn``, removal is identity-checked so a
    stopped broker cannot evict the provider of the one that replaced it."""
    with _lock:
        if fn is None or _providers.get(name) is fn:
            _providers.pop(name, None)


def register_engine_status(session: str, fn: Callable[[], Any]) -> None:
    """Install an ENGINE snapshot callable, keyed by its search session.

    The old contract — ``register_status_provider("engine", fn)`` — was
    last-wins: two engines sharing a broker (multi-tenant sessions)
    silently overwrote each other and ``/statusz`` showed whichever
    registered second.  Engines now register here instead; the combined
    ``engine`` provider renders ONE engine as the same flat snapshot as
    before (plus a ``session`` key) and several as
    ``{"mode": "multi", "sessions": {session: snapshot}}``.
    """
    with _lock:
        _engines[str(session)] = fn
        _providers["engine"] = _engine_status


def unregister_engine_status(session: str, fn: Optional[Callable[[], Any]] = None) -> None:
    """Remove one engine's snapshot (identity-checked like
    :func:`unregister_status_provider`); the combined provider goes with
    the last engine."""
    with _lock:
        if fn is None or _engines.get(str(session)) is fn:
            _engines.pop(str(session), None)
        if not _engines and _providers.get("engine") is _engine_status:
            _providers.pop("engine", None)


def _engine_status() -> Any:
    """The combined ``engine`` /statusz block over every registered
    engine.  A snapshot callable that raises contributes its error string,
    same contract as :func:`status_snapshot`."""
    with _lock:
        engines = dict(_engines)

    def _snap(fn: Callable[[], Any]) -> Any:
        try:
            return fn()
        except Exception as e:  # pragma: no cover - defensive
            return {"error": f"{type(e).__name__}: {e}"}

    if len(engines) == 1:
        (sid, fn), = engines.items()
        snap = _snap(fn)
        if isinstance(snap, dict):
            snap.setdefault("session", sid)
        return snap
    return {"mode": "multi",
            "sessions": {sid: _snap(fn) for sid, fn in sorted(engines.items())}}


def status_snapshot() -> Dict[str, Any]:
    """Poll every provider; a provider that raises contributes its error
    string instead of taking down the whole statusz page."""
    with _lock:
        providers = dict(_providers)
    out: Dict[str, Any] = {}
    for name, fn in sorted(providers.items()):
        try:
            out[name] = fn()
        except Exception as e:  # pragma: no cover - defensive
            out[name] = {"error": f"{type(e).__name__}: {e}"}
    return out


def register_watchdog(wd: "StallWatchdog") -> None:
    with _lock:
        if wd not in _watchdogs:
            _watchdogs.append(wd)


def unregister_watchdog(wd: "StallWatchdog") -> None:
    with _lock:
        try:
            _watchdogs.remove(wd)
        except ValueError:
            pass


def check_health() -> Tuple[bool, List[str]]:
    """(healthy, reasons).  Unhealthy iff a *gating* heartbeat source is
    stale or any registered watchdog currently flags a straggler.  Both
    conditions self-clear (a beat arrives; the job completes or is
    requeued), so recovery needs no operator action."""
    reasons: List[str] = []
    for name, info in heartbeats().items():
        if info["stale"]:
            reasons.append(
                f"heartbeat '{name}' stale: {info['age_s']}s > {info['timeout_s']}s")
    with _lock:
        dogs = list(_watchdogs)
    for wd in dogs:
        wd.check()  # flag anything newly over threshold before reporting
        for s in wd.stragglers():
            reasons.append(
                "straggler job %s on worker %s: in flight %.1fs > %.1fs threshold"
                % (s["job_id"], s["worker_id"], s["age_s"], s["threshold_s"]))
    return (not reasons), reasons


class StallWatchdog:
    """Flags jobs in flight longer than ``max(floor_s, k × p95(RTT))``.

    The broker feeds it from its loop thread (``job_started`` at
    dispatch, ``job_finished`` at result-accept, ``job_removed`` on
    requeue/cancel/fail) and drives :meth:`check` from a periodic task;
    the healthz handler may call :meth:`check`/:meth:`stragglers` from
    HTTP threads — every method takes the instance lock.

    The RTT window is a bounded deque kept here (not read back out of
    the registry histogram) so the threshold adapts to the live run and
    costs O(window) only on ``check``.  Until ``min_samples`` RTTs have
    been seen the threshold is just ``floor_s`` — early in a run the p95
    of two samples says nothing.

    ``on_straggler`` (opt-in) is called once per newly flagged job with
    the straggler info dict — the broker uses it to requeue
    (``straggler_requeue=True``).  A job is flagged at most once per
    dispatch; finishing or being removed clears the flag (and heals
    ``/healthz``).
    """

    def __init__(self, floor_s: float = 30.0, k: float = 4.0,
                 window: int = 256, min_samples: int = 8,
                 on_straggler: Optional[Callable[[Dict[str, Any]], None]] = None):
        if floor_s <= 0:
            raise ValueError(f"floor_s must be positive, got {floor_s}")
        if k <= 0:
            raise ValueError(f"k must be positive, got {k}")
        self.floor_s = float(floor_s)
        self.k = float(k)
        self.min_samples = int(min_samples)
        self.on_straggler = on_straggler
        self._lock = threading.Lock()
        self._rtts: deque = deque(maxlen=int(window))
        # job_id -> (t0, worker, session | None)
        self._inflight: Dict[str, Tuple[float, str, Optional[str]]] = {}
        self._flagged: Dict[str, Dict[str, Any]] = {}
        self.detected_total = 0

    def job_started(self, job_id: str, worker_id: str,
                    session: Optional[str] = None) -> None:
        """``session`` (multi-tenant brokers) rides into the straggler
        info/event and labels ``stragglers_detected_total``; ``None`` (the
        single-tenant default) keeps the metric series unchanged."""
        with self._lock:
            self._inflight[str(job_id)] = (
                time.monotonic(), str(worker_id),
                None if session is None else str(session))

    def job_finished(self, job_id: str) -> None:
        """Result accepted: record the RTT sample and clear any flag."""
        with self._lock:
            entry = self._inflight.pop(str(job_id), None)
            if entry is not None:
                self._rtts.append(time.monotonic() - entry[0])
            self._flagged.pop(str(job_id), None)

    def job_removed(self, job_id: str) -> None:
        """Requeue/cancel/fail: forget the job WITHOUT taking an RTT
        sample (a requeued job's elapsed time is not a round trip)."""
        with self._lock:
            self._inflight.pop(str(job_id), None)
            self._flagged.pop(str(job_id), None)

    def threshold(self) -> float:
        """Current flagging threshold: ``max(floor_s, k × p95(RTT))``."""
        with self._lock:
            return self._threshold_locked()

    def _threshold_locked(self) -> float:
        n = len(self._rtts)
        if n < self.min_samples:
            return self.floor_s
        ordered = sorted(self._rtts)
        p95 = ordered[min(n - 1, int(0.95 * n))]
        return max(self.floor_s, self.k * p95)

    def check(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """Flag every job over threshold that is not already flagged.
        Returns the NEWLY flagged stragglers (possibly empty) after
        bumping ``stragglers_detected_total`` and emitting a
        ``straggler_detected`` telemetry event for each."""
        t = time.monotonic() if now is None else now
        newly: List[Dict[str, Any]] = []
        with self._lock:
            thr = self._threshold_locked()
            for job_id, (t0, worker_id, session) in self._inflight.items():
                age = t - t0
                if age > thr and job_id not in self._flagged:
                    info = {
                        "job_id": job_id,
                        "worker_id": worker_id,
                        "age_s": round(age, 3),
                        "threshold_s": round(thr, 3),
                    }
                    if session is not None:
                        info["session"] = session
                    self._flagged[job_id] = info
                    self.detected_total += 1
                    newly.append(info)
        for info in newly:
            labels = {"worker": info["worker_id"]}
            if "session" in info:
                labels["session"] = info["session"]
            get_registry().counter("stragglers_detected_total", **labels).inc()
            _spans.record_event("straggler_detected", dict(info))
            if self.on_straggler is not None:
                try:
                    self.on_straggler(dict(info))
                except Exception:  # pragma: no cover - hook must not kill check
                    pass
        return newly

    def stragglers(self) -> List[Dict[str, Any]]:
        """Currently flagged jobs, ages refreshed."""
        now = time.monotonic()
        with self._lock:
            out = []
            for job_id, info in self._flagged.items():
                entry = self._inflight.get(job_id)
                d = dict(info)
                if entry is not None:
                    d["age_s"] = round(now - entry[0], 3)
                out.append(d)
            return out

    def in_flight(self) -> int:
        with self._lock:
            return len(self._inflight)

    def clear(self) -> None:
        with self._lock:
            self._inflight.clear()
            self._flagged.clear()
            self._rtts.clear()
