"""RunTelemetry: the per-run ``telemetry.jsonl`` artifact + final summary.

One :class:`RunTelemetry` per search run.  It is the process-wide span
sink while installed: every finished span/event — master-side directly,
worker-side via the ``spans`` field of ``result`` frames (see
``broker._on_result`` → :func:`gentun_tpu.telemetry.spans.ingest`) — is
appended to the JSONL file as it arrives, and the raw durations are kept
per span kind so :meth:`summary` reports *exact* p50/p95/p99 (the
registry histograms are the bucketed always-on estimate; the run summary
does better because it has the run's full duration list).

Artifact schema (one JSON object per line):

- ``{"type": "run_start", ...}``   — first line: pid, wall time, label
- ``{"type": "span", ...}``        — see ``spans.py`` record fields
- ``{"type": "event", ...}``       — structured events (fault injections)
- ``{"type": "summary", ...}``     — last line: per-kind percentiles,
  counter totals and gauge values from the metrics registry snapshot

Usage::

    with RunTelemetry("out/telemetry.jsonl") as run:
        ga.run(generations)
    print(run.summary()["spans"]["evaluate"]["p95"])
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from . import lineage as _lineage
from . import spans as _spans
from .registry import get_registry

__all__ = ["RunTelemetry", "start_run", "active_run", "end_run"]


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Exact linear-interpolated percentile of a pre-sorted list."""
    if not sorted_vals:
        return 0.0
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    pos = q * (len(sorted_vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return sorted_vals[lo] * (1.0 - frac) + sorted_vals[hi] * frac


class RunTelemetry:
    """Streaming JSONL writer + in-memory span aggregator for one run.

    Thread-safe: the master thread, the broker loop thread (ingesting
    worker reports), and any in-process worker threads all call
    :meth:`record` concurrently.
    """

    def __init__(self, path: str, label: Optional[str] = None, registry=None):
        self.path = str(path)
        self.label = label
        self._registry = registry or get_registry()
        self._lock = threading.Lock()
        self._fh = None
        self._durations: Dict[str, List[float]] = {}
        self._event_counts: Dict[str, int] = {}
        self._n_spans = 0
        self._closed = False
        self._installed = False
        self._t0 = time.monotonic()

    # -- lifecycle ---------------------------------------------------------

    def install(self) -> "RunTelemetry":
        """Open the artifact, become the process sink, enable tracing."""
        parent = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(parent, exist_ok=True)
        with self._lock:
            self._fh = open(self.path, "w", encoding="utf-8")
        self._write({"type": "run_start", "t_wall": time.time(),
                     "pid": os.getpid(), "label": self.label})
        _spans.set_run_sink(self)
        _spans.enable()
        self._installed = True
        return self

    def close(self) -> Dict[str, Any]:
        """Write the summary line, release the sink, return the summary."""
        if self._closed:
            return self.summary()
        self._closed = True
        summ = self.summary()
        self._write({"type": "summary", **summ})
        if self._installed:
            _spans.set_run_sink(None)
            # The flight recorder (ops plane) may still be consuming
            # records; only stop collection when this run was the last
            # sink — the mirror of flight.disable()'s has_run_sink check.
            if not _spans.has_flight_sink():
                _spans.disable()
            self._installed = False
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
        return summ

    def __enter__(self) -> "RunTelemetry":
        return self.install()

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    # -- record path -------------------------------------------------------

    def _write(self, rec: Dict[str, Any]) -> None:
        line = json.dumps(rec, separators=(",", ":"), default=str)
        with self._lock:
            if self._fh is not None:
                self._fh.write(line + "\n")
                self._fh.flush()

    def record(self, rec: Dict[str, Any]) -> None:
        """Sink entry point (spans module calls this for every record)."""
        kind = rec.get("kind")
        if rec.get("type") == "span" and kind is not None:
            with self._lock:
                self._durations.setdefault(kind, []).append(float(rec.get("dur_s", 0.0)))
                self._n_spans += 1
        elif rec.get("type") == "event":
            name = str(rec.get("name"))
            with self._lock:
                self._event_counts[name] = self._event_counts.get(name, 0) + 1
        self._write(rec)

    def ingest(self, records) -> None:
        """Merge a worker's shipped span records into this run (also
        re-observes their durations into the local registry histograms
        — see spans.ingest)."""
        _spans.ingest(records)

    # -- read side ---------------------------------------------------------

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            durations = {k: sorted(v) for k, v in self._durations.items()}
            events = dict(self._event_counts)
            n_spans = self._n_spans
        span_summ = {}
        for kind, vals in sorted(durations.items()):
            span_summ[kind] = {
                "count": len(vals),
                "total_s": sum(vals),
                "p50": _percentile(vals, 0.50),
                "p95": _percentile(vals, 0.95),
                "p99": _percentile(vals, 0.99),
            }
        snap = self._registry.snapshot()
        out = {
            "label": self.label,
            "wall_s": time.monotonic() - self._t0,
            "n_spans": n_spans,
            "spans": span_summ,
            "events": events,
            "counters": snap["counters"],
            "gauges": snap["gauges"],
        }
        if _lineage.enabled():
            # Chip-hour cost table (docs/OBSERVABILITY.md "Search
            # forensics"): measured device-seconds per rung/session/worker
            # from the forensics ledger — the run's cost accounting,
            # derived from per-genome device spans rather than estimated
            # from analytic schedule costs.
            ledger = _lineage.get_ledger()
            out["cost"] = {
                "device_s_total": ledger.total(),
                "cost_s_by_rung": {str(k): v for k, v in
                                   sorted(ledger.by_rung().items())},
                "cost_s_by_session": {k: v for k, v in
                                      sorted(ledger.by_session().items())},
                "cost_s_by_worker": {k: v for k, v in
                                     sorted(ledger.by_worker().items())},
            }
        return out


# -- module-level active run (what production hook sites look up) ----------

_active: Optional[RunTelemetry] = None
_active_lock = threading.Lock()


def start_run(path: str, label: Optional[str] = None) -> RunTelemetry:
    """Create + install the process-wide run; closes any previous one."""
    global _active
    with _active_lock:
        if _active is not None:
            _active.close()
        _active = RunTelemetry(path, label=label).install()
        return _active


def active_run() -> Optional[RunTelemetry]:
    return _active


def end_run() -> Optional[Dict[str, Any]]:
    """Close the active run and return its summary (None if no run)."""
    global _active
    with _active_lock:
        if _active is None:
            return None
        summ = _active.close()
        _active = None
        return summ
