"""Process-local metrics registry: counters, gauges, log-scale histograms.

SURVEY.md §5 records that the reference gentun has no metrics of any kind;
the rebuild's observability plane starts here.  Design constraints, each
load-bearing:

- **zero-dependency** — the registry must be importable by the GA outer
  loop, which never imports jax (``algorithms._initialized_chip_count``),
  and by workers on minimal installs.  stdlib only.
- **thread-safe** — the broker loop thread, worker consume threads, and
  the master thread all write concurrently.  One lock per instrument,
  held for a few arithmetic ops; no lock on the registry read path that
  tests care about (``snapshot`` takes the creation lock only to copy
  the instrument table).
- **fixed log-scale histogram buckets** — span durations range from
  microseconds (a cache hit) to minutes (a CIFAR compile); linear buckets
  cannot cover that.  Buckets are FIXED at construction so concurrent
  ``observe`` never reallocates and snapshots are always comparable.

Renderers: :meth:`MetricsRegistry.render_prometheus` (the text exposition
format, scrape-ready) and :meth:`MetricsRegistry.render_jsonl` (one JSON
object per metric line, the same schema ``snapshot`` returns — the
``telemetry.jsonl`` artifact embeds these).
"""

from __future__ import annotations

import json
import math
import threading
from typing import Any, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "DeltaSnapshotter",
    "get_registry",
    "DEFAULT_BUCKETS",
]


def _log_buckets(lo: float, hi: float, per_decade: int) -> Tuple[float, ...]:
    """Fixed log-scale bucket upper bounds from ``lo`` to ``hi`` inclusive."""
    n = int(round(math.log10(hi / lo) * per_decade))
    return tuple(lo * 10 ** (i / per_decade) for i in range(n + 1))


#: Default histogram bounds: 10 µs .. 10 ks, 4 buckets per decade (~1.78×
#: resolution).  Covers a sub-millisecond OneMax evaluation and a
#: minutes-long CIFAR-scale XLA compile in one fixed 37-bucket layout.
DEFAULT_BUCKETS: Tuple[float, ...] = _log_buckets(1e-5, 1e4, 4)


def _label_key(labels: Dict[str, Any]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    """Monotonically increasing count.  ``inc`` is thread-safe."""

    __slots__ = ("name", "labels", "_lock", "_value")

    def __init__(self, name: str, labels: Dict[str, str]):
        self.name = name
        self.labels = dict(labels)
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease (inc {amount})")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """A value that can go up and down (queue depth, connected workers)."""

    __slots__ = ("name", "labels", "_lock", "_value")

    def __init__(self, name: str, labels: Dict[str, str]):
        self.name = name
        self.labels = dict(labels)
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Cumulative histogram over fixed log-scale buckets.

    ``observe`` is O(log n_buckets) (bisect) under one lock — safe from
    the broker loop thread at per-frame rates.  ``quantile`` interpolates
    log-linearly inside the bucket; span-record percentiles in the run
    summary are exact (``export.RunTelemetry`` keeps the raw durations),
    the histogram quantile is the cheap always-on estimate.
    """

    __slots__ = ("name", "labels", "bounds", "_lock", "_counts", "_sum", "_count")

    def __init__(self, name: str, labels: Dict[str, str],
                 buckets: Optional[Iterable[float]] = None):
        self.name = name
        self.labels = dict(labels)
        bounds = tuple(sorted(float(b) for b in (buckets or DEFAULT_BUCKETS)))
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self.bounds = bounds
        self._lock = threading.Lock()
        self._counts = [0] * (len(bounds) + 1)  # +1 = +Inf overflow bucket
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        import bisect

        v = float(value)
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def quantile(self, q: float) -> float:
        """Estimated q-quantile (0..1), log-interpolated within the bucket."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        with self._lock:
            total = self._count
            counts = list(self._counts)
        if total == 0:
            return 0.0
        rank = q * total
        seen = 0
        for i, c in enumerate(counts):
            seen += c
            if seen >= rank and c:
                if i >= len(self.bounds):
                    return self.bounds[-1]  # overflow bucket: clamp
                hi = self.bounds[i]
                lo = self.bounds[i - 1] if i else hi / 10.0
                frac = (rank - (seen - c)) / c
                return lo * (hi / lo) ** frac
        return self.bounds[-1]  # pragma: no cover - defensive

    def snapshot_buckets(self) -> List[Tuple[float, int]]:
        """Cumulative (upper_bound, count) pairs, Prometheus-style."""
        with self._lock:
            counts = list(self._counts)
        out, cum = [], 0
        for b, c in zip(self.bounds, counts):
            cum += c
            out.append((b, cum))
        out.append((math.inf, cum + counts[-1]))
        return out


class MetricsRegistry:
    """Thread-safe instrument factory + snapshot/render surface.

    ``counter``/``gauge``/``histogram`` are get-or-create keyed on
    (name, sorted labels): calling them on the hot path is a dict lookup
    under the registry lock, but callers that care (broker, populations)
    hold the instrument object instead of re-looking it up per event.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._instruments: Dict[Tuple[str, str, Tuple], Any] = {}

    def _get(self, cls_tag: str, cls, name: str, labels: Dict[str, Any], **kw):
        key = (cls_tag, name, _label_key(labels))
        with self._lock:
            inst = self._instruments.get(key)
            if inst is None:
                inst = cls(name, {str(k): str(v) for k, v in labels.items()}, **kw)
                self._instruments[key] = inst
            return inst

    def counter(self, name: str, **labels: Any) -> Counter:
        return self._get("counter", Counter, name, labels)

    def gauge(self, name: str, **labels: Any) -> Gauge:
        return self._get("gauge", Gauge, name, labels)

    def histogram(self, name: str, buckets: Optional[Iterable[float]] = None,
                  **labels: Any) -> Histogram:
        return self._get("histogram", Histogram, name, labels, buckets=buckets)

    def reset(self) -> None:
        """Drop every instrument (tests; a fresh run artifact)."""
        with self._lock:
            self._instruments.clear()

    # -- read side ---------------------------------------------------------

    def _items(self) -> List[Tuple[str, Any]]:
        with self._lock:
            return [(tag, inst) for (tag, _, _), inst in sorted(
                self._instruments.items(),
                key=lambda kv: (kv[0][1], kv[0][2], kv[0][0]),
            )]

    def snapshot(self) -> Dict[str, Any]:
        """{"counters": [...], "gauges": [...], "histograms": [...]} — every
        value JSON-native, the shape the JSONL renderer and the run summary
        consume."""
        out: Dict[str, List[Dict[str, Any]]] = {
            "counters": [], "gauges": [], "histograms": [],
        }
        for tag, inst in self._items():
            if tag == "counter":
                out["counters"].append(
                    {"name": inst.name, "labels": inst.labels, "value": inst.value})
            elif tag == "gauge":
                out["gauges"].append(
                    {"name": inst.name, "labels": inst.labels, "value": inst.value})
            else:
                out["histograms"].append({
                    "name": inst.name,
                    "labels": inst.labels,
                    "count": inst.count,
                    "sum": inst.sum,
                    "buckets": [
                        ["+Inf" if math.isinf(b) else b, c]
                        for b, c in inst.snapshot_buckets()
                    ],
                })
        return out

    def render_jsonl(self) -> str:
        """One JSON object per metric, newline-delimited (artifact-ready)."""
        lines = []
        snap = self.snapshot()
        for tag in ("counters", "gauges", "histograms"):
            for rec in snap[tag]:
                lines.append(json.dumps({"metric": tag[:-1], **rec},
                                        separators=(",", ":")))
        return "\n".join(lines) + ("\n" if lines else "")

    def render_prometheus(self) -> str:
        """Prometheus text exposition format (scrape- or textfile-ready).

        Label values are escaped per the exposition format spec
        (backslash, double-quote, newline — in that order, so an
        already-present backslash can't re-arm the later replacements).
        """

        def esc(v: str) -> str:
            return v.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")

        def fmt_labels(labels: Dict[str, str], extra: str = "") -> str:
            parts = [f'{k}="{esc(v)}"' for k, v in sorted(labels.items())]
            if extra:
                parts.append(extra)
            return "{" + ",".join(parts) + "}" if parts else ""

        lines: List[str] = []
        typed: set = set()
        for tag, inst in self._items():
            if (tag, inst.name) not in typed:
                typed.add((tag, inst.name))
                lines.append(f"# TYPE {inst.name} {tag}")
            if tag in ("counter", "gauge"):
                lines.append(f"{inst.name}{fmt_labels(inst.labels)} {inst.value:g}")
            else:
                for b, c in inst.snapshot_buckets():
                    le = "+Inf" if math.isinf(b) else f"{b:g}"
                    le_label = 'le="%s"' % le
                    lines.append(
                        f"{inst.name}_bucket{fmt_labels(inst.labels, le_label)} {c}")
                lines.append(f"{inst.name}_sum{fmt_labels(inst.labels)} {inst.sum:g}")
                lines.append(f"{inst.name}_count{fmt_labels(inst.labels)} {inst.count}")
        return "\n".join(lines) + ("\n" if lines else "")


class DeltaSnapshotter:
    """Incremental :meth:`MetricsRegistry.snapshot`: only changed series.

    The aggregator pusher ships a snapshot every flush interval; most
    series are quiet between flushes (a search touches a handful of
    instruments per job).  ``collect`` memoizes the last-shipped scalar
    per instrument — ``(value)`` for counters/gauges, ``(count, sum)``
    for histograms — and emits only series whose scalar moved, with the
    FULL cumulative value (the aggregator derives deltas itself, which
    is what makes counter-reset detection possible server-side).  Cost
    is O(#instruments) cheap compares per flush, zero per metric write —
    the property the ``broker_throughput`` push-path gate certifies.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self._registry = registry if registry is not None else get_registry()
        self._last: Dict[Tuple[str, str, Tuple], Any] = {}

    def collect(self, full: bool = False) -> Dict[str, Any]:
        """Changed-series snapshot (same shape as ``snapshot``).

        ``full=True`` resends everything (first push after a reconnect,
        so an aggregator that lost state recovers the whole picture).
        """
        out: Dict[str, List[Dict[str, Any]]] = {
            "counters": [], "gauges": [], "histograms": [],
        }
        last = self._last
        for tag, inst in self._registry._items():
            key = (tag, inst.name, _label_key(inst.labels))
            if tag == "histogram":
                cur = (inst.count, inst.sum)
            else:
                cur = inst.value
            if not full and last.get(key) == cur:
                continue
            last[key] = cur
            if tag == "counter":
                out["counters"].append(
                    {"name": inst.name, "labels": inst.labels, "value": cur})
            elif tag == "gauge":
                out["gauges"].append(
                    {"name": inst.name, "labels": inst.labels, "value": cur})
            else:
                out["histograms"].append({
                    "name": inst.name,
                    "labels": inst.labels,
                    "count": cur[0],
                    "sum": cur[1],
                    "buckets": [
                        ["+Inf" if math.isinf(b) else b, c]
                        for b, c in inst.snapshot_buckets()
                    ],
                })
        return out

    def reset(self) -> None:
        """Forget memoized values: the next ``collect`` ships everything."""
        self._last.clear()


#: The process-wide default registry.  Everything in-tree records here;
#: tests that need isolation construct their own MetricsRegistry.
_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _REGISTRY
