"""Black-box canary plane: golden-genome probes of the REAL serving path.

Every sensor built through PRs 14–19 is white-box — the fleet reporting
on itself.  Nothing measured the fleet the way a *user* experiences it,
and nothing continuously verified that the increasingly aggressive
serving path (sharding, cross-session packing, the wire fast path,
shared caches) still returns **bit-correct** fitnesses.  This module is
that missing synthetic monitor: a :class:`CanaryDaemon` — a stdlib-HTTP
sibling of the fitness/compile/aggregator services — continuously runs
tiny known-answer probe sessions end to end through the production
stack:

1. ``SessionClient.open_session(tag="canary", weight≈0, quota 1)`` —
   exercising auth, admission control, shard routing, reconnect;
2. submit ONE **golden genome** — a member of a content-addressed golden
   set keyed ``space_key × fidelity fingerprint × genome key`` whose
   fitness is *sealed* at first evaluation (:class:`GoldenSet`);
3. wait for the result, read the broker's time-to-first-dispatch,
   verify the fitness is **bit-equal** to the sealed value, close.

Each probe decomposes into golden-signal SLIs (docs/OBSERVABILITY.md):
``canary_open_seconds``, ``canary_ttfd_seconds``, ``canary_e2e_seconds``,
``canary_errors_total{stage}``, and the headline
``canary_fitness_drift_total`` — a returned fitness that is not
bit-equal to its sealed value means the fleet is lying, and
``telemetry.slo.default_rules``'s zero-tolerance ``canary_correctness``
rule pages on the first occurrence.

Probes are invisible to tenants by construction:

- **weight ≈ 0, quota 1** — the fair-share scheduler only hands a probe
  a slot the tenants aren't contending for, and at most one probe job is
  ever in flight;
- **rung-0 fidelity** — the cheapest runnable schedule, tagged with a
  real v1 fidelity tag so the worker's fingerprint check is exercised;
- **no_memo** — the probe payload carries ``no_memo: true``, which the
  worker folds into its evaluation grouping and answers with NO fitness
  cache at all (neither lookup nor publish): every probe is a real
  evaluation, and sealed goldens never memoize into tenant caches;
- **session tag** — the broker keeps ``tag="canary"`` sessions out of
  tenant-facing SLI series (``session_in_flight``,
  ``session_queue_depth``, per-session ``queue_wait_s``).

With ``--aggregator-url`` the daemon pushes its SLIs into the fleet
aggregator (role ``canary``) where the three stock canary rules judge
them; ``/canaryz`` serves the bounded probe history, ``/statusz`` the
config + verdict counts, ``/healthz`` liveness.  With lineage forensics
enabled the probe's trace context carries the ``fz`` flag, so the cost
ledger attributes canary device-seconds to the probe session — the data
behind ``scripts/canary_study.py``'s ≤1%-overhead gate.

Standalone::

    python -m gentun_tpu.telemetry.canary \\
        --broker-urls tcp://b0:5672,tcp://b1:5672 \\
        --aggregator-url http://agg:9100 --probes probes.json
"""

from __future__ import annotations

import json
import logging
import os
import struct
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

from . import lineage as _lineage
from . import spans as _tele
from .registry import get_registry as _get_registry

__all__ = ["GoldenSet", "CanaryDaemon", "main", "CANARY_TAG"]

logger = logging.getLogger("gentun_tpu.telemetry")

#: The session tag the broker recognizes (sessions.py / broker.py).
CANARY_TAG = "canary"

#: Probe records kept for ``/canaryz`` (durable copy: telemetry.jsonl).
_PROBE_RING = 256


def _bits(value: float) -> bytes:
    """IEEE-754 little-endian bytes — THE bit-equality the golden check
    means (``==`` would call -0.0 equal to 0.0 and NaN unequal to
    itself; the serving path must reproduce the exact bits)."""
    return struct.pack("<d", float(value))


class GoldenSet:
    """Content-addressed golden fitnesses, sealed at first evaluation.

    Key: ``space_key × fidelity fingerprint × genome key`` — the same
    identity triple the fitness store files measurements under, so a
    golden is pinned to one search space, one training schedule, and one
    exact genome.  The FIRST fitness observed for a key is *sealed*;
    every later probe must reproduce it bit-for-bit.  Optionally
    persisted as JSON (atomic tmp+rename per seal) so a restarted canary
    keeps holding the fleet to the same answers.
    """

    def __init__(self, path: Optional[str] = None):
        self._path = path
        self._lock = threading.Lock()
        self._sealed: Dict[str, float] = {}
        if path and os.path.exists(path):
            try:
                with open(path) as fh:
                    raw = json.load(fh)
                self._sealed = {str(k): float(v)
                                for k, v in (raw.get("sealed") or {}).items()}
            except (OSError, ValueError):
                logger.exception("golden set %s unreadable; starting empty", path)

    @staticmethod
    def key(space_key: str, fingerprint: str, genome_key: str) -> str:
        return f"{space_key}:{fingerprint}:{genome_key}"

    def __len__(self) -> int:
        with self._lock:
            return len(self._sealed)

    def get(self, key: str) -> Optional[float]:
        with self._lock:
            return self._sealed.get(key)

    def seal(self, key: str, fitness: float) -> Tuple[float, bool]:
        """Seal ``fitness`` under ``key`` unless already sealed; returns
        ``(sealed_value, newly_sealed)`` — an existing seal always wins
        (first evaluation is the truth; later values are *verified*)."""
        with self._lock:
            cur = self._sealed.get(key)
            if cur is not None:
                return cur, False
            self._sealed[key] = float(fitness)
            snap = dict(self._sealed)
        self._persist(snap)
        return float(fitness), True

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._sealed)

    def _persist(self, sealed: Dict[str, float]) -> None:
        if not self._path:
            return
        tmp = f"{self._path}.tmp"
        try:
            with open(tmp, "w") as fh:
                json.dump({"sealed": sealed}, fh, indent=2, sort_keys=True)
            os.replace(tmp, self._path)
        except OSError:
            logger.exception("golden set persist failed: %s", self._path)


# -- HTTP plane --------------------------------------------------------------


class _Handler(BaseHTTPRequestHandler):
    """Request handler; ``self.server.canary`` is the daemon."""

    server_version = "gentun-canary/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # noqa: D102 - silence stderr chatter
        pass

    def _send_json(self, code: int, obj: Any) -> None:
        body = json.dumps(obj, separators=(",", ":")).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 - http.server API
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        cn = self.server.canary  # type: ignore[attr-defined]
        if path in ("/", "/healthz"):
            self._send_json(200, {"status": "ok", **cn.stats()})
        elif path == "/statusz":
            self._send_json(200, cn.statusz())
        elif path == "/canaryz":
            self._send_json(200, cn.canaryz())
        else:
            self._send_json(404, {"error": f"no route {path}"})


# -- the daemon --------------------------------------------------------------


class CanaryDaemon:
    """Continuously probes a broker fleet with golden genomes.

    Parameters
    ----------
    broker_urls:
        Broker address list (``["tcp://h:p", ...]`` or ``"h:p,h:p"``) —
        handed to :class:`~gentun_tpu.distributed.sessions.SessionClient`
        verbatim, so a multi-shard list probes through the same
        consistent-hash router tenants use.
    probes:
        Known-answer probe payloads: each a dict with ``genes`` and
        (optionally) ``additional_parameters`` the fleet's species can
        evaluate.  Probed round-robin, one per cycle.
    space_key:
        Names the search space the probes belong to — the first component
        of every golden key, so one golden file can serve many fleets.
    aggregator_url:
        Optional fleet aggregator; when set the daemon pushes its SLIs
        there under role ``canary`` for the stock canary rules to judge.
    probe_interval / probe_timeout:
        Seconds between probe cycles / per-probe result deadline.
    golden_path:
        Optional JSON persistence for the golden set.
    token:
        Broker auth token (the same ``--token`` workers use).
    """

    def __init__(
        self,
        broker_urls,
        probes: List[Dict[str, Any]],
        space_key: str = "default",
        aggregator_url: Optional[str] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        probe_interval: float = 10.0,
        probe_timeout: float = 30.0,
        golden_path: Optional[str] = None,
        token: Optional[str] = None,
        serve_http: bool = True,
    ):
        if not probes:
            raise ValueError("CanaryDaemon needs at least one probe payload")
        if isinstance(broker_urls, str):
            broker_urls = [u for u in broker_urls.split(",") if u.strip()]
        self.broker_urls = list(broker_urls)
        if not self.broker_urls:
            raise ValueError("CanaryDaemon needs at least one broker url")
        self.probes = [dict(p) for p in probes]
        self.space_key = str(space_key)
        self.probe_interval = float(probe_interval)
        self.probe_timeout = float(probe_timeout)
        self.token = token
        self.golden = GoldenSet(golden_path)
        self._agg_url = aggregator_url.rstrip("/") if aggregator_url else None
        self._pusher = None
        self._client = None
        self._client_lock = threading.Lock()
        self._probe_i = 0
        self._cycle = 0
        self._probes_ring: List[Dict[str, Any]] = []
        self._ok_total = 0
        self._drift_total = 0
        self._error_total = 0
        self._started = time.time()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._http_thread: Optional[threading.Thread] = None
        if serve_http:
            self._httpd = ThreadingHTTPServer((host, port), _Handler)
            self._httpd.daemon_threads = True
            self._httpd.canary = self  # type: ignore[attr-defined]

    # -- address -----------------------------------------------------------

    @property
    def address(self) -> Optional[Tuple[str, int]]:
        return self._httpd.server_address[:2] if self._httpd else None

    @property
    def url(self) -> Optional[str]:
        addr = self.address
        return f"http://{addr[0]}:{addr[1]}" if addr else None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "CanaryDaemon":
        self._stop.clear()
        if self._agg_url is not None and self._pusher is None:
            from .aggregator import acquire_pusher

            self._pusher = acquire_pusher(self._agg_url, role="canary")
        if self._httpd is not None:
            self._http_thread = threading.Thread(
                target=self._httpd.serve_forever,
                kwargs={"poll_interval": 0.25},
                name="canary-http", daemon=True)
            self._http_thread.start()
        self._thread = threading.Thread(
            target=self._loop, name="canary", daemon=True)
        self._thread.start()
        logger.info(
            "canary serving on %s (brokers %s, %d probe(s), every %.1fs)",
            self.url or "<no http>", ",".join(self.broker_urls),
            len(self.probes), self.probe_interval)
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
        if self._http_thread is not None:
            self._http_thread.join(timeout=5.0)
            self._http_thread = None
        if self._thread is not None:
            self._thread.join(timeout=max(5.0, self.probe_timeout))
            self._thread = None
        with self._client_lock:
            client, self._client = self._client, None
        if client is not None:
            try:
                client.close()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
        if self._pusher is not None:
            from .aggregator import release_pusher

            release_pusher(self._pusher)
            self._pusher = None

    def __enter__(self) -> "CanaryDaemon":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _loop(self) -> None:
        while not self._stop.wait(self.probe_interval):
            try:
                self.probe_once()
            except Exception:  # noqa: BLE001 - the loop must survive anything
                logger.exception("canary probe cycle failed")

    # -- the probe ---------------------------------------------------------

    def _get_client(self):
        """The persistent probe client — reused across cycles so broker
        restarts exercise the real reconnect path; rebuilt from scratch
        only after a fatal (window-exhausted) connection error."""
        from ..distributed.sessions import SessionClient

        with self._client_lock:
            if self._client is None:
                self._client = SessionClient(
                    broker_urls=self.broker_urls, token=self.token,
                    timeout=min(10.0, self.probe_timeout), reconnect=True,
                    reconnect_window=self.probe_timeout)
            return self._client

    def _drop_client(self) -> None:
        with self._client_lock:
            client, self._client = self._client, None
        if client is not None:
            try:
                client.close()
            except Exception:  # noqa: BLE001
                pass

    def _build_payload(self, probe: Dict[str, Any],
                       fingerprint: str) -> Dict[str, Any]:
        """The wire payload for one probe: the caller's genes + params,
        plus a rung-0 v1 fidelity tag (the fingerprint check is part of
        the path under test) and the ``no_memo`` dedup bypass."""
        params = probe.get("additional_parameters") or {}
        payload: Dict[str, Any] = {"genes": probe["genes"], "no_memo": True}
        if params:
            payload["additional_parameters"] = params
        payload["fidelity"] = {"v": 1, "rung": 0, "fingerprint": fingerprint}
        ctx = _lineage.forensic_context(_tele.current_context())
        if ctx:
            # With forensics on, workers split the probe's device time
            # into ledger cells under the canary session — the data
            # behind the ≤1%-overhead gate (scripts/canary_study.py).
            payload["trace"] = ctx
        return payload

    def probe_once(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One full probe cycle; returns (and rings) the probe record.

        Public so tests, the chaos act, and the study harness drive
        cycles deterministically — the background loop calls nothing
        else.  Never raises: every failure mode lands in the record's
        ``error``/``stage`` fields and the ``canary_errors_total{stage}``
        counter.
        """
        now = time.time() if now is None else float(now)
        reg = _get_registry()
        self._cycle += 1
        probe = self.probes[self._probe_i % len(self.probes)]
        self._probe_i += 1
        gk = _lineage.genome_key(probe["genes"])
        record: Dict[str, Any] = {
            "type": "canary_probe",
            "cycle": self._cycle,
            "space_key": self.space_key,
            "genome": gk,
            "t": now,
        }
        sid = f"canary-{uuid.uuid4().hex[:10]}"
        t0 = time.monotonic()
        stage = "open"
        client = None
        drop = False
        with _tele.span("canary_probe", {"session": sid, "genome": gk}):
            try:
                client = self._get_client()
                client.open_session(sid, weight=1e-6, max_in_flight=1,
                                    tag=CANARY_TAG)
                open_s = time.monotonic() - t0
                record["open_s"] = round(open_s, 6)
                reg.histogram("canary_open_seconds").observe(open_s)

                stage = "submit"
                fingerprint = self._build_fingerprint(probe)
                job_id = f"cn-{self._cycle}-{uuid.uuid4().hex[:6]}"
                client.submit(sid, {job_id: self._build_payload(probe,
                                                                fingerprint)})

                stage = "result"
                deadline = time.monotonic() + self.probe_timeout
                fitness: Optional[float] = None
                while True:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"probe {job_id} no result in "
                            f"{self.probe_timeout}s")
                    results, failures = client.wait_any([job_id],
                                                        timeout=remaining)
                    if job_id in failures:
                        raise RuntimeError(f"probe failed: {failures[job_id]}")
                    if job_id in results:
                        fitness = float(results[job_id])
                        break
                e2e = time.monotonic() - t0
                record["e2e_s"] = round(e2e, 6)
                reg.histogram("canary_e2e_seconds").observe(e2e)

                # TTFD rides the (OPTIONAL) session_stats reply — absent
                # from old brokers, in which case the SLI is not observed.
                try:
                    ttfd = client.session_stats(sid).get("ttfd_s")
                except Exception:  # noqa: BLE001 - stats are advisory
                    ttfd = None
                if ttfd is not None:
                    record["ttfd_s"] = round(float(ttfd), 6)
                    reg.histogram("canary_ttfd_seconds").observe(float(ttfd))

                stage = "verify"
                key = GoldenSet.key(self.space_key, fingerprint, gk)
                sealed, newly = self.golden.seal(key, fitness)
                record["fitness"] = fitness
                record["sealed"] = sealed
                record["newly_sealed"] = newly
                if not newly and _bits(fitness) != _bits(sealed):
                    # THE headline: the fleet returned a wrong answer.
                    self._drift_total += 1
                    record["result"] = "drift"
                    reg.counter("canary_fitness_drift_total").inc()
                    reg.counter("canary_probes_total", result="drift").inc()
                    if _tele.enabled():
                        _tele.record_event("canary_drift", {
                            "session": sid, "genome": gk, "key": key,
                            "fitness": fitness, "sealed": sealed,
                            "cycle": self._cycle,
                        })
                    logger.error(
                        "CANARY DRIFT: golden %s returned %r, sealed %r — "
                        "the fleet is returning wrong fitnesses", key,
                        fitness, sealed)
                else:
                    self._ok_total += 1
                    record["result"] = "ok"
                    reg.counter("canary_probes_total", result="ok").inc()
            except Exception as e:  # noqa: BLE001 - every failure is a datum
                self._error_total += 1
                record["result"] = "error"
                record["stage"] = stage
                record["error"] = f"{type(e).__name__}: {e}"[:500]
                reg.counter("canary_errors_total", stage=stage).inc()
                reg.counter("canary_probes_total", result="error").inc()
                logger.warning("canary probe failed at %s: %s", stage, e)
                # A torn transport means the persistent client is suspect:
                # rebuild it next cycle (the fresh dial is itself a probe
                # of the open path).  A TimeoutError is NOT torn transport
                # — the broker is reachable, the fleet is slow/hung.
                drop = (isinstance(e, ConnectionError)
                        or (isinstance(e, OSError)
                            and not isinstance(e, TimeoutError)))
            finally:
                if client is not None and not drop:
                    try:
                        client.close_session(sid)
                    except Exception:  # noqa: BLE001 - close is best-effort
                        pass
                if drop:
                    self._drop_client()
        reg.gauge("canary_goldens_sealed").set(len(self.golden))
        self._probes_ring.append(record)
        if len(self._probes_ring) > _PROBE_RING:
            del self._probes_ring[: len(self._probes_ring) - _PROBE_RING]
        if _tele.enabled():
            _tele.emit_record(record)
        return record

    def _build_fingerprint(self, probe: Dict[str, Any]) -> str:
        from ..utils.fitness_store import fidelity_fingerprint

        return fidelity_fingerprint(probe.get("additional_parameters") or {})

    # -- read side ---------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        return {
            "uptime_s": round(time.time() - self._started, 3),
            "cycles": self._cycle,
            "ok_total": self._ok_total,
            "drift_total": self._drift_total,
            "error_total": self._error_total,
            "goldens_sealed": len(self.golden),
        }

    def statusz(self) -> Dict[str, Any]:
        return {
            "status": "ok",
            **self.stats(),
            "config": {
                "broker_urls": self.broker_urls,
                "space_key": self.space_key,
                "probes": len(self.probes),
                "probe_interval": self.probe_interval,
                "probe_timeout": self.probe_timeout,
                "aggregator": self._agg_url or "<none>",
            },
            "goldens": self.golden.snapshot(),
            "last_probe": self._probes_ring[-1] if self._probes_ring else None,
        }

    def canaryz(self) -> Dict[str, Any]:
        return {"probes": list(self._probes_ring),
                "total": self._cycle,
                "ok": self._ok_total,
                "drift": self._drift_total,
                "errors": self._error_total}


# -- standalone entrypoint ---------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m gentun_tpu.telemetry.canary`` — run the daemon."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m gentun_tpu.telemetry.canary",
        description="black-box canary: golden-genome correctness sentinel "
                    "+ end-to-end SLI probes of a broker fleet")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=9093,
                    help="ops plane bind port (/healthz /statusz /canaryz)")
    ap.add_argument("--broker-urls", required=True, metavar="URLS",
                    help="comma-separated broker addresses, e.g. "
                         "tcp://b0:5672,tcp://b1:5672 — multi-shard lists "
                         "probe through the tenants' consistent-hash router")
    ap.add_argument("--aggregator-url", default=None, metavar="URL",
                    help="fleet aggregator to push canary SLIs to (the "
                         "stock canary_error_burn/canary_latency/"
                         "canary_correctness rules judge them there)")
    ap.add_argument("--probes", required=True, metavar="JSON",
                    help="path to a JSON file: a list of probe payloads, "
                         'each {"genes": ..., "additional_parameters": ...}')
    ap.add_argument("--space-key", default="default",
                    help="golden-set namespace for this fleet's search space")
    ap.add_argument("--golden", default=None, metavar="PATH",
                    help="persist sealed goldens here (JSON; survives "
                         "canary restarts)")
    ap.add_argument("--probe-interval", type=float, default=10.0)
    ap.add_argument("--probe-timeout", type=float, default=30.0)
    ap.add_argument("--token", default=None, help="broker auth token")
    ap.add_argument("--telemetry", action="store_true",
                    help="emit {type: canary_probe} records to the "
                         "telemetry sink (GENTUN_TPU_TELEMETRY=1 equivalent)")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)

    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    if args.telemetry:
        _tele.enable()
    try:
        with open(args.probes) as fh:
            probes = json.load(fh)
        if not isinstance(probes, list):
            raise ValueError("--probes file must hold a JSON list")
        agg_url = None
        if args.aggregator_url:
            from .aggregator import parse_aggregator_url

            agg_url = parse_aggregator_url(args.aggregator_url)
        daemon = CanaryDaemon(
            args.broker_urls, probes,
            space_key=args.space_key,
            aggregator_url=agg_url,
            host=args.host, port=args.port,
            probe_interval=args.probe_interval,
            probe_timeout=args.probe_timeout,
            golden_path=args.golden,
            token=args.token,
        )
    except (OSError, ValueError) as e:
        raise SystemExit(f"canary: {e}")
    daemon.start()
    print(f"canary serving on {daemon.url} (/healthz /statusz /canaryz)")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        daemon.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
