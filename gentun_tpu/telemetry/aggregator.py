"""Fleet metrics aggregator: push-gateway + time-series rings + SLO plane.

Every gentun process already exports a process-local ``/metrics`` (PR 5),
but a fleet of one master, one broker, and N workers is N+2 scrape
targets with no time dimension and no judgment.  This module closes that
gap with the same three-part shape as the fitness/compile services:

- :class:`MetricsAggregator` — a ``ThreadingHTTPServer`` daemon that
  accepts ``POST /v1/push`` snapshots (``AGG_PROTOCOL`` guarded, HTTP 409
  on skew — all-writers-upgrade-together, like ``FITNESS_PROTOCOL``),
  merges per-process series into one fleet exposition on ``/metrics``,
  keeps a bounded ring of ``(t, value)`` points per series, and drives a
  declarative SLO engine (:mod:`gentun_tpu.telemetry.slo`) whose alerts
  surface on ``/alertz`` and as ``{"type": "alert"}`` telemetry records.

  Merge semantics are the part worth being careful about: pushers send
  *cumulative* values with ``(boot_id, seq)`` identity, so the server —
  not the client — detects counter resets (a restarted worker pushing
  ``5`` after ``100`` contributes ``105`` to the fleet total, never
  ``-95``) and drops late/out-of-order snapshots (stale ``seq`` under an
  unchanged ``boot_id``).

- :class:`TelemetryPusher` — the client side: a daemon flusher that ships
  a memoized snapshot *delta* (:class:`~gentun_tpu.telemetry.registry.
  DeltaSnapshotter`: only series that moved since the last push) every
  ``interval`` seconds.  Every network failure marks the aggregator down
  for a ``cooldown`` window and emits exactly ONE ``aggregator_degraded``
  telemetry event per up→down transition — aggregator downtime can never
  touch a search, the same degradation contract as
  ``FitnessServiceClient``.

- :func:`acquire_pusher`/:func:`release_pusher` — a refcounted per-URL
  process registry, because the master and its in-process broker share
  one metrics registry: two components wiring the same URL must share
  one pusher (their roles merge into the ``role`` label) or the fleet
  rollup would double-count every counter in that process.

Run standalone::

    python -m gentun_tpu.telemetry.aggregator --port 9100
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import socket
import threading
import time
import urllib.request
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse
from uuid import uuid4

from . import spans as _tele
from .buildinfo import set_build_info
from .registry import DeltaSnapshotter, MetricsRegistry, get_registry
from .slo import SeriesPoints, SloEngine, default_rules, match_series

__all__ = [
    "AGG_PROTOCOL",
    "MetricsAggregator",
    "TelemetryPusher",
    "acquire_pusher",
    "release_pusher",
    "flush_active_pushers",
    "parse_aggregator_url",
    "main",
]

logger = logging.getLogger("gentun_tpu.telemetry")

#: Wire protocol for ``/v1/push``.  Bump on any change to the payload
#: schema or merge semantics; skewed pushers are refused with HTTP 409.
AGG_PROTOCOL = 1

#: Request-body ceiling — matches the broker frame / fitness service
#: ceiling.  A full snapshot of every instrument in a busy master is
#: ~100 series; even histogram-heavy payloads sit far under this.
_MAX_BODY_BYTES = 4 * 1024 * 1024


def parse_aggregator_url(url: str) -> str:
    """Validate an ``--aggregator-url`` value; returns it normalized.

    Same contract as ``fitness_service.parse_cache_url`` (reimplemented
    here so the telemetry plane never imports the distributed layer):
    loud ``ValueError`` on anything but ``http(s)://host:port`` — a
    typo'd URL must not silently leave a fleet unmonitored.
    """
    parsed = urlparse(url)
    if parsed.scheme not in ("http", "https"):
        raise ValueError(
            f"aggregator url {url!r}: scheme must be http or https "
            f"(got {parsed.scheme or 'none'!r})")
    if not parsed.hostname:
        raise ValueError(f"aggregator url {url!r}: missing host")
    if parsed.port is None:
        raise ValueError(f"aggregator url {url!r}: missing port")
    if parsed.path not in ("", "/") or parsed.query or parsed.fragment:
        raise ValueError(
            f"aggregator url {url!r}: must be scheme://host:port with no "
            "path/query (endpoints are appended by the pusher)")
    return f"{parsed.scheme}://{parsed.hostname}:{parsed.port}"


# -- merged series state -----------------------------------------------------


class _Series:
    """One (instance, instrument) merged series with reset correction.

    ``base`` accumulates everything lost to counter resets: on a push
    whose cumulative value went *down* (process restart — monotone
    counters cannot decrease otherwise), the previous cumulative folds
    into ``base`` and the new value starts fresh, so ``effective = base +
    last`` stays monotone across restarts.  Gauges skip all of that.
    """

    __slots__ = ("tag", "name", "labels", "base", "last", "base_sum",
                 "last_sum", "buckets", "base_buckets", "ring")

    def __init__(self, tag: str, name: str, labels: Dict[str, str],
                 ring_len: int):
        self.tag = tag
        self.name = name
        self.labels = dict(labels)
        self.base = 0.0       # counters: reset carry; histograms: count carry
        self.last = 0.0       # counters: last raw value; histograms: count
        self.base_sum = 0.0   # histograms only
        self.last_sum = 0.0
        self.buckets: List[Tuple[Any, float]] = []   # last raw cum buckets
        self.base_buckets: List[float] = []          # reset carry per bucket
        # counters/gauges: (t, value); histograms: (t, count, sum) —
        # values reset-corrected so window deltas are plain subtraction.
        self.ring: deque = deque(maxlen=ring_len)

    @property
    def effective(self) -> float:
        return self.base + self.last

    @property
    def effective_sum(self) -> float:
        return self.base_sum + self.last_sum

    def effective_buckets(self) -> List[Tuple[Any, float]]:
        out = []
        for i, (b, c) in enumerate(self.buckets):
            carry = self.base_buckets[i] if i < len(self.base_buckets) else 0.0
            out.append((b, c + carry))
        return out

    def update(self, tag: str, t: float, value: float,
               hist_sum: float = 0.0,
               buckets: Optional[List] = None) -> bool:
        """Merge one pushed cumulative value; returns True on a reset."""
        reset = False
        if tag == "gauge":
            self.last = value
            self.ring.append((t, value))
            return False
        if value < self.last - 1e-9:  # monotone violated ⇒ process restart
            reset = True
            self.base += self.last
            if tag == "histogram":
                self.base_sum += self.last_sum
                prev = [c for _, c in self.buckets]
                if len(prev) == len(self.base_buckets):
                    self.base_buckets = [a + b for a, b
                                         in zip(self.base_buckets, prev)]
                else:
                    self.base_buckets = prev
                # The folded counts now live in base; the raw view must
                # restart at zero or effective_buckets double-counts.
                self.buckets = [(b, 0.0) for b, _ in self.buckets]
        self.last = value
        if tag == "histogram":
            self.last_sum = hist_sum
            if buckets is not None:
                cleaned = []
                for pair in buckets:
                    if isinstance(pair, (list, tuple)) and len(pair) == 2:
                        cleaned.append((pair[0], float(pair[1])))
                if len(cleaned) != len(self.base_buckets):
                    # First push, or the bucket layout changed across a
                    # restart: the carry no longer lines up — drop it.
                    self.base_buckets = [0.0] * len(cleaned)
                self.buckets = cleaned
            self.ring.append((t, self.effective, self.effective_sum))
        else:
            self.ring.append((t, self.effective))
        return reset


@dataclass
class _Instance:
    """Everything the aggregator knows about one pushing process."""

    instance: str
    role: str
    boot_id: str = ""
    seq: int = 0
    pushes: int = 0
    last_push: float = 0.0  # time.time() at receipt
    series: Dict[Tuple[str, str, Tuple], _Series] = field(default_factory=dict)


def _label_key(labels: Dict[str, Any]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


# -- HTTP plumbing -----------------------------------------------------------


class _Handler(BaseHTTPRequestHandler):
    """Request handler; ``self.server.aggregator`` is the MetricsAggregator."""

    server_version = "gentun-agg/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # noqa: D102 - silence stderr chatter
        pass

    def _send_json(self, code: int, obj: Any) -> None:
        body = json.dumps(obj, separators=(",", ":")).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_text(self, code: int, text: str,
                   ctype: str = "text/plain; version=0.0.4") -> None:
        body = text.encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _read_body(self) -> Optional[Any]:
        try:
            n = int(self.headers.get("Content-Length", "0"))
        except ValueError:
            n = -1
        if not 0 < n <= _MAX_BODY_BYTES:
            self._send_json(413, {"error": f"body length {n} out of range"})
            return None
        try:
            return json.loads(self.rfile.read(n).decode())
        except (ValueError, UnicodeDecodeError) as e:
            self._send_json(400, {"error": f"bad json: {e}"})
            return None

    def _check_versions(self, msg: Dict[str, Any]) -> bool:
        """The wire-level all-writers-upgrade-together guard (409 on skew)."""
        proto = msg.get("protocol")
        if proto != AGG_PROTOCOL:
            self._send_json(409, {
                "error": "version skew",
                "protocol": AGG_PROTOCOL,
                "client_protocol": proto,
            })
            return False
        return True

    def do_GET(self):  # noqa: N802 - http.server API
        raw_path = self.path
        path = raw_path.split("?", 1)[0].rstrip("/") or "/"
        agg = self.server.aggregator  # type: ignore[attr-defined]
        if path in ("/", "/healthz"):
            self._send_json(200, {"status": "ok", **agg.stats()})
        elif path == "/statusz":
            self._send_json(200, agg.statusz())
        elif path == "/metrics":
            self._send_text(200, agg.render_prometheus())
        elif path == "/alertz":
            self._send_json(200, agg.alertz())
        elif path == "/ringz":
            qs = parse_qs(urlparse(raw_path).query)
            self._send_json(200, agg.ringz(
                name=qs.get("name", ["*"])[0],
                instance=qs.get("instance", [""])[0] or None))
        else:
            self._send_json(404, {"error": f"no route {path}"})

    def do_POST(self):  # noqa: N802 - http.server API
        path = self.path.split("?", 1)[0].rstrip("/")
        agg = self.server.aggregator  # type: ignore[attr-defined]
        msg = self._read_body()
        if msg is None:
            return
        if not isinstance(msg, dict):
            self._send_json(400, {"error": "body must be an object"})
            return
        if not self._check_versions(msg):
            return
        if path == "/v1/push":
            ok, detail = agg.push(msg)
            if ok:
                self._send_json(200, {"ok": True, **detail})
            else:
                self._send_json(400, {"error": detail.get("error", "bad push")})
        else:
            self._send_json(404, {"error": f"no route {path}"})


# -- the aggregator ----------------------------------------------------------


class MetricsAggregator:
    """Push-gateway + rings + SLO judgment behind a ThreadingHTTPServer.

    All state — the instance table, every merged series, every ring —
    lives under one lock, is bounded (``max_instances`` LRU-evicted by
    last push, ``max_series_per_instance`` drop-with-counter,
    ``ring_len`` points per series), and is served on:

    - ``GET /metrics``  — merged fleet exposition, every series labelled
      ``instance=…,role=…``, counters reset-corrected (monotone).
    - ``GET /statusz``  — instance table, fleet counter/gauge rollups,
      the build_info version-skew table, alert summary.
    - ``GET /alertz``   — the SLO engine's full state + history.
    - ``GET /ringz``    — raw ring points for sparklines
      (``?name=pattern&instance=…``).
    - ``POST /v1/push`` — snapshot ingestion (``AGG_PROTOCOL``).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 ring_len: int = 128, max_instances: int = 64,
                 max_series_per_instance: int = 2048,
                 instance_ttl: float = 30.0,
                 slo_rules: Optional[List] = None,
                 slo_interval: float = 2.0):
        if ring_len <= 1:
            raise ValueError(f"ring_len must be > 1, got {ring_len}")
        if max_instances <= 0:
            raise ValueError(
                f"max_instances must be positive, got {max_instances}")
        self.ring_len = int(ring_len)
        self.max_instances = int(max_instances)
        self.max_series_per_instance = int(max_series_per_instance)
        self.instance_ttl = float(instance_ttl)
        self.slo_interval = float(slo_interval)
        self._lock = threading.Lock()
        self._instances: "OrderedDict[str, _Instance]" = OrderedDict()
        self._pushes = 0
        self._pushes_dropped = 0        # late/out-of-order
        self._resets = 0                # counter resets folded into base
        self._series_dropped = 0        # per-instance series cap overflow
        self._evicted_instances = 0
        self._started = time.time()
        self._slo = SloEngine(slo_rules if slo_rules is not None
                              else default_rules())
        self._alerts_fired = 0
        self._alerts_cleared = 0
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.aggregator = self  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None
        self._slo_stop = threading.Event()
        self._slo_thread: Optional[threading.Thread] = None

    # -- address -----------------------------------------------------------

    @property
    def address(self) -> Tuple[str, int]:
        return self._httpd.server_address[:2]

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "MetricsAggregator":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.25},
            name="metrics-aggregator", daemon=True)
        self._thread.start()
        self._slo_stop.clear()
        self._slo_thread = threading.Thread(
            target=self._slo_loop, name="slo-engine", daemon=True)
        self._slo_thread.start()
        logger.info("metrics aggregator serving on %s (ring %d, "
                    "%d rules)", self.url, self.ring_len,
                    len(self._slo.rules))
        return self

    def stop(self) -> None:
        self._slo_stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if self._slo_thread is not None:
            self._slo_thread.join(timeout=5.0)
            self._slo_thread = None

    def __enter__(self) -> "MetricsAggregator":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- ingestion ---------------------------------------------------------

    def push(self, msg: Dict[str, Any]) -> Tuple[bool, Dict[str, Any]]:
        """Merge one snapshot; (ok, detail).  Usable in-process, no HTTP."""
        instance = msg.get("instance")
        role = msg.get("role", "")
        boot_id = msg.get("boot_id", "")
        seq = msg.get("seq")
        metrics = msg.get("metrics")
        if not isinstance(instance, str) or not instance:
            return False, {"error": "instance must be a non-empty string"}
        if not isinstance(seq, int):
            return False, {"error": "seq must be an int"}
        if not isinstance(metrics, dict):
            return False, {"error": "metrics must be an object"}
        now = time.time()
        with self._lock:
            inst = self._instances.get(instance)
            if inst is None:
                inst = _Instance(instance=instance, role=str(role))
                self._instances[instance] = inst
                while len(self._instances) > self.max_instances:
                    victim, _ = self._instances.popitem(last=False)
                    self._evicted_instances += 1
                    logger.warning("aggregator: evicted instance %s "
                                   "(max_instances=%d)", victim,
                                   self.max_instances)
            if boot_id and boot_id != inst.boot_id:
                # Restarted pusher: every cumulative series it had rolls
                # into base so the restart reads as continuation, and the
                # sequence restarts with the new life.
                if inst.boot_id:
                    for s in inst.series.values():
                        if s.tag != "gauge" and s.last:
                            s.update(s.tag, now, 0.0, 0.0, None)
                            self._resets += 1
                inst.boot_id = boot_id
                inst.seq = 0
            elif seq <= inst.seq:
                # Late or duplicated snapshot from the same life: the
                # newer state already merged; dropping keeps counters
                # from travelling backwards.
                self._pushes_dropped += 1
                inst.last_push = now
                return True, {"dropped": True, "seq": inst.seq}
            inst.seq = seq
            inst.role = str(role) or inst.role
            inst.pushes += 1
            inst.last_push = now
            self._instances.move_to_end(instance)
            self._pushes += 1
            n = 0
            for tag, key_name in (("counter", "counters"),
                                  ("gauge", "gauges"),
                                  ("histogram", "histograms")):
                for rec in metrics.get(key_name, []) or []:
                    if not isinstance(rec, dict):
                        continue
                    name = rec.get("name")
                    labels = rec.get("labels") or {}
                    if not isinstance(name, str) or not isinstance(labels, dict):
                        continue
                    key = (tag, name, _label_key(labels))
                    s = inst.series.get(key)
                    if s is None:
                        if len(inst.series) >= self.max_series_per_instance:
                            self._series_dropped += 1
                            continue
                        s = inst.series[key] = _Series(
                            tag, name, labels, self.ring_len)
                    try:
                        if tag == "histogram":
                            reset = s.update(
                                tag, now, float(rec.get("count", 0)),
                                float(rec.get("sum", 0.0)),
                                rec.get("buckets"))
                        else:
                            reset = s.update(tag, now,
                                             float(rec.get("value", 0.0)))
                    except (TypeError, ValueError):
                        continue
                    if reset:
                        self._resets += 1
                    n += 1
            return True, {"n": n, "seq": seq}

    # -- read side ---------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "protocol": AGG_PROTOCOL,
                "uptime_s": round(time.time() - self._started, 3),
                "instances": len(self._instances),
                "series": sum(len(i.series) for i in self._instances.values()),
                "pushes": self._pushes,
                "pushes_dropped": self._pushes_dropped,
                "resets_detected": self._resets,
                "series_dropped": self._series_dropped,
                "evicted_instances": self._evicted_instances,
                "alerts_active": len(self._slo.active()),
                "alerts_fired": self._alerts_fired,
                "alerts_cleared": self._alerts_cleared,
            }

    def _fleet_rollup(self) -> Dict[str, Dict[str, float]]:
        """Reset-corrected fleet sums by metric name (lock held)."""
        counters: Dict[str, float] = {}
        gauges: Dict[str, float] = {}
        for inst in self._instances.values():
            for s in inst.series.values():
                if s.tag == "counter":
                    counters[s.name] = counters.get(s.name, 0.0) + s.effective
                elif s.tag == "gauge" and s.name != "build_info":
                    gauges[s.name] = gauges.get(s.name, 0.0) + s.last
        return {"counters": counters, "gauges": gauges}

    def _version_table(self) -> Dict[str, Any]:
        """Group instances by their pushed build_info labels (lock held)."""
        builds: Dict[Tuple, List[str]] = {}
        for inst in self._instances.values():
            sig: Optional[Tuple] = None
            for s in inst.series.values():
                if s.tag == "gauge" and s.name == "build_info":
                    sig = tuple(sorted(s.labels.items()))
                    break
            builds.setdefault(sig or (("version", "unreported"),),
                              []).append(inst.instance)
        return {
            "skew": len(builds) > 1,
            "builds": [{**dict(sig), "instances": sorted(members)}
                       for sig, members in sorted(builds.items())],
        }

    def statusz(self) -> Dict[str, Any]:
        now = time.time()
        with self._lock:
            instances = [{
                "instance": i.instance,
                "role": i.role,
                "boot_id": i.boot_id,
                "seq": i.seq,
                "pushes": i.pushes,
                "age_s": round(now - i.last_push, 3) if i.last_push else None,
                "stale": bool(i.last_push
                              and now - i.last_push > self.instance_ttl),
                "n_series": len(i.series),
            } for i in self._instances.values()]
            fleet = self._fleet_rollup()
            skew = self._version_table()
        return {
            "status": "ok",
            **self.stats(),
            "instance_table": instances,
            "fleet": fleet,
            "version_skew": skew,
            "alerts": {"active": self._slo.active()},
        }

    def alertz(self) -> Dict[str, Any]:
        return self._slo.snapshot()

    def ringz(self, name: str = "*", instance: Optional[str] = None,
              max_series: int = 200) -> Dict[str, Any]:
        """Raw ring points for dashboards (histograms as _sum/_count)."""
        out: List[Dict[str, Any]] = []
        for sp in self._slo_view(name, instance=instance):
            out.append({"name": sp.name, "labels": sp.labels,
                        "points": [[round(t, 3), v] for t, v in sp.points]})
            if len(out) >= max_series:
                break
        return {"series": out, "ring_len": self.ring_len}

    def render_prometheus(self) -> str:
        """Merged fleet exposition (same grammar subset as the registry)."""

        def esc(v: str) -> str:
            return v.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")

        def fmt_labels(labels: Dict[str, str], extra: str = "") -> str:
            parts = [f'{k}="{esc(v)}"' for k, v in sorted(labels.items())]
            if extra:
                parts.append(extra)
            return "{" + ",".join(parts) + "}" if parts else ""

        lines: List[str] = []
        typed: set = set()

        def emit(tag: str, name: str, labels: Dict[str, str],
                 value: float) -> None:
            if (tag, name) not in typed:
                typed.add((tag, name))
                lines.append(f"# TYPE {name} {tag}")
            lines.append(f"{name}{fmt_labels(labels)} {value:g}")

        with self._lock:
            stats = {
                "aggregator_instances": float(len(self._instances)),
                "aggregator_series": float(sum(
                    len(i.series) for i in self._instances.values())),
            }
            rows = []
            for inst in self._instances.values():
                meta = {"instance": inst.instance, "role": inst.role}
                for s in sorted(inst.series.values(),
                                key=lambda s: (s.name, s.tag)):
                    rows.append((s.tag, s.name, {**s.labels, **meta},
                                 s.effective if s.tag == "counter" else s.last,
                                 s.effective_sum,
                                 s.effective_buckets() if s.tag == "histogram"
                                 else None,
                                 s.effective))
            pushes = float(self._pushes)
            dropped = float(self._pushes_dropped)
            resets = float(self._resets)
        for tag, name, labels, value, hsum, buckets, hcount in sorted(
                rows, key=lambda r: (r[1], r[0], sorted(r[2].items()))):
            if tag == "histogram":
                if ("histogram", name) not in typed:
                    typed.add(("histogram", name))
                    lines.append(f"# TYPE {name} histogram")
                for b, c in buckets or []:
                    le = b if isinstance(b, str) else f"{float(b):g}"
                    le_label = 'le="%s"' % le
                    lines.append(
                        f"{name}_bucket{fmt_labels(labels, le_label)} {c:g}")
                lines.append(f"{name}_sum{fmt_labels(labels)} {hsum:g}")
                lines.append(f"{name}_count{fmt_labels(labels)} {hcount:g}")
            else:
                emit(tag, name, labels, value)
        emit("counter", "aggregator_pushes_total", {}, pushes)
        emit("counter", "aggregator_pushes_dropped_total", {}, dropped)
        emit("counter", "aggregator_resets_detected_total", {}, resets)
        for gname, gval in sorted(stats.items()):
            emit("gauge", gname, {}, gval)
        return "\n".join(lines) + ("\n" if lines else "")

    # -- SLO plumbing ------------------------------------------------------

    def _slo_view(self, pattern: str,
                  instance: Optional[str] = None) -> List[SeriesPoints]:
        """Ring adapter for the SLO engine (histograms as _sum/_count)."""
        out: List[SeriesPoints] = []
        with self._lock:
            for inst in self._instances.values():
                if instance is not None and inst.instance != instance:
                    continue
                meta = {"instance": inst.instance, "role": inst.role}
                for s in inst.series.values():
                    labels = {**s.labels, **meta}
                    if s.tag == "histogram":
                        if match_series(pattern, s.name + "_sum"):
                            out.append(SeriesPoints(
                                s.name + "_sum", labels,
                                [(t, hs) for t, _, hs in s.ring]))
                        if match_series(pattern, s.name + "_count"):
                            out.append(SeriesPoints(
                                s.name + "_count", labels,
                                [(t, c) for t, c, _ in s.ring]))
                    elif match_series(pattern, s.name):
                        out.append(SeriesPoints(
                            s.name, labels, [(t, v) for t, v in s.ring]))
        return out

    def evaluate_slos(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """One SLO pass; fires/clears alerts, returns the transitions.

        Called from the background loop; exposed for tests and for
        deterministic drives from the study harness.
        """
        transitions = self._slo.evaluate(self._slo_view, now=now)
        for tr in transitions:
            if tr["event"] == "fire":
                self._alerts_fired += 1
                logger.warning(
                    "SLO alert FIRING: %s [%s] subject=%s value=%.4g "
                    "(%s %s %.4g) — %s", tr["rule"], tr["severity"],
                    tr["subject"], tr["value"], tr["rule"], tr["op"],
                    tr["threshold"], tr["description"])
            else:
                self._alerts_cleared += 1
                logger.info("SLO alert cleared: %s subject=%s",
                            tr["rule"], tr["subject"])
            if _tele.enabled():
                _tele.emit_record({
                    "type": "alert",
                    "event": tr["event"],
                    "rule": tr["rule"],
                    "severity": tr["severity"],
                    "subject": tr["subject"],
                    "value": tr["value"],
                    "threshold": tr["threshold"],
                    "transition_seq": tr["transition_seq"],
                    "firing_since": tr["firing_since"],
                    "t": tr["t"],
                })
        return transitions

    def _slo_loop(self) -> None:
        while not self._slo_stop.wait(self.slo_interval):
            try:
                self.evaluate_slos()
            except Exception:  # noqa: BLE001 - judgment must not kill serving
                logger.exception("SLO evaluation pass failed")


# -- the pusher --------------------------------------------------------------


class TelemetryPusher:
    """Background snapshot pusher with the ONE-degraded-event contract.

    A daemon thread ships ``DeltaSnapshotter.collect()`` every
    ``interval`` seconds.  Failures mark the aggregator down for
    ``cooldown`` seconds (no socket is touched during the window), emit
    exactly ONE ``aggregator_degraded`` telemetry event per up→down
    transition, and schedule a *full* snapshot for the next successful
    push — the aggregator may have restarted empty, and deltas alone
    would leave it blind to quiet series.  Nothing here ever raises into
    the caller and nothing here touches the search RNG.
    """

    def __init__(self, url: str, role: str, instance: Optional[str] = None,
                 interval: float = 2.0, timeout: float = 2.0,
                 cooldown: float = 5.0, full_every: int = 15,
                 registry: Optional[MetricsRegistry] = None):
        if interval <= 0:
            raise ValueError(f"interval must be positive, got {interval}")
        if full_every < 1:
            raise ValueError(f"full_every must be >= 1, got {full_every}")
        self.url = parse_aggregator_url(url)
        self.instance = instance or f"{socket.gethostname()}:{os.getpid()}"
        self.interval = float(interval)
        self.timeout = float(timeout)
        self.cooldown = float(cooldown)
        self._registry = registry if registry is not None else get_registry()
        self._delta = DeltaSnapshotter(self._registry)
        self._roles = [role]
        self._boot_id = uuid4().hex
        self._seq = 0
        self._full_next = True
        self.full_every = int(full_every)
        self._since_full = 0
        self._down_until = 0.0
        self._degraded = False
        self._degraded_total = 0
        self._pushes_ok = 0
        self._pushes_failed = 0
        self._lock = threading.Lock()
        self._push_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._refs = 1  # managed by acquire_pusher/release_pusher

    # -- roles -------------------------------------------------------------

    @property
    def role(self) -> str:
        with self._lock:
            return "+".join(self._roles)

    def add_role(self, role: str) -> None:
        with self._lock:
            if role not in self._roles:
                self._roles.append(role)

    # -- availability ------------------------------------------------------

    def available(self) -> bool:
        with self._lock:
            return time.monotonic() >= self._down_until

    @property
    def degraded(self) -> bool:
        with self._lock:
            return self._degraded

    def _mark_down(self, err: Exception) -> None:
        with self._lock:
            self._down_until = time.monotonic() + self.cooldown
            first = not self._degraded
            self._degraded = True
            self._degraded_total += 1
            self._full_next = True  # it may come back empty: resend all
        if first:
            logger.warning(
                "metrics aggregator %s unreachable (%s); pushing pauses, "
                "retrying every %.1fs — the search is not affected",
                self.url, err, self.cooldown)
            _tele.record_event("aggregator_degraded", {
                "url": self.url, "instance": self.instance,
                "error": str(err)[:200],
            })
            if _tele.enabled():
                self._registry.counter("aggregator_degraded_total").inc()

    def _mark_up(self) -> None:
        with self._lock:
            was = self._degraded
            self._degraded = False
        if was:
            logger.info("metrics aggregator %s reachable again", self.url)

    # -- push --------------------------------------------------------------

    def _build_payload(self) -> Dict[str, Any]:
        """One wire snapshot; memoized deltas unless a full resend is due.

        Every ``full_every``-th push resends the complete snapshot even
        when nothing changed: quiet series keep receiving ring points on
        the aggregator (a firing SLO over a series that simply stopped
        moving must be able to observe the flatline and self-clear), and
        an aggregator that silently lost state re-learns it within one
        heartbeat cycle.
        """
        with self._lock:
            self._since_full += 1
            full = self._full_next or self._since_full >= self.full_every
            if full:
                self._since_full = 0
            self._full_next = False
            self._seq += 1
            seq = self._seq
            role = "+".join(self._roles)
        return {
            "v": 1,
            "protocol": AGG_PROTOCOL,
            "instance": self.instance,
            "role": role,
            "boot_id": self._boot_id,
            "seq": seq,
            "t": time.time(),
            "metrics": self._delta.collect(full=full),
        }

    def push_once(self) -> bool:
        """One push attempt now (degradation-safe); True on success."""
        if not self.available():
            return False
        with self._push_lock:
            payload = self._build_payload()
            req = urllib.request.Request(
                self.url + "/v1/push",
                data=json.dumps(payload, separators=(",", ":")).encode(),
                headers={"Content-Type": "application/json"},
                method="POST")
            try:
                with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                    json.loads(resp.read().decode())
            except Exception as e:  # noqa: BLE001 - degradation boundary
                # The delta state already advanced; resend everything on
                # recovery so the lost snapshot cannot leave holes.
                self._mark_down(e)
                with self._lock:
                    self._pushes_failed += 1
                return False
            self._mark_up()
            with self._lock:
                self._pushes_ok += 1
            return True

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self.push_once()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "TelemetryPusher":
        set_build_info(self._registry)
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="telemetry-pusher", daemon=True)
            self._thread.start()
        return self

    def flush(self, timeout: float = 2.0) -> bool:
        """Synchronous best-effort push (run boundaries, tests)."""
        old = self.timeout
        self.timeout = min(old, timeout) if timeout else old
        try:
            return self.push_once()
        finally:
            self.timeout = old

    def stop(self, flush: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if flush:
            self.push_once()

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "url": self.url,
                "instance": self.instance,
                "role": "+".join(self._roles),
                "seq": self._seq,
                "degraded": self._degraded,
                "degraded_total": self._degraded_total,
                "pushes_ok": self._pushes_ok,
                "pushes_failed": self._pushes_failed,
            }


# -- per-process pusher registry ---------------------------------------------

_PUSHER_LOCK = threading.Lock()
_ACTIVE_PUSHERS: Dict[str, TelemetryPusher] = {}


def acquire_pusher(url: str, role: str, instance: Optional[str] = None,
                   interval: Optional[float] = None,
                   **kw: Any) -> TelemetryPusher:
    """Get-or-create the process's pusher for ``url`` (refcounted).

    The master and its in-process broker share one metrics registry;
    if each started its own pusher the fleet rollup would double-count
    every counter in the process.  Sharing one pusher per URL (roles
    merging into the ``role`` label) is what keeps instance == process.

    Cadence defaults come from the environment when not passed — the
    wiring call sites (broker/master/worker) never hardcode a rhythm, so
    seconds-long drills and studies can compress the push/heartbeat
    cadence fleet-wide without touching the dispatch plane:

    - ``GENTUN_TPU_AGG_PUSH_INTERVAL`` (seconds, default 2.0)
    - ``GENTUN_TPU_AGG_FULL_EVERY`` (pushes per heartbeat full resend,
      default 15)
    """
    if interval is None:
        interval = float(os.environ.get("GENTUN_TPU_AGG_PUSH_INTERVAL", "2.0"))
    if "full_every" not in kw and "GENTUN_TPU_AGG_FULL_EVERY" in os.environ:
        kw["full_every"] = int(os.environ["GENTUN_TPU_AGG_FULL_EVERY"])
    key = parse_aggregator_url(url)
    with _PUSHER_LOCK:
        p = _ACTIVE_PUSHERS.get(key)
        if p is not None:
            p._refs += 1
            p.add_role(role)
            return p
        p = TelemetryPusher(key, role, instance=instance,
                            interval=interval, **kw)
        _ACTIVE_PUSHERS[key] = p
        p.start()
        return p


def release_pusher(pusher: TelemetryPusher, flush: bool = True) -> None:
    """Drop one reference; the last release stops (and flushes) it."""
    with _PUSHER_LOCK:
        pusher._refs -= 1
        if pusher._refs > 0:
            return
        _ACTIVE_PUSHERS.pop(pusher.url, None)
    pusher.stop(flush=flush)


def flush_active_pushers(timeout: float = 2.0) -> None:
    """Best-effort immediate push from every live pusher (run boundaries)."""
    with _PUSHER_LOCK:
        pushers = list(_ACTIVE_PUSHERS.values())
    for p in pushers:
        p.flush(timeout=timeout)


# -- standalone entrypoint ---------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m gentun_tpu.telemetry.aggregator`` — run a fleet aggregator."""
    ap = argparse.ArgumentParser(
        prog="gentun-aggregator",
        description="Fleet metrics aggregation + SLO/alerting plane")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=9100)
    ap.add_argument("--ring-len", type=int, default=128,
                    help="time-series points kept per series")
    ap.add_argument("--max-instances", type=int, default=64)
    ap.add_argument("--instance-ttl", type=float, default=30.0,
                    help="seconds without a push before an instance "
                         "reads as stale on /statusz")
    ap.add_argument("--slo-interval", type=float, default=2.0)
    ap.add_argument("--slo-scale", type=float, default=1.0,
                    help="shrink every SLO window/hold by this factor "
                         "(drills; production keeps 1.0)")
    args = ap.parse_args(argv)
    try:
        rules = default_rules(scale=args.slo_scale)
        agg = MetricsAggregator(
            host=args.host, port=args.port, ring_len=args.ring_len,
            max_instances=args.max_instances,
            instance_ttl=args.instance_ttl,
            slo_rules=rules, slo_interval=args.slo_interval)
    except ValueError as e:
        raise SystemExit(f"aggregator: {e}")
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(levelname)s %(message)s")
    agg.start()
    print(f"aggregator serving on {agg.url} "
          f"(/metrics /statusz /alertz /ringz)")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        agg.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
