"""gentun_tpu observability plane: metrics registry, spans, run artifacts.

Three small zero-dependency modules (see ``docs/OBSERVABILITY.md``):

- :mod:`.registry` — process-local counters/gauges/log-bucket histograms
  with Prometheus-text and JSONL renderers.
- :mod:`.spans` — monotonic-clock spans with trace_id/parent_id context
  that propagates across the distributed wire; no-op singleton fast path
  when disabled (the default).
- :mod:`.export` — ``RunTelemetry``: streams every span/event to a
  per-run ``telemetry.jsonl`` and summarises exact p50/p95/p99 per span
  kind plus counter totals, merged across worker reports.

Plus the live ops plane (OBSERVABILITY.md "Live ops plane"):

- :mod:`.health` — heartbeat registry, status providers, and the
  :class:`~.health.StallWatchdog` straggler detector behind ``/healthz``.
- :mod:`.flight` — always-on bounded flight recorder (crash black box).
- :mod:`.ops_server` — ``/metrics`` + ``/healthz`` + ``/statusz`` +
  ``/debugz/flight`` on a stdlib HTTP server in a daemon thread.

The fleet aggregation plane (OBSERVABILITY.md "Fleet aggregation & SLOs"):

- :mod:`.aggregator` — push-gateway :class:`~.aggregator.MetricsAggregator`
  (merged fleet ``/metrics``/``/statusz``/``/alertz``/``/ringz``) and the
  degradation-safe :class:`~.aggregator.TelemetryPusher` every process
  wires via ``aggregator_url=`` / ``--aggregator-url``.
- :mod:`.slo` — the declarative burn-rate rule table and alert state
  machine the aggregator evaluates over its time-series rings.
- :mod:`.buildinfo` — the ``build_info`` version-identity gauge behind
  the fleet version-skew table.

And the search-forensics plane (OBSERVABILITY.md "Search forensics"):

- :mod:`.lineage` — per-genome lineage ledger (born/dispatched/completed/
  promoted/evicted/…) and the chip-hour :class:`~.lineage.CostLedger`
  attributing device-seconds to ``(session, genome, rung, worker)``.
- :mod:`.traceviz` — offline converter from a run's ``telemetry.jsonl``
  to Chrome ``trace_event`` JSON loadable in Perfetto, with flow events
  linking dispatch→evaluate→result across processes.

Quick start::

    from gentun_tpu import telemetry
    with telemetry.RunTelemetry("out/telemetry.jsonl"):
        ga.run(generations)
"""

from .aggregator import (
    AGG_PROTOCOL,
    MetricsAggregator,
    TelemetryPusher,
    acquire_pusher,
    flush_active_pushers,
    parse_aggregator_url,
    release_pusher,
)
from .buildinfo import build_info_labels, set_build_info
from .export import RunTelemetry, active_run, end_run, start_run
from .flight import FlightRecorder
from .health import StallWatchdog
from .lineage import CostLedger, genome_key, get_ledger
from .ops_server import OpsServer, active_ops_server, start_ops_server, stop_ops_server
from .registry import (
    DEFAULT_BUCKETS,
    Counter,
    DeltaSnapshotter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
)
from .slo import SeriesPoints, SloEngine, SloRule, default_rules
from .spans import (
    attach,
    capture,
    current_context,
    disable,
    enable,
    enabled,
    ingest,
    record_event,
    record_span,
    span,
)

__all__ = [
    "RunTelemetry",
    "start_run",
    "active_run",
    "end_run",
    "MetricsRegistry",
    "Counter",
    "Gauge",
    "Histogram",
    "get_registry",
    "DEFAULT_BUCKETS",
    "span",
    "record_span",
    "record_event",
    "enabled",
    "enable",
    "disable",
    "current_context",
    "attach",
    "capture",
    "ingest",
    "StallWatchdog",
    "FlightRecorder",
    "CostLedger",
    "genome_key",
    "get_ledger",
    "OpsServer",
    "start_ops_server",
    "stop_ops_server",
    "active_ops_server",
    "AGG_PROTOCOL",
    "MetricsAggregator",
    "TelemetryPusher",
    "acquire_pusher",
    "release_pusher",
    "flush_active_pushers",
    "parse_aggregator_url",
    "DeltaSnapshotter",
    "SloEngine",
    "SloRule",
    "SeriesPoints",
    "default_rules",
    "build_info_labels",
    "set_build_info",
]
