"""`build_info`: the version-skew tripwire gauge.

Every process that serves ``/metrics`` (ops server) or pushes to the
fleet aggregator stamps a single ``build_info`` gauge whose *labels*
carry the identity that matters operationally: package version, the
checkpoint schema, the fitness/compile wire protocols, and the jax/jaxlib
versions.  The value is always 1 — Prometheus convention: information
rides in labels, and ``sum by (version) (build_info)`` counts processes
per build.  The aggregator folds the pushed gauges into the fleet
version-skew table on its ``/statusz``, which is where a half-upgraded
fleet becomes visible *before* the 409s start.

Imports are lazy and fail-soft: the telemetry plane must stay importable
on minimal installs (no jax on the GA outer loop — registry.py's
zero-dependency constraint), and a missing constant reports ``"unknown"``
rather than breaking metrics export.
"""

from __future__ import annotations

from typing import Dict, Optional

from .registry import MetricsRegistry, get_registry

__all__ = ["build_info_labels", "set_build_info"]

_CACHED: Optional[Dict[str, str]] = None


def build_info_labels() -> Dict[str, str]:
    """The identity labels, computed once per process."""
    global _CACHED
    if _CACHED is not None:
        return dict(_CACHED)
    labels: Dict[str, str] = {}

    def probe(key: str, fn) -> None:
        try:
            labels[key] = str(fn())
        except Exception:  # noqa: BLE001 - identity is best-effort
            labels[key] = "unknown"

    probe("version", lambda: __import__(
        "gentun_tpu").__version__)
    probe("checkpoint_schema", lambda: __import__(
        "gentun_tpu.utils.checkpoint", fromlist=["CHECKPOINT_SCHEMA"]
    ).CHECKPOINT_SCHEMA)
    probe("fitness_protocol", lambda: __import__(
        "gentun_tpu.utils.fitness_store", fromlist=["FITNESS_PROTOCOL"]
    ).FITNESS_PROTOCOL)
    probe("compile_protocol", lambda: __import__(
        "gentun_tpu.distributed.compile_service", fromlist=["COMPILE_PROTOCOL"]
    ).COMPILE_PROTOCOL)
    # jax is optional on purpose: workers on minimal installs and the GA
    # outer loop never import it, and build_info must not drag it in if
    # it is not already loaded elsewhere in the process.
    try:
        import importlib.metadata as _md
        labels["jax"] = _md.version("jax")
        labels["jaxlib"] = _md.version("jaxlib")
    except Exception:  # noqa: BLE001 - absent on minimal installs
        labels.setdefault("jax", "absent")
        labels.setdefault("jaxlib", "absent")
    _CACHED = labels
    return dict(labels)


def set_build_info(registry: Optional[MetricsRegistry] = None) -> None:
    """Stamp the ``build_info`` gauge (value 1) on ``registry``."""
    reg = registry if registry is not None else get_registry()
    reg.gauge("build_info", **build_info_labels()).set(1)
