"""Declarative SLO engine: burn-rate rules over aggregator time-series rings.

The aggregator (:mod:`gentun_tpu.telemetry.aggregator`) keeps a bounded
ring of ``(t, value)`` points per fleet series; this module judges those
rings against a declarative rule table and drives an alert state machine
with hysteresis on both edges:

- **burn-rate, not point-in-time** — every rule measures a *delta over a
  window* (``increase``), a *ratio of two deltas* (``ratio``), or
  *sustained growth of a gauge* (``gauge_growth``).  A single slow scrape
  or one straggly job can never page anyone.
- **flap damping** — a breach must hold for ``for_s`` before an alert
  fires, and the condition must stay healthy for ``clear_for_s`` before
  it resolves.  Between those edges the alert neither re-fires nor
  flickers; a fire→clear→fire cycle inside ``2 * clear_for_s`` is counted
  in ``flaps`` so ``/alertz`` exposes noisy rules.
- **self-clearing** — resolution is an explicit ``clear`` transition (and
  a ``{"type": "alert"}`` telemetry record), never silence.

The engine is deliberately ignorant of HTTP and of the aggregator's
storage: it sees only a *view* callable ``view(name) -> [SeriesPoints]``
so unit tests drive it with hand-built rings.
"""

from __future__ import annotations

import fnmatch
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "SloRule",
    "SloEngine",
    "SeriesPoints",
    "default_rules",
]

#: Ratio denominators smaller than this count as "no traffic" — the rule
#: abstains rather than dividing noise by noise.
_MIN_DENOM = 1e-9


@dataclass
class SeriesPoints:
    """One fleet series as the engine sees it: labels + time-ordered ring.

    ``points`` are ``(t_monotonic_like, value)`` with counter values
    already reset-corrected by the aggregator (monotone across process
    restarts), so window deltas here are plain subtraction.
    """

    name: str
    labels: Dict[str, str]
    points: List[Tuple[float, float]]

    def window_delta(self, now: float, window_s: float) -> Optional[float]:
        """``v(now) - v(now - window)``; None with <2 usable points."""
        if len(self.points) < 2:
            return None
        cutoff = now - window_s
        first = None
        for t, v in self.points:
            if t >= cutoff:
                first = (t, v)
                break
        if first is None or first == self.points[-1]:
            return None
        return self.points[-1][1] - first[1]

    def window_span(self, now: float, window_s: float) -> float:
        """Observed time span of the points inside the window."""
        cutoff = now - window_s
        ts = [t for t, _ in self.points if t >= cutoff]
        return (ts[-1] - ts[0]) if len(ts) >= 2 else 0.0


@dataclass(frozen=True)
class SloRule:
    """One declarative burn-rate rule.

    ``kind``:

    - ``increase`` — Δ(sum of series matching ``series``) over
      ``window_s`` compared against ``threshold`` with ``op``.
    - ``ratio`` — Δ(series) / Δ(denom) over the window; ``denom`` may be
      the pseudo-series ``"__time__"`` (the observed wall span, giving
      time-fraction ratios like worker-idle), or a pattern whose matched
      deltas are summed.  ``denom_includes_series=True`` adds the
      numerator delta into the denominator (hit / (hit + miss) rates).
    - ``gauge_growth`` — fires when the gauge both grew by at least
      ``threshold`` over the window *and* is still at its window peak
      (backlog that is draining never alerts).

    ``series`` supports ``fnmatch`` wildcards (``*_degraded_total``).
    ``subject`` groups evaluation: ``"instance"`` judges each pushing
    process separately (one alert per sick worker), ``"fleet"`` sums
    everything first.  ``role`` restricts which instances participate.
    """

    name: str
    kind: str
    series: str
    threshold: float
    op: str = ">"
    denom: str = ""
    denom_includes_series: bool = False
    window_s: float = 60.0
    for_s: float = 10.0
    clear_for_s: float = 20.0
    subject: str = "fleet"  # or "instance"
    role: str = ""          # restrict to instances with this role label
    severity: str = "warn"  # or "page"
    description: str = ""

    def __post_init__(self) -> None:
        if self.kind not in ("increase", "ratio", "gauge_growth"):
            raise ValueError(f"rule {self.name}: unknown kind {self.kind!r}")
        if self.op not in (">", "<", ">=", "<="):
            raise ValueError(f"rule {self.name}: unknown op {self.op!r}")
        if self.kind == "ratio" and not self.denom:
            raise ValueError(f"rule {self.name}: ratio needs a denom")
        if self.subject not in ("fleet", "instance"):
            raise ValueError(f"rule {self.name}: subject must be "
                             f"fleet|instance, got {self.subject!r}")
        if self.window_s <= 0:
            raise ValueError(f"rule {self.name}: window_s must be positive")

    def compare(self, value: float) -> bool:
        if self.op == ">":
            return value > self.threshold
        if self.op == "<":
            return value < self.threshold
        if self.op == ">=":
            return value >= self.threshold
        return value <= self.threshold


def default_rules(scale: float = 1.0) -> List[SloRule]:
    """The stock fleet rule table.

    ``scale`` shrinks every window/hold uniformly — production keeps 1.0,
    studies and chaos drills run seconds-long searches and pass ~0.1 so
    the same rules (same thresholds, same shapes) judge a compressed
    timeline.  Thresholds are never scaled: a 60% idle fleet is sick at
    any timescale.
    """
    s = float(scale)
    if s <= 0:
        raise ValueError(f"scale must be positive, got {scale}")
    return [
        SloRule(
            name="worker_idle_ratio", kind="ratio",
            series="worker_idle_s_sum", denom="__time__",
            threshold=0.5, op=">",
            window_s=60.0 * s, for_s=10.0 * s, clear_for_s=20.0 * s,
            subject="instance", role="worker", severity="page",
            description="worker spent >50% of the window waiting for "
                        "jobs — dispatch starvation or a stalled master",
        ),
        SloRule(
            name="fitness_cache_hit_rate", kind="ratio",
            series="fitness_service_hits_total",
            denom="fitness_service_misses_total",
            denom_includes_series=True,
            threshold=0.05, op="<",
            window_s=120.0 * s, for_s=30.0 * s, clear_for_s=60.0 * s,
            subject="fleet", severity="warn",
            description="fleet fitness-cache hit rate collapsed — cache "
                        "restarted, version skew, or key churn",
        ),
        SloRule(
            name="compile_cache_hit_rate", kind="ratio",
            series="compile_cache_hits_total",
            denom="compile_cache_misses_total",
            denom_includes_series=True,
            threshold=0.05, op="<",
            window_s=120.0 * s, for_s=30.0 * s, clear_for_s=60.0 * s,
            subject="fleet", severity="warn",
            description="fleet compile-cache hit rate collapsed — every "
                        "worker is paying full XLA compiles",
        ),
        SloRule(
            name="straggler_rate", kind="increase",
            series="stragglers_detected_total",
            threshold=0.0, op=">",
            window_s=60.0 * s, for_s=5.0 * s, clear_for_s=30.0 * s,
            subject="fleet", severity="warn",
            description="straggler watchdog fired inside the window",
        ),
        SloRule(
            name="degraded_dependency", kind="increase",
            series="*_degraded_total",
            threshold=0.0, op=">",
            window_s=60.0 * s, for_s=0.0, clear_for_s=30.0 * s,
            subject="instance", severity="warn",
            description="a process marked a dependency degraded "
                        "(fitness/compile cache, surrogate, aggregator)",
        ),
        SloRule(
            name="admission_rejection_burn", kind="increase",
            series="admission_rejected_total",
            threshold=0.0, op=">",
            window_s=60.0 * s, for_s=5.0 * s, clear_for_s=20.0 * s,
            subject="fleet", severity="warn",
            description="broker admission control rejected session_open/"
                        "submit inside the window — fleet saturated or a "
                        "tenant over its token-bucket rate (ISSUE 16)",
        ),
        SloRule(
            name="queue_depth_growth", kind="gauge_growth",
            series="session_queue_depth",
            threshold=8.0, op=">",
            window_s=60.0 * s, for_s=10.0 * s, clear_for_s=20.0 * s,
            subject="fleet", severity="page",
            description="session queue depth grew monotonically across "
                        "the window — submission outpacing the fleet",
        ),
        # -- canary plane (telemetry/canary.py): black-box probes of the
        # REAL serving path.  Error burn says "users can't get work
        # through"; latency says "they can, slowly"; correctness is the
        # zero-tolerance page — a golden genome came back with a fitness
        # that is not bit-equal to its sealed value, i.e. the fleet is
        # returning wrong answers and every live search is suspect.
        SloRule(
            name="canary_error_burn", kind="increase",
            series="canary_errors_total",
            threshold=0.0, op=">",
            window_s=60.0 * s, for_s=5.0 * s, clear_for_s=30.0 * s,
            subject="fleet", severity="warn",
            description="canary probes failed inside the window (open/"
                        "submit/result/verify stage) — the serving path "
                        "is broken the way a tenant would see it",
        ),
        SloRule(
            name="canary_latency", kind="ratio",
            series="canary_e2e_seconds_sum",
            denom="canary_e2e_seconds_count",
            threshold=30.0, op=">",
            window_s=120.0 * s, for_s=10.0 * s, clear_for_s=60.0 * s,
            subject="fleet", severity="warn",
            description="mean canary end-to-end probe latency exceeded "
                        "30 s — queueing or evaluation is degraded for "
                        "everyone, not just the probe",
        ),
        SloRule(
            name="canary_correctness", kind="increase",
            series="canary_fitness_drift_total",
            threshold=0.0, op=">",
            window_s=60.0 * s, for_s=0.0, clear_for_s=60.0 * s,
            subject="fleet", severity="page",
            description="a golden genome's fitness was NOT bit-equal to "
                        "its sealed value — the fleet is lying; quarantine "
                        "results since the last clean probe",
        ),
    ]


# -- alert state machine -----------------------------------------------------

_INACTIVE, _PENDING, _FIRING, _CLEARING = "inactive", "pending", "firing", "clearing"


@dataclass
class _AlertState:
    rule: SloRule
    subject: str
    state: str = _INACTIVE
    value: float = 0.0
    pending_since: float = 0.0
    fired_at: float = 0.0
    healthy_since: float = 0.0
    cleared_at: float = 0.0
    fires: int = 0
    flaps: int = 0
    last_transition: float = 0.0
    #: Engine-global monotonic counter stamped at every fire/clear edge.
    #: A poller that caches the last seq it saw detects a fire→clear→fire
    #: cycle even when both edges land between two polls — the seq moved
    #: by 2, where every timestamp-based scheme races the poll interval.
    transition_seq: int = 0
    #: Wall time of the CURRENT firing episode (0.0 while not firing).
    #: ``fired_at`` is "most recent fire ever" and survives the clear for
    #: flap accounting; ``firing_since`` is the edge-triggered view.
    firing_since: float = 0.0

    def public(self) -> Dict[str, Any]:
        return {
            "rule": self.rule.name,
            "severity": self.rule.severity,
            "subject": self.subject,
            "state": self.state,
            "value": round(self.value, 6),
            "threshold": self.rule.threshold,
            "op": self.rule.op,
            "fired_at": self.fired_at,
            "fires": self.fires,
            "flaps": self.flaps,
            "transition_seq": self.transition_seq,
            "firing_since": self.firing_since,
            "description": self.rule.description,
        }


class SloEngine:
    """Evaluates a rule table against a series view; owns alert lifecycle.

    ``view(name_pattern)`` must return ``List[SeriesPoints]`` whose labels
    include ``instance`` and ``role`` (the aggregator's ring adapter).
    ``evaluate`` returns the transitions that happened this pass —
    ``{"event": "fire"|"clear", ...alert}`` — which the caller turns into
    telemetry records; current state is always available via ``active``
    and ``snapshot`` (the ``/alertz`` payload).
    """

    def __init__(self, rules: Optional[List[SloRule]] = None):
        self.rules: List[SloRule] = list(rules if rules is not None
                                         else default_rules())
        names = [r.name for r in self.rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate rule names: {sorted(names)}")
        self._alerts: Dict[Tuple[str, str], _AlertState] = {}
        self._history: List[Dict[str, Any]] = []
        self._max_history = 256
        # Monotonic across ALL alerts in this engine — one counter, not
        # per-rule, so a watcher can order interleaved transitions from
        # different rules with a single cursor.
        self._transition_seq = 0

    # -- measurement -------------------------------------------------------

    @staticmethod
    def _group(series: List[SeriesPoints], rule: SloRule) -> Dict[str, List[SeriesPoints]]:
        groups: Dict[str, List[SeriesPoints]] = {}
        for sp in series:
            if rule.role and sp.labels.get("role", "") != rule.role:
                continue
            subject = (sp.labels.get("instance", "unknown")
                       if rule.subject == "instance" else "fleet")
            groups.setdefault(subject, []).append(sp)
        return groups

    @staticmethod
    def _sum_delta(series: List[SeriesPoints], now: float,
                   window_s: float) -> Optional[float]:
        deltas = [d for d in (sp.window_delta(now, window_s) for sp in series)
                  if d is not None]
        return sum(deltas) if deltas else None

    def _measure(self, rule: SloRule, view: Callable[[str], List[SeriesPoints]],
                 now: float) -> Dict[str, float]:
        """subject -> measured value; subjects with no data are absent."""
        out: Dict[str, float] = {}
        num_series = view(rule.series)
        if rule.kind == "increase":
            for subject, group in self._group(num_series, rule).items():
                d = self._sum_delta(group, now, rule.window_s)
                if d is not None:
                    out[subject] = d
        elif rule.kind == "ratio":
            den_series = ([] if rule.denom == "__time__" else view(rule.denom))
            den_groups = self._group(den_series, rule)
            for subject, group in self._group(num_series, rule).items():
                num = self._sum_delta(group, now, rule.window_s)
                if num is None:
                    continue
                if rule.denom == "__time__":
                    den = max(sp.window_span(now, rule.window_s)
                              for sp in group)
                else:
                    den = self._sum_delta(den_groups.get(subject, []),
                                          now, rule.window_s)
                    if den is None:
                        continue
                if rule.denom_includes_series:
                    den += num
                if den <= _MIN_DENOM:
                    continue  # no traffic: abstain, never divide by ~0
                out[subject] = num / den
        else:  # gauge_growth
            for subject, group in self._group(num_series, rule).items():
                grew = 0.0
                at_peak = False
                for sp in group:
                    cutoff = now - rule.window_s
                    pts = [(t, v) for t, v in sp.points if t >= cutoff]
                    if len(pts) < 2:
                        continue
                    delta = pts[-1][1] - pts[0][1]
                    peak = max(v for _, v in pts)
                    grew = max(grew, delta)
                    at_peak = at_peak or pts[-1][1] >= peak - 1e-9
                if grew and at_peak:
                    out[subject] = grew
                elif group and any(len(sp.points) >= 2 for sp in group):
                    out[subject] = 0.0
        return out

    # -- lifecycle ---------------------------------------------------------

    def _transition(self, st: _AlertState, event: str, now: float) -> Dict[str, Any]:
        self._transition_seq += 1
        st.transition_seq = self._transition_seq
        rec = {"event": event, "t": now, **st.public()}
        self._history.append(rec)
        if len(self._history) > self._max_history:
            del self._history[: len(self._history) - self._max_history]
        st.last_transition = now
        return rec

    def evaluate(self, view: Callable[[str], List[SeriesPoints]],
                 now: Optional[float] = None) -> List[Dict[str, Any]]:
        now = time.time() if now is None else float(now)
        transitions: List[Dict[str, Any]] = []
        for rule in self.rules:
            measured = self._measure(rule, view, now)
            # Subjects never measured stay wherever they are until data
            # returns (a silent instance is the stale-instance sweep's
            # problem, not a phantom "recovered" signal).
            for subject, value in measured.items():
                key = (rule.name, subject)
                st = self._alerts.get(key)
                if st is None:
                    st = self._alerts[key] = _AlertState(rule=rule, subject=subject)
                st.value = value
                breach = rule.compare(value)
                if st.state == _INACTIVE:
                    if breach:
                        st.state = _PENDING
                        st.pending_since = now
                        if now - st.pending_since >= rule.for_s:
                            st.state = _FIRING
                            st.fired_at = now
                            st.firing_since = now
                            st.fires += 1
                            transitions.append(self._transition(st, "fire", now))
                elif st.state == _PENDING:
                    if not breach:
                        st.state = _INACTIVE
                    elif now - st.pending_since >= rule.for_s:
                        st.state = _FIRING
                        st.fired_at = now
                        st.firing_since = now
                        st.fires += 1
                        transitions.append(self._transition(st, "fire", now))
                elif st.state == _FIRING:
                    if not breach:
                        st.state = _CLEARING
                        st.healthy_since = now
                elif st.state == _CLEARING:
                    if breach:
                        st.state = _FIRING  # damped: no duplicate fire event
                    elif now - st.healthy_since >= rule.clear_for_s:
                        st.state = _INACTIVE
                        if now - st.fired_at <= 2 * rule.clear_for_s + rule.for_s:
                            st.flaps += 1
                        st.cleared_at = now
                        st.firing_since = 0.0
                        transitions.append(self._transition(st, "clear", now))
        return transitions

    # -- read side ---------------------------------------------------------

    def active(self) -> List[Dict[str, Any]]:
        return [st.public() for st in self._alerts.values()
                if st.state in (_FIRING, _CLEARING)]

    def snapshot(self) -> Dict[str, Any]:
        """The ``/alertz`` payload: active alerts, full state, history."""
        return {
            "active": self.active(),
            "alerts": [st.public() for st in self._alerts.values()
                       if st.state != _INACTIVE or st.fires],
            "history": list(self._history[-64:]),
            "rules": [{
                "name": r.name, "kind": r.kind, "series": r.series,
                "denom": r.denom or None, "op": r.op,
                "threshold": r.threshold, "window_s": r.window_s,
                "for_s": r.for_s, "clear_for_s": r.clear_for_s,
                "subject": r.subject, "role": r.role or None,
                "severity": r.severity, "description": r.description,
            } for r in self.rules],
        }


def match_series(pattern: str, name: str) -> bool:
    """fnmatch-style series matching (``*_degraded_total``)."""
    if any(ch in pattern for ch in "*?["):
        return fnmatch.fnmatchcase(name, pattern)
    return pattern == name
