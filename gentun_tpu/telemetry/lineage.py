"""Search forensics: per-genome lineage ledger + chip-hour cost accounting.

Two planes in one module, both off by default behind the same contract as
``spans.py`` (one module-level bool read per site, nothing touches RNG
state, bit-identical trajectories when off — docs/OBSERVABILITY.md
"Search forensics"):

- the **lineage ledger** — an event-sourced record of every genome's life:
  :func:`record` emits ``{"type": "lineage", "event": ..., "genome": ...}``
  records through the standard span sinks (flight ring, worker capture
  list, run JSONL), so a run's ``telemetry.jsonl`` doubles as the ledger.
  Event taxonomy (emitters in parentheses):

  ========================  ====================================================
  ``born``                  genome created — ``parents`` (genome keys),
                            ``op`` (``spawn``/``reproduce``) and ``genes``
                            (the genome itself, so the ledger doubles as a
                            surrogate training set — ``gentun_trace.py
                            dataset``) (both engines, populations)
  ``gate_rejected``         bred child vetoed by the surrogate rung −1
                            before dispatch — ``score`` (async engine,
                            ``surrogate.py``)
  ``dispatched``            job handed to a worker at a rung (broker)
  ``completed``             fitness landed — ``fitness``, ``rung``, ``cached``
                            (async engine)
  ``failed``                terminal evaluation failure (async engine)
  ``cache_hit``             fitness served without training — ``source`` is
                            ``local`` or ``service`` (async engine,
                            ``ServiceBackedCache``)
  ``follower_attach``       duplicate submission attached to an in-flight
                            evaluation instead of dispatching (async engine)
  ``promoted``              ASHA rung promotion — ``from_rung``, ``to_rung``
                            (fidelity ladder)
  ``evicted``               aged out of the steady-state ring (async engine)
  ``quarantined``           poisoned for a session after repeated terminal
                            failures (sessions)
  ``requeued``              dispatched job returned to the queue (worker loss,
                            drain, straggler speculation, transient failure)
                            (broker)
  ``warm_started``          slot inherited banked lower-rung weights
                            (``models/cnn`` weight bank)
  ========================  ====================================================

- the **cost ledger** (:class:`CostLedger`) — every device-second measured
  by per-genome ``device`` spans attributed to a
  ``(session, genome, rung, worker)`` cell, with by-rung/by-session/
  by-worker rollups, a ``device_seconds_total{rung}`` counter, and a
  ``cost`` status provider on ``/statusz``.  Workers emit the device spans
  inside their capture sink (:func:`emit_device`), ship them home in the
  result frame, and the broker attributes them on ingest
  (:func:`observe_records`); local (no-broker) evaluation attributes
  directly.  Both paths bill the same spans, never both.

The forensics plane rides the telemetry plane: :func:`enable` requires
``spans.enable()`` (or a ``RunTelemetry`` install) for the records to
land anywhere, and the master advertises forensics to workers by stamping
``fz: 1`` into the propagated trace context so the per-job device spans
are only produced when someone is accounting for them.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from . import health as _health
from . import spans as _spans
from .registry import get_registry

__all__ = [
    "enabled",
    "enable",
    "disable",
    "record",
    "genome_key",
    "CostLedger",
    "get_ledger",
    "reset_ledger",
    "emit_device",
    "observe_records",
    "forensic_context",
    "wants_device_spans",
]

# Module-level switch, mirroring spans._ENABLED: one bool read is the
# entire disabled-path cost of every lineage site.
_ENABLED = False

# genome_key runs once per submitted job on the broker's dispatch path; a
# shared encoder instance skips the per-call JSONEncoder construction that
# custom separators force on json.dumps.  Byte-identical output, so the
# hash — the identity everything keys on — is unchanged.
_canon_encode = json.JSONEncoder(sort_keys=True, separators=(",", ":")).encode


def enabled() -> bool:
    """The one guard every lineage/cost site checks."""
    return _ENABLED


def enable() -> None:
    """Turn the forensics plane on and expose the cost ledger on
    ``/statusz`` (provider name ``cost``)."""
    global _ENABLED
    _ENABLED = True
    _health.register_status_provider("cost", _cost_status)


def disable() -> None:
    global _ENABLED
    _ENABLED = False
    _health.unregister_status_provider("cost", _cost_status)


def genome_key(genes: Any) -> str:
    """Content address for a genome — the identity every lineage event and
    cost cell keys on.

    64-bit blake2b over the canonical (sorted-key) JSON of the genes, the
    same hash family and width as ``utils/fitness_store.key_digest``.
    Genes that don't survive JSON fall back to ``repr`` so the identity
    still sticks to the exact value.  (Canonical home of the hash the
    session quarantine table re-exports as ``sessions.genome_key``.)
    """
    try:
        blob = _canon_encode(genes)
    except (TypeError, ValueError):
        blob = repr(genes)
    return hashlib.blake2b(blob.encode("utf-8"), digest_size=8).hexdigest()


def record(event: str, genome: Optional[str], **fields: Any) -> None:
    """Emit one lineage ledger entry.  No-op (one bool read) when the
    plane is off.  ``fields`` with value None are dropped so optional
    dimensions (session, worker) never pad the JSONL."""
    if not _ENABLED:
        return
    rec: Dict[str, Any] = {
        "type": "lineage",
        "event": event,
        "genome": genome,
        "t_wall": time.time(),
        "pid": os.getpid(),
    }
    for k, v in fields.items():
        if v is not None:
            rec[k] = v
    _spans.emit_record(rec)


# -- chip-hour cost accounting ---------------------------------------------


class CostLedger:
    """Device-seconds attributed to ``(session, genome, rung, worker)``.

    Fed by :func:`emit_device` (local evaluation) and
    :func:`observe_records` (worker-shipped device spans, attributed
    broker-side).  Written from broker-loop and evaluation threads, read
    as snapshots from HTTP/status threads — every method takes the lock.
    """

    def __init__(self):
        self._lock = threading.Lock()
        # (session, genome, rung, worker) -> seconds
        self._cells: Dict[tuple, float] = {}

    def add(self, seconds: float, session: Optional[str] = None,
            genome: Optional[str] = None, rung: Any = 0,
            worker: Optional[str] = None) -> None:
        key = (str(session) if session else "default",
               str(genome) if genome else "?",
               int(rung or 0),
               str(worker) if worker else "local")
        s = float(seconds)
        with self._lock:
            self._cells[key] = self._cells.get(key, 0.0) + s
        get_registry().counter("device_seconds_total", rung=str(key[2])).inc(s)

    def _rollup(self, idx: int) -> Dict[Any, float]:
        with self._lock:
            out: Dict[Any, float] = {}
            for key, s in self._cells.items():
                out[key[idx]] = out.get(key[idx], 0.0) + s
            return out

    def by_session(self) -> Dict[str, float]:
        return self._rollup(0)

    def by_genome(self) -> Dict[str, float]:
        return self._rollup(1)

    def by_rung(self) -> Dict[int, float]:
        return self._rollup(2)

    def by_worker(self) -> Dict[str, float]:
        return self._rollup(3)

    def total(self) -> float:
        with self._lock:
            return sum(self._cells.values())

    def cells(self) -> List[Dict[str, Any]]:
        """Every attribution cell as a JSON-native row (artifacts)."""
        with self._lock:
            items = sorted(self._cells.items())
        return [{"session": k[0], "genome": k[1], "rung": k[2],
                 "worker": k[3], "device_s": v} for k, v in items]

    def snapshot(self) -> Dict[str, Any]:
        """The ``/statusz`` ``cost`` block: totals and rollups, never the
        (unbounded) per-genome cells."""
        with self._lock:
            n_genomes = len({k[1] for k in self._cells})
        return {
            "device_s_total": round(self.total(), 6),
            "by_rung": {str(k): round(v, 6)
                        for k, v in sorted(self.by_rung().items())},
            "by_session": {k: round(v, 6)
                           for k, v in sorted(self.by_session().items())},
            "by_worker": {k: round(v, 6)
                          for k, v in sorted(self.by_worker().items())},
            "genomes": n_genomes,
        }

    def reset(self) -> None:
        with self._lock:
            self._cells.clear()


_LEDGER = CostLedger()


def get_ledger() -> CostLedger:
    """The process-wide cost ledger."""
    return _LEDGER


def reset_ledger() -> None:
    """Drop every attribution cell (tests, fresh studies)."""
    _LEDGER.reset()


def _cost_status() -> Dict[str, Any]:
    return _LEDGER.snapshot()


def emit_device(dur_s: float, genome: Optional[str], rung: Any = 0,
                session: Optional[str] = None, worker: Optional[str] = None,
                job: Optional[str] = None,
                start_monotonic: Optional[float] = None) -> None:
    """Emit one per-genome ``device`` span record and attribute it.

    Inside a worker's capture sink the record ships home in the result
    frame and the broker attributes it (:func:`observe_records`); outside
    one (local evaluation on the master) the ledger is charged directly.
    Exactly one of the two paths bills each span.

    Unlike :func:`record` this does NOT check :func:`enabled` — the
    caller guards (locally with :func:`enabled`, or worker-side with
    :func:`wants_device_spans`, where the MASTER's plane is the one that
    is on).
    """
    attrs: Dict[str, Any] = {"genome": genome, "rung": int(rung or 0)}
    if session is not None:
        attrs["session"] = session
    if worker is not None:
        attrs["worker"] = worker
    if job is not None:
        attrs["job"] = job
    t0 = time.monotonic() - dur_s if start_monotonic is None else start_monotonic
    shipped = _spans.capturing()
    _spans.record_span("device", t0, dur_s, attrs=attrs)
    if not shipped:
        _LEDGER.add(dur_s, session=session, genome=genome, rung=rung,
                    worker=worker)


def observe_records(records, worker: Optional[str] = None) -> None:
    """Attribute the ``device`` spans of a worker's shipped record list to
    the cost ledger (called broker-side at result ingest, AFTER the
    duplicate-result guard, so redelivered frames never double-bill)."""
    if not _ENABLED or not records:
        return
    for rec in records:
        if (isinstance(rec, dict) and rec.get("type") == "span"
                and rec.get("kind") == "device"):
            a = rec.get("attrs") or {}
            _LEDGER.add(rec.get("dur_s", 0.0), session=a.get("session"),
                        genome=a.get("genome"), rung=a.get("rung", 0),
                        worker=a.get("worker") or worker)


# -- cross-process advertisement -------------------------------------------
#
# Workers must not pay per-job span emission for a master nobody is
# accounting: the master stamps `fz: 1` into the trace context it already
# propagates (protocol-transparent — old workers ignore the key, old
# masters never send it), and the worker checks it before emitting.


def forensic_context(ctx: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Stamp the forensics flag into a wire trace context when the plane
    is on.  Returns ``ctx`` unchanged (possibly None) when off — the wire
    stays byte-identical to a forensics-less run."""
    if _ENABLED and ctx is not None:
        ctx = dict(ctx)
        ctx["fz"] = 1
    return ctx


def wants_device_spans(ctx: Optional[Dict[str, Any]]) -> bool:
    """Worker-side check: did the master ask for per-job device spans?"""
    return bool(ctx and ctx.get("fz"))
