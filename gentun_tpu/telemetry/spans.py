"""Span-based tracing with cross-process trace propagation.

A *span* is a named, monotonic-clock-timed interval with a
``trace_id``/``span_id``/``parent_id`` identity.  The GA master opens a
``generation`` span; the trace context it creates rides the job payload
over the wire (``distributed/protocol.py``), the worker re-attaches it
(:func:`attach`), and the worker's ``train``/``eval`` spans come back in
the ``result`` frame carrying the *same* ``trace_id`` — so one run is one
trace, stitched across processes.

Disabled is the default and the fast path: every instrumentation site
guards on :func:`enabled` (one global bool read) and :func:`span` returns
a shared no-op singleton — no dict, no object, no contextvar churn.  The
production code paths are byte-identical in behaviour when telemetry is
off; nothing here touches RNG state either way.

Routing: finished span records go to the innermost active sink —
a :func:`capture` list (used by workers to ship spans home in the result
frame) if one is installed in the current context, else the process-wide
run sink (``export.RunTelemetry``).  Span durations are additionally
observed into the ``span_seconds{kind=...}`` histogram of the global
metrics registry.
"""

from __future__ import annotations

import contextvars
import os
import threading
import time
import uuid
from typing import Any, Dict, Iterator, List, Optional, Tuple

from .registry import get_registry

__all__ = [
    "enabled",
    "enable",
    "disable",
    "span",
    "record_span",
    "record_event",
    "current_context",
    "attach",
    "capture",
    "capturing",
    "emit_record",
    "set_run_sink",
    "has_run_sink",
    "set_flight_sink",
    "has_flight_sink",
]

# Module-level switch.  A plain bool read is the entire disabled-path cost
# at every instrumentation site.
_ENABLED = False

# (trace_id, span_id) of the innermost live span in this context.
_CTX: contextvars.ContextVar[Optional[Tuple[str, str]]] = contextvars.ContextVar(
    "gentun_tpu_trace", default=None)

# Innermost capture list, if any (worker-side shipping).  Falls back to
# the process-wide run sink below.
_CAPTURE: contextvars.ContextVar[Optional[List[Dict[str, Any]]]] = contextvars.ContextVar(
    "gentun_tpu_capture", default=None)

# The active RunTelemetry (export.py installs/uninstalls it).  Guarded by
# a lock only on mutation; the read is a plain attribute load.
_run_sink = None
_sink_lock = threading.Lock()

# The active flight recorder ring (telemetry/flight.py), fed a copy of
# EVERY record regardless of capture/run-sink routing — the black box
# must see worker-side captured spans too.  One attribute load when off.
_flight_sink = None


def enabled() -> bool:
    """The one guard every instrumentation site checks."""
    return _ENABLED


def enable() -> None:
    global _ENABLED
    _ENABLED = True


def disable() -> None:
    global _ENABLED
    _ENABLED = False


def set_run_sink(sink) -> None:
    """Install (or clear, with None) the process-wide record sink.  The
    sink needs one method: ``record(dict)`` (thread-safe)."""
    global _run_sink
    with _sink_lock:
        _run_sink = sink


def has_run_sink() -> bool:
    return _run_sink is not None


def has_flight_sink() -> bool:
    return _flight_sink is not None


def set_flight_sink(sink) -> None:
    """Install (or clear) the flight-recorder ring.  Managed by
    ``telemetry/flight.py``; unlike the run sink it is NOT bypassed by
    :class:`capture` — the ring sees every record this process emits."""
    global _flight_sink
    with _sink_lock:
        _flight_sink = sink


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


def _emit(rec: Dict[str, Any], dur_kind: Optional[Tuple[float, str]] = None) -> None:
    """Route a record to the innermost capture list or the run sink.

    ``dur_kind`` carries (duration, kind) for span records; the
    ``span_seconds`` histogram is observed here ONLY when the record goes
    to a sink directly — captured records are observed at :func:`ingest`
    on the master instead, so in-process workers (which share this
    registry) don't double-count.
    """
    fl = _flight_sink
    if fl is not None:
        fl.record(rec)
    cap = _CAPTURE.get()
    if cap is not None:
        cap.append(rec)
        return
    if dur_kind is not None:
        _observe_span_seconds(dur_kind[1], dur_kind[0], rec)
    sink = _run_sink
    if sink is not None:
        sink.record(rec)


def _observe_span_seconds(kind: str, dur: float, rec: Dict[str, Any]) -> None:
    """Observe a span duration, adding a ``session`` label only when the
    span carries one (multi-tenant runs) — single-tenant series keep their
    pre-session label set, same pattern as the straggler counters."""
    attrs = rec.get("attrs")
    sess = attrs.get("session") if attrs else None
    if sess is None:
        get_registry().histogram("span_seconds", kind=kind).observe(dur)
    else:
        get_registry().histogram("span_seconds", kind=kind, session=str(sess)).observe(dur)


class _NoopSpan:
    """Shared do-nothing context manager: the disabled-path return value
    of :func:`span`.  A singleton — ``span(...) is span(...)`` when
    disabled, which the tests assert as the no-allocation guarantee."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs) -> None:
        pass


_NOOP = _NoopSpan()


class _Span:
    __slots__ = ("kind", "attrs", "trace_id", "span_id", "parent_id",
                 "_token", "_t0", "_wall0")

    def __init__(self, kind: str, attrs: Optional[Dict[str, Any]]):
        self.kind = kind
        self.attrs = dict(attrs) if attrs else {}
        parent = _CTX.get()
        if parent is None:
            self.trace_id = _new_id()
            self.parent_id = None
        else:
            self.trace_id, self.parent_id = parent
        self.span_id = _new_id()
        self._token = None
        self._t0 = 0.0
        self._wall0 = 0.0

    def set(self, **attrs: Any) -> None:
        """Attach attributes after entry (e.g. a result count)."""
        self.attrs.update(attrs)

    def __enter__(self) -> "_Span":
        self._token = _CTX.set((self.trace_id, self.span_id))
        self._wall0 = time.time()
        self._t0 = time.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        dur = time.monotonic() - self._t0
        _CTX.reset(self._token)
        rec = {
            "type": "span",
            "kind": self.kind,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "t_wall": self._wall0,
            "dur_s": dur,
            "pid": os.getpid(),
        }
        if self.attrs:
            rec["attrs"] = self.attrs
        if exc_type is not None:
            rec["error"] = exc_type.__name__
        _emit(rec, dur_kind=(dur, self.kind))
        return False


def span(kind: str, attrs: Optional[Dict[str, Any]] = None):
    """Open a span context manager; the no-op singleton when disabled.

    ``attrs`` is an optional dict parameter rather than ``**kwargs`` so
    the disabled path allocates nothing at the call site.
    """
    if not _ENABLED:
        return _NOOP
    return _Span(kind, attrs)


def record_span(kind: str, start_monotonic: float, dur_s: float,
                trace: Optional[Dict[str, str]] = None,
                attrs: Optional[Dict[str, Any]] = None) -> None:
    """Record a span measured externally (the broker times queue-wait with
    raw monotonic stamps because submit and dispatch happen in different
    callbacks — there is no ``with`` block to wrap)."""
    if not _ENABLED:
        return
    if trace:
        trace_id = trace.get("trace_id") or _new_id()
        parent_id = trace.get("span_id")
    else:
        ctx = _CTX.get()
        trace_id, parent_id = (ctx if ctx else (_new_id(), None))
    rec = {
        "type": "span",
        "kind": kind,
        "trace_id": trace_id,
        "span_id": _new_id(),
        "parent_id": parent_id,
        "t_wall": time.time() - (time.monotonic() - start_monotonic),
        "dur_s": dur_s,
        "pid": os.getpid(),
    }
    if attrs:
        rec["attrs"] = attrs
    _emit(rec, dur_kind=(dur_s, kind))


def record_event(name: str, data: Optional[Dict[str, Any]] = None) -> None:
    """Record a point-in-time structured event (fault injections)."""
    if not _ENABLED:
        return
    ctx = _CTX.get()
    rec: Dict[str, Any] = {
        "type": "event",
        "name": name,
        "t_wall": time.time(),
        "pid": os.getpid(),
    }
    if ctx is not None:
        rec["trace_id"], rec["parent_id"] = ctx
    if data:
        rec["data"] = data
    _emit(rec)


def current_context() -> Optional[Dict[str, str]]:
    """The wire form of the innermost span identity — what the master
    injects into job payloads.  None when no span is live (or disabled)."""
    if not _ENABLED:
        return None
    ctx = _CTX.get()
    if ctx is None:
        return None
    return {"trace_id": ctx[0], "span_id": ctx[1]}


def capturing() -> bool:
    """Whether a :class:`capture` sink is active in this context — i.e.
    records emitted here will be shipped to (and accounted by) a remote
    master rather than landing locally.  The lineage cost ledger uses
    this to avoid double-counting in-process workers."""
    return _CAPTURE.get() is not None


def emit_record(rec: Dict[str, Any]) -> None:
    """Route an externally built record (a lineage ledger entry) through
    the standard sinks — flight ring, innermost capture list, else the
    run sink — with no histogram side effects.  Callers guard on
    :func:`enabled`; this is the raw-routing twin of :func:`record_event`
    for records whose schema the caller owns."""
    _emit(rec)


class attach:
    """Adopt a remote trace context so local spans parent under it.

    Worker-side: ``with attach(job.get("trace")): ...`` makes every span
    opened inside carry the master's ``trace_id`` with the master-side
    span as parent.  A None/empty context is a no-op (jobs from a
    telemetry-disabled master)."""

    __slots__ = ("_ctx", "_token")

    def __init__(self, ctx: Optional[Dict[str, str]]):
        self._ctx = ctx
        self._token = None

    def __enter__(self):
        if self._ctx and self._ctx.get("trace_id"):
            self._token = _CTX.set(
                (self._ctx["trace_id"], self._ctx.get("span_id") or _new_id()))
        return self

    def __exit__(self, *exc):
        if self._token is not None:
            _CTX.reset(self._token)
        return False


class capture:
    """Divert span/event records in this context into a list instead of
    the run sink — how a worker collects the spans it ships back in the
    ``result`` frame (and how in-process workers avoid double-writing the
    master's artifact)."""

    __slots__ = ("records", "_token")

    def __init__(self):
        self.records: List[Dict[str, Any]] = []
        self._token = None

    def __enter__(self) -> List[Dict[str, Any]]:
        self._token = _CAPTURE.set(self.records)
        return self.records

    def __exit__(self, *exc):
        _CAPTURE.reset(self._token)
        return False


def ingest(records) -> None:
    """Feed externally produced span records (a worker's shipped list)
    into the active sink, re-observing their durations locally so the
    master's histograms cover worker time too."""
    if not _ENABLED or not records:
        return
    for rec in records:
        if not isinstance(rec, dict):
            continue
        if rec.get("type") == "span" and "dur_s" in rec and "kind" in rec:
            _observe_span_seconds(rec["kind"], rec["dur_s"], rec)
        _emit(rec)


# Subprocess workers opt in via environment: the master can't reach into
# their interpreter, so `GENTUN_TPU_TELEMETRY=1` (or the worker CLI's
# --telemetry flag) enables collection there.
if os.environ.get("GENTUN_TPU_TELEMETRY", "").lower() in ("1", "true", "on"):
    enable()
