"""Offline converter: run ``telemetry.jsonl`` → Chrome ``trace_event`` JSON.

The run artifact (``export.RunTelemetry``) already contains everything a
timeline needs — master spans, broker spans, every worker's shipped spans
(tagged ``src`` by the worker), per-genome ``device`` spans, and lineage
ledger entries — but as flat JSONL.  :func:`to_trace_events` reshapes it
into the `Chrome trace_event format
<https://docs.google.com/document/d/1CvAClvFfyA5R-PhYUmn5OOQtYMH4h6I0nSsKchNAySU>`_
(the ``{"traceEvents": [...]}`` object form), loadable in Perfetto
(https://ui.perfetto.dev) or ``chrome://tracing``:

- one **process track per emitting process**: ``master`` (the search
  engine), ``broker`` (dispatch/queue spans), and one per worker ``src``.
  The pids are synthetic and stable: master=1, broker=2, workers from 3 in
  sorted-name order — in-process fleets share one OS pid, so the OS pid on
  the records cannot be the track key.
- one **thread track per span kind** within a process, except ``device``
  spans, which land on a per-rung track (tid ``1000 + rung``) so the
  chip-hour attribution reads directly off the timeline.
- **flow arrows** stitching each propagated trace (``trace_id``) across
  processes: dispatch on the broker → evaluate on the worker → result
  ingest, drawn start-to-finish in span start order.  Flow ``id`` is the
  chain's first span's ``span_id`` (span ids are unique, so flows never
  collide).
- **instant events** for the lineage ledger (``born``, ``promoted``,
  ``evicted``, …) and structured events (fault injections) on the track of
  the process that emitted them.

Timestamps are wall-clock microseconds normalized so the earliest record
sits at ts=0 — Perfetto needs non-negative, same-epoch stamps, and the
JSONL's ``t_wall`` (span START wall time) provides exactly that.

Offline and stdlib-only by design: nothing here runs during a search, so
a forensics pass costs the search nothing.  CLI: ``scripts/gentun_trace.py
convert run/telemetry.jsonl trace.json``.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List, Optional, Tuple

__all__ = ["load_jsonl", "to_trace_events", "convert"]

#: span kinds emitted by the broker loop (no ``src`` on the record, but
#: they are dispatch-plane time, not engine time)
BROKER_KINDS = frozenset({"queue_wait", "job", "dispatch_rtt"})

#: tid offset for per-rung device tracks (rung r → tid 1000+r)
DEVICE_TID_BASE = 1000

_MASTER_PID = 1
_BROKER_PID = 2
_FIRST_WORKER_PID = 3

#: instant/metadata records that carry a wall stamp worth normalizing on
_TIMED_TYPES = frozenset({"span", "event", "lineage"})


def load_jsonl(path: str) -> List[Dict[str, Any]]:
    """Read one run artifact (or lineage ledger) — one JSON object per
    line, bad lines skipped (a crashed run may truncate the tail)."""
    records: List[Dict[str, Any]] = []
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict):
                records.append(rec)
    return records


def _process_key(rec: Dict[str, Any]) -> str:
    """Which track a record belongs to: the worker that shipped it, the
    broker for dispatch-plane kinds, the master otherwise."""
    src = rec.get("src")
    if src is not None:
        return str(src)
    if rec.get("type") == "span" and rec.get("kind") in BROKER_KINDS:
        return "broker"
    return "master"


def _pid_map(records: Iterable[Dict[str, Any]]) -> Dict[str, int]:
    """Stable synthetic pids: master=1, broker=2, workers from 3 in
    sorted order — same input, same mapping, every time."""
    keys = {_process_key(rec) for rec in records}
    pids = {}
    if "master" in keys:
        pids["master"] = _MASTER_PID
    if "broker" in keys:
        pids["broker"] = _BROKER_PID
    workers = sorted(k for k in keys if k not in ("master", "broker"))
    for i, k in enumerate(workers):
        pids[k] = _FIRST_WORKER_PID + i
    return pids


def _t0_wall(records: Iterable[Dict[str, Any]]) -> float:
    stamps = [rec["t_wall"] for rec in records
              if rec.get("type") in _TIMED_TYPES
              and isinstance(rec.get("t_wall"), (int, float))]
    return min(stamps) if stamps else 0.0


def _us(t_wall: float, t0: float) -> int:
    return max(0, int(round((t_wall - t0) * 1e6)))


def to_trace_events(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Convert loaded JSONL records to a trace_event object.

    Returns ``{"traceEvents": [...], "displayTimeUnit": "ms"}`` —
    ``json.dump`` it to a file and load that file in Perfetto.
    """
    pids = _pid_map(records)
    t0 = _t0_wall(records)
    events: List[Dict[str, Any]] = []

    # Metadata: name every process track.
    for name, pid in sorted(pids.items(), key=lambda kv: kv[1]):
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": name}})

    # Thread tracks: one per (process, span kind), allocated in first-seen
    # deterministic order; device spans get per-rung tracks instead.
    tids: Dict[Tuple[int, str], int] = {}
    next_tid: Dict[int, int] = {}
    device_rungs: set = set()

    def _tid(pid: int, kind: str) -> int:
        key = (pid, kind)
        if key not in tids:
            next_tid[pid] = next_tid.get(pid, 0) + 1
            tids[key] = next_tid[pid]
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": tids[key], "args": {"name": kind}})
        return tids[key]

    # trace_id → [(ts_us, pid, tid, span_id)] for the flow pass.
    chains: Dict[str, List[Tuple[int, int, int, str]]] = {}

    for rec in records:
        rtype = rec.get("type")
        if rtype == "span":
            kind = str(rec.get("kind"))
            pid = pids[_process_key(rec)]
            attrs = rec.get("attrs") or {}
            if kind == "device":
                rung = int(attrs.get("rung", 0) or 0)
                tid = DEVICE_TID_BASE + rung
                if (pid, rung) not in device_rungs:
                    device_rungs.add((pid, rung))
                    events.append({
                        "ph": "M", "name": "thread_name", "pid": pid,
                        "tid": tid, "args": {"name": f"device rung {rung}"}})
            else:
                tid = _tid(pid, kind)
            ts = _us(rec.get("t_wall", t0), t0)
            args: Dict[str, Any] = dict(attrs)
            for k in ("trace_id", "span_id", "error"):
                if rec.get(k) is not None:
                    args[k] = rec[k]
            events.append({
                "ph": "X", "name": kind, "cat": "span",
                "pid": pid, "tid": tid, "ts": ts,
                "dur": max(0, int(round(float(rec.get("dur_s", 0.0)) * 1e6))),
                "args": args,
            })
            trace_id = rec.get("trace_id")
            span_id = rec.get("span_id")
            if trace_id and span_id:
                chains.setdefault(str(trace_id), []).append(
                    (ts, pid, tid, str(span_id)))
        elif rtype == "lineage":
            pid = pids[_process_key(rec)]
            tid = _tid(pid, "lineage")
            args = {k: v for k, v in rec.items()
                    if k not in ("type", "t_wall", "pid")}
            events.append({
                "ph": "i", "s": "t", "name": str(rec.get("event")),
                "cat": "lineage", "pid": pid, "tid": tid,
                "ts": _us(rec.get("t_wall", t0), t0), "args": args,
            })
        elif rtype == "event":
            pid = pids[_process_key(rec)]
            tid = _tid(pid, "events")
            args = {k: v for k, v in rec.items()
                    if k not in ("type", "t_wall", "pid")}
            events.append({
                "ph": "i", "s": "t", "name": str(rec.get("name")),
                "cat": "event", "pid": pid, "tid": tid,
                "ts": _us(rec.get("t_wall", t0), t0), "args": args,
            })

    # Flow arrows: a propagated trace that touched more than one process
    # becomes a start→(step…)→finish chain in span start order.  Flow id =
    # the chain's first span_id, so ids are unique across flows and every
    # flow id IS a span id (the forensics tests key on that).
    for trace_id, chain in sorted(chains.items()):
        if len({pid for _, pid, _, _ in chain}) < 2:
            continue
        chain.sort()
        flow_id = chain[0][3]
        for i, (ts, pid, tid, _sid) in enumerate(chain):
            ph = "s" if i == 0 else ("f" if i == len(chain) - 1 else "t")
            ev = {"ph": ph, "id": flow_id, "name": "dispatch",
                  "cat": "flow", "pid": pid, "tid": tid, "ts": ts}
            if ph == "f":
                ev["bp"] = "e"
            events.append(ev)

    return {"traceEvents": events, "displayTimeUnit": "ms"}


def convert(in_path: str, out_path: Optional[str] = None) -> Dict[str, Any]:
    """Load a run's JSONL and write the Perfetto-loadable trace JSON.
    Returns the trace object (also when ``out_path`` is None)."""
    trace = to_trace_events(load_jsonl(in_path))
    if out_path is not None:
        with open(out_path, "w", encoding="utf-8") as fh:
            json.dump(trace, fh, separators=(",", ":"))
    return trace
