"""Live ops endpoints: /metrics, /healthz, /statusz, /debugz/flight.

The scrape side of the observability plane (OBSERVABILITY.md "Live ops
plane").  Zero-dependency by construction: a stdlib
``ThreadingHTTPServer`` running in a daemon thread, so a master or
worker gains live introspection without growing a web framework — the
same constraint as ``registry.py``.

Endpoints (GET only):

- ``/metrics`` — the process registry via ``render_prometheus()``,
  scrape-ready text exposition format.
- ``/healthz`` — 200 ``{"status": "ok"}`` / 503 ``{"status":
  "unhealthy", "reasons": [...]}`` from :func:`health.check_health`:
  a *gating* heartbeat source gone silent past its timeout, or a
  watchdog-flagged straggler job, flips it; both self-heal.
- ``/statusz`` — JSON fleet/engine snapshot: uptime, pid, healthz
  verdict, per-source heartbeat ages, and every registered status
  provider (broker fleet table, engine progress, worker identity).
- ``/debugz/flight`` — the flight recorder ring as ndjson (404 when no
  recorder is active).

:func:`start_ops_server` is the one-call entry point the worker CLI's
``--ops-port`` uses: it enables the health plane, arms the flight
recorder (which enables span collection), and serves.  Everything it
turns on follows the PR-2 contract — a process that never calls it runs
the untouched one-bool-read disabled paths.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

from . import flight as _flight
from . import health as _health
from .registry import MetricsRegistry, get_registry

__all__ = ["OpsServer", "start_ops_server", "stop_ops_server", "active_ops_server"]

_active: Optional["OpsServer"] = None


class _Handler(BaseHTTPRequestHandler):
    # Tests and gentun-top poll rapidly; per-request stderr noise would
    # drown real logs.
    def log_message(self, format, *args):  # noqa: A002 - stdlib signature
        pass

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        try:
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):  # pragma: no cover
            pass

    def _send_json(self, code: int, obj) -> None:
        self._send(code, json.dumps(obj, indent=1, default=str).encode("utf-8"),
                   "application/json; charset=utf-8")

    def do_GET(self):  # noqa: N802 - stdlib dispatch name
        path = self.path.split("?", 1)[0]
        srv: "OpsServer" = self.server.ops  # type: ignore[attr-defined]
        if path == "/metrics":
            body = srv.registry.render_prometheus().encode("utf-8")
            self._send(200, body, "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/healthz":
            ok, reasons = _health.check_health()
            self._send_json(
                200 if ok else 503,
                {"status": "ok" if ok else "unhealthy",
                 "reasons": reasons,
                 "uptime_s": round(time.monotonic() - srv.t_start, 3)})
        elif path == "/statusz":
            ok, reasons = _health.check_health()
            self._send_json(200, {
                "uptime_s": round(time.monotonic() - srv.t_start, 3),
                "pid": srv.pid,
                "healthy": ok,
                "reasons": reasons,
                "heartbeats": _health.heartbeats(),
                **_health.status_snapshot(),
            })
        elif path == "/debugz/flight":
            rec = _flight.active()
            if rec is None:
                self._send_json(404, {"error": "no flight recorder active"})
            else:
                self._send(200, rec.render_jsonl(reason="debugz").encode("utf-8"),
                           "application/x-ndjson; charset=utf-8")
        else:
            self._send_json(404, {
                "error": f"unknown path {path!r}",
                "endpoints": ["/metrics", "/healthz", "/statusz", "/debugz/flight"],
            })


class OpsServer:
    """The HTTP surface; owns the daemon serve thread.

    ``port=0`` binds an ephemeral port (tests, multi-process fleets on
    one box) — read it back from :attr:`address` after :meth:`start`.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 registry: Optional[MetricsRegistry] = None):
        import os

        self.registry = registry if registry is not None else get_registry()
        self.t_start = time.monotonic()
        self.pid = os.getpid()
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.ops = self  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> Tuple[str, int]:
        return self._httpd.server_address[:2]

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    def start(self) -> "OpsServer":
        # Version identity on every /metrics page this process serves —
        # the aggregator folds the pushed copy into the fleet version-skew
        # table, and a lone scraped process still self-identifies.
        from .buildinfo import set_build_info
        set_build_info(self.registry)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.25},
            name="gentun-ops-server", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


def active_ops_server() -> Optional[OpsServer]:
    return _active


def start_ops_server(port: int = 0, host: str = "127.0.0.1",
                     registry: Optional[MetricsRegistry] = None,
                     flight_path: str = "flight.jsonl",
                     flight_capacity: int = _flight.DEFAULT_CAPACITY) -> OpsServer:
    """Turn the whole ops plane on: health beats gating /healthz, flight
    recorder armed (span collection enabled), HTTP endpoints serving.
    Replaces any previously started server."""
    global _active
    if _active is not None:
        stop_ops_server()
    _health.enable()
    _flight.enable(path=flight_path, capacity=flight_capacity)
    srv = OpsServer(host=host, port=port, registry=registry).start()
    _active = srv
    return srv


def stop_ops_server() -> None:
    """Stop the active server and switch the health/flight planes back
    off (span collection survives only if a RunTelemetry sink holds it)."""
    global _active
    srv = _active
    _active = None
    if srv is not None:
        srv.stop()
    _health.disable()
    _flight.disable()
