"""Gene and genome specifications for the GA engine.

Reference parity: in gentun the genome lives implicitly inside each
``Individual`` subclass as a dict of hyperparameter values plus per-gene
(default, minimum, maximum) bounds (``gentun/individuals.py`` [PUB]; see
SURVEY.md §2.3).  The TPU rebuild factors that into an explicit, declarative
layer: a :class:`GenomeSpec` is an ordered collection of typed genes, and all
genetic operators (sampling, crossover, mutation) are pure functions of a
``numpy.random.Generator`` — determinism under a fixed seed is a design goal
(SURVEY.md §7 step 1), because it is what makes the distributed search
reproducible and the operator suite property-testable.

Genome *values* are plain JSON-serializable dicts ``{gene_name: value}``;
binary genes are tuples of 0/1 ints.  Keeping values as plain data (rather
than objects) is what lets the distributed layer ship genes over the wire
untouched, mirroring the reference's tiny wire format (SURVEY.md §1).
"""

from __future__ import annotations

import dataclasses
import itertools
import math
from typing import Any, Dict, Iterable, List, Mapping, Sequence, Tuple, Union

import numpy as np

__all__ = [
    "BinaryGene",
    "FloatGene",
    "IntGene",
    "ChoiceGene",
    "Gene",
    "GenomeSpec",
    "genetic_cnn_genome",
    "boosting_genome",
    "xgboost_genome",
]


@dataclasses.dataclass(frozen=True)
class BinaryGene:
    """A fixed-length bit-string gene.

    Used for the Genetic-CNN DAG encoding: one gene per stage, one bit per
    ordered node pair (SURVEY.md §2.3; gentun ``GeneticCnnIndividual`` [PUB]).
    """

    name: str
    length: int

    def __post_init__(self):
        if self.length < 0:
            raise ValueError(f"gene {self.name!r}: length must be >= 0")

    def default(self) -> Tuple[int, ...]:
        return (1,) * self.length  # fully-connected DAG

    def sample(self, rng: np.random.Generator) -> Tuple[int, ...]:
        # Bernoulli(0.5) per bit, per the reference's random init (SURVEY §2.3).
        return tuple(int(b) for b in rng.integers(0, 2, size=self.length))

    def mutate(self, value: Tuple[int, ...], rng: np.random.Generator, rate: float) -> Tuple[int, ...]:
        """Per-bit flip with probability ``rate`` (gentun bit-flip mutation)."""
        flips = rng.random(self.length) < rate
        return tuple(int(b) ^ int(f) for b, f in zip(value, flips))

    def validate(self, value: Any) -> Tuple[int, ...]:
        value = tuple(int(v) for v in value)
        if len(value) != self.length or any(v not in (0, 1) for v in value):
            raise ValueError(f"gene {self.name!r}: invalid bit-string {value!r}")
        return value

    def grid_values(self) -> List[Tuple[int, ...]]:
        """All 2**length values — only sensible for short genes."""
        return [tuple(bits) for bits in itertools.product((0, 1), repeat=self.length)]


@dataclasses.dataclass(frozen=True)
class FloatGene:
    """A bounded float hyperparameter, sampled uniformly from [minimum, maximum].

    Mirrors the (default, minimum, maximum) triples gentun attaches to each
    XGBoost hyperparameter (SURVEY.md §2.0 row 6).
    """

    name: str
    default_value: float
    minimum: float
    maximum: float
    log_scale: bool = False

    def __post_init__(self):
        if not (self.minimum <= self.default_value <= self.maximum):
            raise ValueError(f"gene {self.name!r}: default outside bounds")
        if self.log_scale and self.minimum <= 0:
            raise ValueError(f"gene {self.name!r}: log-scale needs minimum > 0")

    def default(self) -> float:
        return float(self.default_value)

    def sample(self, rng: np.random.Generator) -> float:
        if self.log_scale:
            lo, hi = math.log(self.minimum), math.log(self.maximum)
            return float(math.exp(rng.uniform(lo, hi)))
        return float(rng.uniform(self.minimum, self.maximum))

    def mutate(self, value: float, rng: np.random.Generator, rate: float) -> float:
        # Per-gene re-sample with probability `rate` (SURVEY §2.3: scalar
        # genomes mutate by random re-sample, not perturbation).
        return self.sample(rng) if rng.random() < rate else float(value)

    def validate(self, value: Any) -> float:
        value = float(value)
        if not (self.minimum <= value <= self.maximum):
            raise ValueError(f"gene {self.name!r}: {value} outside [{self.minimum}, {self.maximum}]")
        return value

    def grid_values(self, n: int = 5) -> List[float]:
        if self.log_scale:
            return [float(v) for v in np.geomspace(self.minimum, self.maximum, n)]
        return [float(v) for v in np.linspace(self.minimum, self.maximum, n)]


@dataclasses.dataclass(frozen=True)
class IntGene:
    """A bounded integer hyperparameter (inclusive bounds)."""

    name: str
    default_value: int
    minimum: int
    maximum: int

    def __post_init__(self):
        if not (self.minimum <= self.default_value <= self.maximum):
            raise ValueError(f"gene {self.name!r}: default outside bounds")

    def default(self) -> int:
        return int(self.default_value)

    def sample(self, rng: np.random.Generator) -> int:
        return int(rng.integers(self.minimum, self.maximum + 1))

    def mutate(self, value: int, rng: np.random.Generator, rate: float) -> int:
        return self.sample(rng) if rng.random() < rate else int(value)

    def validate(self, value: Any) -> int:
        value = int(value)
        if not (self.minimum <= value <= self.maximum):
            raise ValueError(f"gene {self.name!r}: {value} outside [{self.minimum}, {self.maximum}]")
        return value

    def grid_values(self, n: int = 5) -> List[int]:
        span = self.maximum - self.minimum
        n = min(n, span + 1)
        return sorted({int(round(v)) for v in np.linspace(self.minimum, self.maximum, n)})


@dataclasses.dataclass(frozen=True)
class ChoiceGene:
    """A categorical hyperparameter drawn from a fixed choice list."""

    name: str
    default_value: Any
    choices: Tuple[Any, ...]

    def __post_init__(self):
        object.__setattr__(self, "choices", tuple(self.choices))
        if self.default_value not in self.choices:
            raise ValueError(f"gene {self.name!r}: default not in choices")

    def default(self) -> Any:
        return self.default_value

    def sample(self, rng: np.random.Generator) -> Any:
        return self.choices[int(rng.integers(0, len(self.choices)))]

    def mutate(self, value: Any, rng: np.random.Generator, rate: float) -> Any:
        return self.sample(rng) if rng.random() < rate else value

    def validate(self, value: Any) -> Any:
        # JSON round-trips lists to tuples and back; normalise before checking.
        if isinstance(value, list):
            value = tuple(value)
        if value not in self.choices:
            raise ValueError(f"gene {self.name!r}: {value!r} not in {self.choices!r}")
        return value

    def grid_values(self) -> List[Any]:
        return list(self.choices)


Gene = Union[BinaryGene, FloatGene, IntGene, ChoiceGene]


class GenomeSpec:
    """An ordered, named collection of genes plus the genetic operators.

    All operators are pure: they take explicit values and an explicit
    ``numpy.random.Generator`` and return new value dicts.  ``Individual``
    wraps these with the reference's stateful API (SURVEY.md §2.0 row 5).
    """

    def __init__(self, genes: Sequence[Gene]):
        names = [g.name for g in genes]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate gene names: {names}")
        self._genes: Tuple[Gene, ...] = tuple(genes)
        self._by_name: Dict[str, Gene] = {g.name: g for g in genes}

    @property
    def genes(self) -> Tuple[Gene, ...]:
        return self._genes

    @property
    def names(self) -> List[str]:
        return [g.name for g in self._genes]

    def __len__(self) -> int:
        return len(self._genes)

    def __getitem__(self, name: str) -> Gene:
        return self._by_name[name]

    def __contains__(self, name: str) -> bool:
        return name in self._by_name

    # -- operators ---------------------------------------------------------

    def default(self) -> Dict[str, Any]:
        return {g.name: g.default() for g in self._genes}

    def sample(self, rng: np.random.Generator) -> Dict[str, Any]:
        """Random genome: Bernoulli(0.5) bits / uniform scalars (SURVEY §2.3)."""
        return {g.name: g.sample(rng) for g in self._genes}

    def crossover(
        self,
        a: Mapping[str, Any],
        b: Mapping[str, Any],
        rng: np.random.Generator,
        rate: float = 0.5,
    ) -> Dict[str, Any]:
        """Uniform crossover at *gene* granularity.

        The child takes each whole gene from parent ``b`` with probability
        ``rate``, else from parent ``a``; bits within a gene are never spliced
        (gentun ``Individual.crossover`` [PUB]; SURVEY.md §2.3).
        """
        picks = rng.random(len(self._genes)) < rate
        return {
            g.name: (b if take_b else a)[g.name]
            for g, take_b in zip(self._genes, picks)
        }

    def mutate(
        self,
        value: Mapping[str, Any],
        rng: np.random.Generator,
        rate: float = 0.015,
    ) -> Dict[str, Any]:
        """Per-bit flip (binary) / per-gene re-sample (scalar) at ``rate``.

        The 0.015 default mirrors the reference's mutation rate
        (SURVEY.md §2.3, exact constant tagged [UNCERTAIN] there).
        """
        return {g.name: g.mutate(value[g.name], rng, rate) for g in self._genes}

    def validate(self, value: Mapping[str, Any]) -> Dict[str, Any]:
        """Canonicalise and bounds-check a genome value dict (e.g. off the wire)."""
        missing = [g.name for g in self._genes if g.name not in value]
        if missing:
            raise ValueError(f"genome missing genes: {missing}")
        extra = [k for k in value if k not in self._by_name]
        if extra:
            raise ValueError(f"genome has unknown genes: {extra}")
        return {g.name: g.validate(value[g.name]) for g in self._genes}

    def grid(
        self,
        grid_sizes: Mapping[str, int] | None = None,
        gene_values: Mapping[str, Sequence[Any]] | None = None,
    ) -> List[Dict[str, Any]]:
        """Cartesian product of per-gene value grids (``GridPopulation`` init).

        Mirrors gentun's grid-of-gene-values initialisation
        (``gentun/populations.py`` [PUB]; SURVEY.md §2.0 row 4).  Per-gene
        axes come from, in priority order: an explicit value list in
        ``gene_values``, a point count in ``grid_sizes`` (numeric genes), or
        the gene's full ``grid_values()``.
        """
        grid_sizes = dict(grid_sizes or {})
        gene_values = dict(gene_values or {})
        unknown = [k for k in gene_values if k not in self._by_name]
        if unknown:
            raise ValueError(f"gene_values has unknown genes: {unknown}")
        axes: List[List[Any]] = []
        for g in self._genes:
            if g.name in gene_values:
                axes.append([g.validate(v) for v in gene_values[g.name]])
            elif isinstance(g, (FloatGene, IntGene)) and g.name in grid_sizes:
                axes.append(g.grid_values(grid_sizes[g.name]))
            else:
                axes.append(g.grid_values())
        return [dict(zip(self.names, combo)) for combo in itertools.product(*axes)]


# ---------------------------------------------------------------------------
# Canonical genomes
# ---------------------------------------------------------------------------


def genetic_cnn_genome(nodes: Sequence[int] = (3, 5)) -> GenomeSpec:
    """Genetic-CNN DAG genome: gene ``S_k`` has K_k*(K_k-1)/2 bits.

    One bit per ordered node pair (i<j) within stage k — the Xie & Yuille
    ICCV 2017 encoding the reference implements (SURVEY.md §2.3; gentun
    ``GeneticCnnIndividual`` [PUB]).  For nodes=(3, 5) the search space is
    2**(3+10) = 8192 architectures.
    """
    return GenomeSpec(
        [BinaryGene(f"S_{k + 1}", k_s * (k_s - 1) // 2) for k, k_s in enumerate(nodes)]
    )


def boosting_genome() -> GenomeSpec:
    """Hyperparameter genome for the sklearn gradient-boosting control path.

    The rebuild's equivalent of gentun's ``XgboostIndividual`` genome
    (SURVEY.md §2.0 row 6): xgboost is absent from this environment, so the
    control path targets ``sklearn.ensemble.HistGradientBoostingClassifier``
    with an equivalent bounded-hyperparameter search space.
    """
    return GenomeSpec(
        [
            FloatGene("learning_rate", 0.1, 0.001, 1.0, log_scale=True),
            IntGene("max_depth", 6, 2, 12),
            IntGene("max_leaf_nodes", 31, 4, 128),
            IntGene("min_samples_leaf", 20, 1, 100),
            FloatGene("l2_regularization", 0.0, 0.0, 10.0),
            IntGene("max_bins", 255, 16, 255),
            IntGene("max_iter", 100, 10, 300),
        ]
    )


def xgboost_genome() -> GenomeSpec:
    """The reference's XGBoost hyperparameter genome, for drop-in parity.

    Gene set and (default, min, max) bounds per gentun ``XgboostIndividual``
    (``gentun/individuals.py`` [PUB]; SURVEY.md §2.0 row 6 — exact set tagged
    [UNCERTAIN] there).  Usable with any fitness model that consumes these
    names (real xgboost is not installed here; see ``models/boosting.py``).
    """
    return GenomeSpec(
        [
            FloatGene("eta", 0.3, 0.001, 1.0, log_scale=True),
            IntGene("min_child_weight", 1, 0, 10),
            IntGene("max_depth", 6, 3, 10),
            FloatGene("gamma", 0.0, 0.0, 10.0),
            IntGene("max_delta_step", 0, 0, 10),
            FloatGene("subsample", 1.0, 0.5, 1.0),
            FloatGene("colsample_bytree", 1.0, 0.5, 1.0),
            FloatGene("colsample_bylevel", 1.0, 0.5, 1.0),
            FloatGene("lambda", 1.0, 0.0, 10.0),
            FloatGene("alpha", 0.0, 0.0, 10.0),
            FloatGene("scale_pos_weight", 1.0, 0.0, 10.0),
        ]
    )
