"""gentun_tpu — TPU-native distributed genetic-algorithm search.

A brand-new framework with the capabilities of gentun (hyperparameter and
CNN-architecture search via genetic algorithms, distributed master/worker
fitness evaluation), designed TPU-first on JAX/Flax/XLA:

- deterministic, PRNG-threaded GA engine (``genes``, ``individuals``,
  ``populations``, ``algorithms``),
- Genetic-CNN fitness as a *masked supergraph*: every genome shares one
  compiled XLA program, and whole populations train as a single vmapped
  batch (``ops``, ``models``),
- multi-chip scaling via ``jax.sharding`` meshes — population-parallel ×
  data-parallel (``parallel``),
- a master/worker job broker over TCP with at-least-once redelivery, the
  RabbitMQ-equivalent control plane (``distributed``).

Public API mirrors the reference (``gentun/__init__.py`` [PUB]; SURVEY.md
§2.0 row 1): model-dependent names are re-exported defensively so a missing
optional dependency never breaks ``import gentun_tpu``.
"""

from .genes import (
    BinaryGene,
    ChoiceGene,
    FloatGene,
    GenomeSpec,
    IntGene,
    boosting_genome,
    genetic_cnn_genome,
    xgboost_genome,
)
from .individuals import BoostingIndividual, GeneticCnnIndividual, Individual, XgboostIndividual
from .populations import GridPopulation, Population
from .algorithms import GeneticAlgorithm, RussianRouletteGA
from .algorithms_async import AsyncEvolution
from .surrogate import FitnessSurrogate, SurrogateGate
from . import telemetry  # noqa: F401  (zero-dependency; see docs/OBSERVABILITY.md)

__all__ = [
    "telemetry",
    "BinaryGene",
    "FloatGene",
    "IntGene",
    "ChoiceGene",
    "GenomeSpec",
    "genetic_cnn_genome",
    "boosting_genome",
    "xgboost_genome",
    "Individual",
    "GeneticCnnIndividual",
    "BoostingIndividual",
    "XgboostIndividual",
    "Population",
    "GridPopulation",
    "GeneticAlgorithm",
    "RussianRouletteGA",
    "AsyncEvolution",
    "FitnessSurrogate",
    "SurrogateGate",
]

__version__ = "0.6.0"  # keep in sync with pyproject.toml

# Fitness models pull in jax/flax/sklearn; keep them optional at import time,
# matching the reference's try/except around model imports (SURVEY.md §2.0
# row 1: missing xgboost/keras must not break the package import).
try:  # pragma: no cover - exercised implicitly
    from .models.cnn import GeneticCnnModel  # noqa: F401

    __all__.append("GeneticCnnModel")
except ImportError:  # pragma: no cover
    pass

try:  # pragma: no cover
    from .models.boosting import BoostingModel  # noqa: F401

    __all__.append("BoostingModel")
except ImportError:  # pragma: no cover
    pass

try:  # pragma: no cover
    from .distributed.server import DistributedPopulation, DistributedGridPopulation  # noqa: F401
    from .distributed.client import GentunClient  # noqa: F401
    from .distributed.broker import GatherTimeout, JobBroker, JobFailed  # noqa: F401

    __all__ += [
        "DistributedPopulation",
        "DistributedGridPopulation",
        "GentunClient",
        "JobBroker",
        "JobFailed",
        "GatherTimeout",
    ]
except ImportError:  # pragma: no cover
    pass
