"""Surrogate rung −1: a ledger-trained fitness ranker that gates dispatch.

PERF.md closed every single-chip compile-side lever, so the remaining
perf wins are *search efficiency per chip-hour* — and the lineage ledger
(PR 10) plus the shared fitness service (PR 7) already accumulate exactly
a surrogate's training set: genome encoding → fitness at every rung,
across runs and tenants.  This module grafts a learned predictor UNDER
the ASHA ladder (Li et al. 2020) inside the aging-evolution engine
(Real et al. 2019) as **rung −1**: every bred child is scored on the
host in microseconds, and only the top ``1/eta`` fraction (by a
rolling-window quantile of recent scores) ever touches a device at
rung 0.  Rejected children cost one lineage event and a re-breed — no
dispatch, no chip-seconds.

Two classes, both dependency-free (numpy only, already a core dep):

- :class:`FitnessSurrogate` — a tiny ridge regressor over the fixed-width
  binary stage-DAG genome encoding plus a rung feature, fit closed-form
  (``w = solve(XᵀX + λI, Xᵀy)``), refit every ``refit_every``
  completions.  Below ``min_train`` samples it refuses to score
  (``score() → None``) — the minimum-training-set gate: an untrained
  surrogate must never veto a child.
- :class:`SurrogateGate` — the rung −1 admission policy around it:
  rolling-window quantile cut, pending-decision ledger (admitted score →
  realized fitness, resolved on completion into a precision@k telemetry
  gauge), a reject-streak cap so a badly-calibrated model can only stall
  breeding for ``max_reject_streak`` draws, and an optional dataset
  plane on the shared fitness service (warm-start + refit-boundary sync)
  with fail-open degradation: a gate whose training-set sync fails
  cannot trust its score distribution, so it degrades to **admit-all**
  (exactly ONE ``surrogate_degraded`` event per up→down transition) —
  admitting everything costs chip-time, never correctness.

Every existing invariant holds: the gate is off by default and
bit-identical when off (``AsyncEvolution`` reads one attribute per site,
the PR-2 contract); ``decide``/``score`` draw no randomness, so the
gated trajectory is a pure function of (seed, ledger state); the whole
gate — model weights, training samples, score window, pending
decisions — serializes into checkpoint schema v4 so kill/resume is
bit-identical; and the dataset space key is prefixed with the session
namespace, so one tenant's surrogate never trains on (or scores)
another tenant's genomes.  See DISTRIBUTED.md "Surrogate rung −1".
"""

from __future__ import annotations

import hashlib
import json
import logging
import time
from bisect import bisect_left, insort
from collections import OrderedDict, deque
from operator import mul
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .telemetry import lineage as _lineage
from .telemetry import spans as _tele
from .telemetry.registry import get_registry as _get_registry

__all__ = ["FitnessSurrogate", "SurrogateGate", "encode_genes", "space_key"]

logger = logging.getLogger("gentun_tpu")


def _feature(value: Any) -> List[float]:
    """One gene value → its feature columns, deterministically.

    Bit tuples (the Genetic-CNN stage-DAG encoding) flatten to one 0/1
    column per bit; numeric scalars pass through; anything else (e.g. a
    string choice) contributes a stable hashed column in ``[0, 1)`` so
    the encoding is total over every genome spec.
    """
    if isinstance(value, (list, tuple)):
        out: List[float] = []
        for v in value:
            out.extend(_feature(v))
        return out
    if isinstance(value, bool):
        return [1.0 if value else 0.0]
    if isinstance(value, (int, float)):
        return [float(value)]
    h = hashlib.blake2b(repr(value).encode(), digest_size=4).digest()
    return [int.from_bytes(h, "big") / 2**32]


def encode_genes(genes: Dict[str, Any], rung: int = 0) -> List[float]:
    """Genome → fixed-width feature vector: ``[bias, *bits..., rung]``.

    Gene names are sorted, so the width and column order depend only on
    the genome spec — every genome of one search space encodes to the
    same vector length, which is what lets one ridge model score the
    whole space.  On the score-on-breed hot path (one call per bred
    child, broker_throughput surrogate gate), so the common case — flat
    bit tuples — is inlined instead of recursing per bit.
    """
    x = [1.0]
    for name in sorted(genes):
        v = genes[name]
        if type(v) in (tuple, list):
            try:
                x.extend(map(float, v))
            except (TypeError, ValueError):
                x.extend(_feature(v))
        elif type(v) in (int, float):
            x.append(float(v))
        else:
            x.extend(_feature(v))
    x.append(float(rung))
    return x


def _pending_key(genes: Dict[str, Any]) -> Any:
    """Canonical hashable identity for the pending-decision map.

    Cheaper than :func:`~gentun_tpu.telemetry.lineage.genome_key` (no
    JSON + hash round trip — ``decide`` runs once per bred child) while
    still surviving the checkpoint: tuples serialize as JSON lists and
    load back through ``tuplify``-style re-tuplification.
    """
    try:
        return tuple(
            (name, tuple(v) if type(v) in (tuple, list) else v)
            for name, v in sorted(genes.items()))
    except TypeError:  # unhashable exotic gene value — the slow, safe path
        return _lineage.genome_key(genes)


def _tuplify_key(key: Any) -> Any:
    """JSON round trip of a pending key (lists back to tuples)."""
    if isinstance(key, list):
        return tuple(_tuplify_key(v) for v in key)
    return key


def space_key(genes: Dict[str, Any], namespace: Optional[str] = None) -> str:
    """Per-tenant dataset namespace for a search space.

    Digest of the sorted gene names and their feature widths, prefixed
    by the session namespace — two tenants searching the same space
    still get disjoint dataset keys, and two spaces that merely share
    gene names but differ in width never mix training rows.
    """
    sig = [[name, len(_feature(genes[name]))] for name in sorted(genes)]
    digest = hashlib.blake2b(
        json.dumps(sig, separators=(",", ":")).encode(),
        digest_size=8).hexdigest()
    return f"{namespace or 'default'}:{digest}"


class FitnessSurrogate:
    """Closed-form ridge regressor over encoded genomes.

    Training rows live in an insertion-ordered dict keyed by
    ``(genome_key, rung)`` — re-observing a genome at the same rung
    replaces its row (latest measurement wins), and the oldest rows are
    evicted past ``max_samples``, so the model tracks the recent search
    distribution instead of ossifying on founder-era measurements
    (stale-predictor drift, ROADMAP item 3).

    ``score`` returns ``None`` until ``min_train`` rows have been seen:
    the minimum-training-set gate.  Refits fire every ``refit_every``
    observations past that — cheap (one ``d×d`` solve, d ≈ bits + 2)
    and deterministic, so the model state is a pure function of the
    observation stream.
    """

    def __init__(self, l2: float = 1e-2, min_train: int = 32,
                 refit_every: int = 32, max_samples: int = 4096):
        if min_train < 2:
            raise ValueError(f"min_train must be >= 2 (got {min_train})")
        if refit_every < 1:
            raise ValueError(f"refit_every must be >= 1 (got {refit_every})")
        self.l2 = float(l2)
        self.min_train = int(min_train)
        self.refit_every = int(refit_every)
        self.max_samples = int(max_samples)
        #: (genome_key, rung) -> (feature list, fitness)
        self._samples: "OrderedDict[Tuple[str, int], Tuple[List[float], float]]" = OrderedDict()
        self._weights: Optional[List[float]] = None
        self._since_refit = 0
        self.refits = 0

    # -- training ----------------------------------------------------------

    @property
    def trained(self) -> bool:
        return self._weights is not None

    @property
    def n_samples(self) -> int:
        return len(self._samples)

    def add_row(self, genome_key: str, x: List[float], fitness: float) -> None:
        """Insert one training row WITHOUT advancing the refit counter —
        the bulk-merge path (warm-start / dataset sync).  Re-inserting an
        existing ``(genome, rung)`` row keeps its age (no ``move_to_end``):
        merges must not let remote duplicates evict fresh local rows."""
        rung = int(x[-1]) if x else 0
        key = (str(genome_key), rung)
        if key in self._samples:
            self._samples[key] = (list(map(float, x)), float(fitness))
            return
        self._samples[key] = (list(map(float, x)), float(fitness))
        while len(self._samples) > self.max_samples:
            self._samples.popitem(last=False)

    def observe(self, genes: Dict[str, Any], rung: int, fitness: float) -> bool:
        """Feed one completed measurement; returns True when it fired a
        refit (the gate hangs its dataset sync off that boundary)."""
        x = encode_genes(genes, rung)
        self.add_row(_lineage.genome_key(genes), x, fitness)
        self._since_refit += 1
        if len(self._samples) >= self.min_train and (
                self._weights is None or self._since_refit >= self.refit_every):
            self.fit()
            return True
        return False

    def fit(self) -> None:
        """Closed-form ridge solve over the current sample set."""
        if len(self._samples) < 2:
            return
        rows = list(self._samples.values())
        X = np.asarray([x for x, _ in rows], dtype=np.float64)
        y = np.asarray([f for _, f in rows], dtype=np.float64)
        d = X.shape[1]
        A = X.T @ X + self.l2 * np.eye(d)
        try:
            w = np.linalg.solve(A, X.T @ y)
        except np.linalg.LinAlgError:  # pragma: no cover - l2 > 0 prevents
            w, *_ = np.linalg.lstsq(A, X.T @ y, rcond=None)
        self._weights = [float(v) for v in w]
        self._since_refit = 0
        self.refits += 1
        if _tele.enabled():
            _get_registry().counter("surrogate_refits_total").inc()

    # -- scoring -----------------------------------------------------------

    def score(self, genes: Dict[str, Any], rung: int = 0) -> Optional[float]:
        """Predicted fitness, or ``None`` while untrained (admit-all)."""
        w = self._weights
        if w is None:
            return None
        return self.score_x(encode_genes(genes, rung))

    def score_x(self, x: List[float]) -> Optional[float]:
        """Score an already-encoded feature vector (the gate's hot path
        encodes once and reuses the vector for the pending key)."""
        w = self._weights
        if w is None or len(x) != len(w):  # untrained, or spec changed
            return None
        # map(mul) dot: ~15 columns — cheaper than a generator expression
        # or an ndarray round trip at this width (broker_throughput gate).
        return sum(map(mul, w, x))

    # -- (de)serialization -------------------------------------------------

    def state_dict(self) -> Dict[str, Any]:
        return {
            "l2": self.l2,
            "min_train": self.min_train,
            "refit_every": self.refit_every,
            "max_samples": self.max_samples,
            "weights": self._weights,
            "samples": [[gk, rung, x, f]
                        for (gk, rung), (x, f) in self._samples.items()],
            "since_refit": self._since_refit,
            "refits": self.refits,
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.l2 = float(state.get("l2", self.l2))
        self.min_train = int(state.get("min_train", self.min_train))
        self.refit_every = int(state.get("refit_every", self.refit_every))
        self.max_samples = int(state.get("max_samples", self.max_samples))
        w = state.get("weights")
        self._weights = None if w is None else [float(v) for v in w]
        self._samples = OrderedDict(
            ((str(gk), int(rung)), ([float(v) for v in x], float(f)))
            for gk, rung, x, f in state.get("samples", []))
        self._since_refit = int(state.get("since_refit", 0))
        self.refits = int(state.get("refits", 0))


class SurrogateGate:
    """Rung −1 admission control in front of rung-0 dispatch.

    ``decide`` scores a freshly bred child and admits it when the score
    lands in the top ``1/eta`` of the last ``window`` scores (quantile
    over a bisect-maintained sorted window — O(log window) per decide,
    no percentile scan).  Until the surrogate trains, until the window
    holds ``min_window`` scores, or while degraded, every child admits —
    the gate can only ever *save* chip-time, never deadlock the breeder:
    a reject streak of ``max_reject_streak`` force-admits regardless.

    Admitted scores park in a pending map keyed by genome; when the
    measurement lands, :meth:`observe_result` resolves the pair into a
    rolling (score, fitness) buffer from which the ``surrogate_precision_at_k``
    gauge is computed — the self-measured answer to "is this model still
    worth trusting".
    """

    PRECISION_K = 8
    #: ``surrogate_score_seconds`` samples 1 decide in (mask+1): the
    #: perf_counter pair plus histogram bucketing cost more than the whole
    #: scoring step, and the broker_throughput 2% budget is per-decide.
    _SAMPLE_MASK = 15

    def __init__(self, surrogate: Optional[FitnessSurrogate] = None,
                 eta: int = 4, window: int = 64, min_window: int = 16,
                 max_reject_streak: int = 32, dataset_client=None,
                 namespace: Optional[str] = None):
        if eta < 2:
            raise ValueError(f"eta must be >= 2 (got {eta}): admitting "
                             "every child is not a gate")
        self.surrogate = surrogate if surrogate is not None else FitnessSurrogate()
        self.eta = int(eta)
        self.window = max(2, int(window))
        self.min_window = max(2, int(min_window))
        self.max_reject_streak = max(1, int(max_reject_streak))
        self.dataset_client = dataset_client
        self.namespace = str(namespace) if namespace else None
        self.maximize = True
        self.admitted = 0
        self.rejected = 0
        self.degraded = False
        self.degraded_total = 0
        self.precision_at_k: Optional[float] = None
        self._space: Optional[str] = None
        self._scores: deque = deque()   # arrival order (window eviction)
        self._sorted: List[float] = []  # same multiset, sorted (quantile)
        self._pending: Dict[str, float] = {}
        self._pairs: deque = deque(maxlen=16 * self.PRECISION_K)
        self._publish_buf: List[List[Any]] = []
        self._reject_streak = 0
        self._prepared = False
        self._metrics = None  # cached (admit, reject, seconds) handles
        self._tick = 0  # latency-histogram sampler (1 in _SAMPLE_MASK+1)

    # -- lifecycle ---------------------------------------------------------

    def prepare(self, example_genes: Dict[str, Any], maximize: bool,
                session: Optional[str] = None) -> None:
        """Bind the gate to a search: objective direction, per-tenant
        dataset space key, and (when a dataset client is attached) the
        warm-start fetch — a fresh master inherits prior runs' training
        rows from the shared fitness service.  Idempotent: a resumed or
        re-entered ``run()`` re-prepares without refetching."""
        if self._prepared:
            return
        self.maximize = bool(maximize)
        self._space = space_key(example_genes, self.namespace or session)
        self._prepared = True
        if self.dataset_client is None:
            return
        rows = self.dataset_client.fetch_dataset(
            self._space, limit=self.surrogate.max_samples)
        if rows is None:
            self._degrade("warm-start dataset fetch failed")
            return
        self._merge_rows(rows)
        if (not self.surrogate.trained
                and self.surrogate.n_samples >= self.surrogate.min_train):
            self.surrogate.fit()
        if rows:
            logger.info(
                "surrogate warm-start: %d dataset row(s) from %s (space %s)",
                len(rows), getattr(self.dataset_client, "url", "?"), self._space)

    # -- the hot path ------------------------------------------------------

    def decide(self, genes: Dict[str, Any], rung: int = 0) -> Tuple[bool, Optional[float]]:
        """Score one bred child and admit or reject it.

        Draws no randomness; the decision is a pure function of the gate
        state, so the gated trajectory stays deterministic and a
        checkpoint (window + pending map) resumes it bit-identically.
        """
        tele = _tele.enabled()
        timed = False
        if tele:
            self._tick = (self._tick + 1) & self._SAMPLE_MASK
            timed = self._tick == 0
            t0 = time.perf_counter() if timed else 0.0
        # Inlined surrogate.score: encode once, dot on the weights — the
        # method-call + double-encode round trip costs as much as scoring.
        w = self.surrogate._weights
        if w is None:
            score = None
        else:
            x = encode_genes(genes, rung)
            score = sum(map(mul, w, x)) if len(x) == len(w) else None
        admit = True
        if score is not None and not self.degraded:
            # Push first, then cut: the threshold includes this score, so
            # the window's best always admits and k = len // eta is exact.
            self._scores.append(score)
            insort(self._sorted, score)
            if len(self._scores) > self.window:
                old = self._scores.popleft()
                del self._sorted[bisect_left(self._sorted, old)]
            if len(self._sorted) >= self.min_window:
                k = max(1, len(self._sorted) // self.eta)
                if self.maximize:
                    admit = score >= self._sorted[-k]
                else:
                    admit = score <= self._sorted[k - 1]
            if not admit and self._reject_streak + 1 >= self.max_reject_streak:
                # A model rejecting everything is miscalibrated, not
                # insightful — force one through so breeding always
                # progresses and fresh measurements re-train it.
                admit = True
        if admit:
            self._reject_streak = 0
            self.admitted += 1
            self._pending[_pending_key(genes)] = (
                score if score is not None else None)
        else:
            self._reject_streak += 1
            self.rejected += 1
        if tele:
            if self._metrics is None:
                # Handles cached once per gate: one registry lock + dict
                # probe per metric per decide would dominate the hot path.
                reg = _get_registry()
                self._metrics = (
                    reg.counter("surrogate_gate_admitted_total"),
                    reg.counter("surrogate_gate_rejected_total"),
                    reg.histogram("surrogate_score_seconds"))
            self._metrics[0 if admit else 1].inc()
            if timed:
                self._metrics[2].observe(time.perf_counter() - t0)
        return admit, score

    def forget(self, genes: Dict[str, Any]) -> None:
        """Drop the pending decision for a permanently failed child —
        there will never be a realized fitness to resolve it against."""
        self._pending.pop(_pending_key(genes), None)

    # -- the feedback path -------------------------------------------------

    def observe_result(self, genes: Dict[str, Any], rung: int, fitness: float) -> None:
        """One measurement landed: train the surrogate, resolve the
        pending gate decision into the precision@k buffer, and — at refit
        boundaries with a dataset client attached — sync training rows
        with the shared fitness service."""
        if self.dataset_client is not None:
            self._publish_buf.append([
                _lineage.genome_key(genes),
                {k: list(v) if isinstance(v, tuple) else v
                 for k, v in genes.items()},
                int(rung), float(fitness)])
        refitted = self.surrogate.observe(genes, rung, fitness)
        score = self._pending.pop(_pending_key(genes), None)
        if score is not None:
            self._pairs.append([float(score), float(fitness)])
            self._update_precision()
        if refitted and self.dataset_client is not None:
            self._sync_dataset()

    def _update_precision(self) -> None:
        k = self.PRECISION_K
        if len(self._pairs) < k:
            return
        pairs = list(self._pairs)
        by_score = sorted(range(len(pairs)), key=lambda i: pairs[i][0],
                          reverse=self.maximize)[:k]
        by_actual = sorted(range(len(pairs)), key=lambda i: pairs[i][1],
                           reverse=self.maximize)[:k]
        self.precision_at_k = len(set(by_score) & set(by_actual)) / k
        if _tele.enabled():
            _get_registry().gauge("surrogate_precision_at_k").set(
                self.precision_at_k)

    # -- dataset plane (shared fitness service) ----------------------------

    def _merge_rows(self, rows: List[Any]) -> None:
        for row in rows:
            if not isinstance(row, dict):
                continue
            genes, fitness = row.get("genes"), row.get("fitness")
            if not isinstance(genes, dict) or fitness is None:
                continue
            try:
                rung = int(row.get("rung", 0))
                x = encode_genes(genes, rung)
                self.surrogate.add_row(
                    str(row.get("genome") or _lineage.genome_key(genes)),
                    x, float(fitness))
            except (TypeError, ValueError):
                continue

    def _sync_dataset(self) -> None:
        """Refit-boundary sync: push the rows measured since the last
        refit, pull the space's merged set.  Off the hot path (refits are
        every ``refit_every`` completions) and fail-open: any failure
        degrades the gate to admit-all until a sync succeeds again."""
        client, space = self.dataset_client, self._space
        if client is None or space is None:
            return
        rows_out = [{"genome": gk, "genes": genes, "rung": rung, "fitness": f}
                    for gk, genes, rung, f in self._publish_buf]
        ok = client.publish_dataset(space, rows_out) is not None
        rows_in = client.fetch_dataset(
            space, limit=self.surrogate.max_samples) if ok else None
        if rows_in is None:
            self._degrade("dataset sync with the fitness service failed")
            return
        self._publish_buf = []
        self._merge_rows(rows_in)
        self._recover()

    def _degrade(self, reason: str) -> None:
        """Admit-all until the dataset plane is consistent again: a gate
        whose training-set sync fails cannot trust its score distribution
        relative to the fleet, and admitting everything costs chip-time,
        never correctness.  Exactly ONE event per up→down transition."""
        if self.degraded:
            return
        self.degraded = True
        self.degraded_total += 1
        logger.warning(
            "surrogate gate degraded to admit-all: %s — the search "
            "continues ungated until a dataset sync succeeds", reason)
        _tele.record_event("surrogate_degraded", {"reason": reason,
                                                  "space": self._space})
        if _tele.enabled():
            _get_registry().counter("surrogate_degraded_total").inc()

    def _recover(self) -> None:
        if self.degraded:
            self.degraded = False
            logger.info("surrogate gate recovered: dataset sync succeeded, "
                        "gating resumes")

    # -- introspection -----------------------------------------------------

    def status(self) -> Dict[str, Any]:
        """The ``/statusz`` engine "surrogate" block (gentun_top panel)."""
        return {
            "trained": self.surrogate.trained,
            "samples": self.surrogate.n_samples,
            "refits": self.surrogate.refits,
            "admitted": self.admitted,
            "rejected": self.rejected,
            "pending": len(self._pending),
            "window": len(self._scores),
            "eta": self.eta,
            "degraded": self.degraded,
            "precision_at_k": self.precision_at_k,
            "space": self._space,
        }

    # -- (de)serialization (checkpoint schema v4) --------------------------

    def state_dict(self) -> Dict[str, Any]:
        return {
            "eta": self.eta,
            "window": self.window,
            "min_window": self.min_window,
            "max_reject_streak": self.max_reject_streak,
            "namespace": self.namespace,
            "maximize": self.maximize,
            "space": self._space,
            "prepared": self._prepared,
            "model": self.surrogate.state_dict(),
            "scores": list(self._scores),
            "pending": [[k, v] for k, v in self._pending.items()],
            "pairs": [list(p) for p in self._pairs],
            "publish_buf": [list(r) for r in self._publish_buf],
            "reject_streak": self._reject_streak,
            "admitted": self.admitted,
            "rejected": self.rejected,
            "degraded": self.degraded,
            "degraded_total": self.degraded_total,
            "precision_at_k": self.precision_at_k,
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.eta = int(state.get("eta", self.eta))
        self.window = int(state.get("window", self.window))
        self.min_window = int(state.get("min_window", self.min_window))
        self.max_reject_streak = int(
            state.get("max_reject_streak", self.max_reject_streak))
        ns = state.get("namespace")
        self.namespace = str(ns) if ns else None
        self.maximize = bool(state.get("maximize", True))
        self._space = state.get("space")
        self._prepared = bool(state.get("prepared", False))
        self.surrogate.load_state_dict(state.get("model", {}))
        self._scores = deque(float(s) for s in state.get("scores", []))
        self._sorted = sorted(self._scores)
        self._pending = {
            _tuplify_key(k): (None if v is None else float(v))
            for k, v in state.get("pending", [])}
        self._pairs = deque((list(p) for p in state.get("pairs", [])),
                            maxlen=16 * self.PRECISION_K)
        self._publish_buf = [list(r) for r in state.get("publish_buf", [])]
        self._reject_streak = int(state.get("reject_streak", 0))
        self.admitted = int(state.get("admitted", 0))
        self.rejected = int(state.get("rejected", 0))
        self.degraded = bool(state.get("degraded", False))
        self.degraded_total = int(state.get("degraded_total", 0))
        p = state.get("precision_at_k")
        self.precision_at_k = None if p is None else float(p)

    @classmethod
    def from_state(cls, state: Dict[str, Any],
                   dataset_client=None) -> "SurrogateGate":
        """Reconstruct a gate from checkpoint state alone — the resume
        path when the resuming constructor didn't pass ``surrogate=``
        (the checkpoint wins, like the ladder)."""
        gate = cls(dataset_client=dataset_client)
        gate.load_state_dict(state)
        return gate
