"""Horizontal broker sharding: consistent-hash session placement (ISSUE 18).

PERF.md's "control plane headroom" pins the fleet's scaling wall: ONE
broker process moves ~1-3k job round-trips/sec, and every earlier win
(wire fast path, compile cache, autoscaler) still funnels through that
single asyncio loop.  This module multiplies the ceiling horizontally
instead of optimizing the loop further: N independent ``JobBroker``
processes ("shards") share one fleet, and *sessions* — the unit of
tenancy since the multi-tenant PR — are placed on shards by consistent
hashing, so each search talks to exactly one broker and the shards never
coordinate.  Li et al. (ASHA, MLSys 2020) shows search throughput at
scale is gated by the dispatch plane, not the accelerators; Real et al.
(ICML 2017) scaled evolution precisely by removing central coordination
— sharding the broker is this codebase's version of both.

Placement rule (DISTRIBUTED.md "Horizontal broker sharding"):

- :class:`ShardRing` is a consistent-hash ring with virtual nodes.  A
  session's **home shard** is ``ring.home(session_id)`` — deterministic
  across processes (the hash is :func:`hashlib.blake2b`, never Python's
  per-process-salted ``hash``), so a master, a reconnecting master, and
  an operator's ``gentun_top`` all compute the same placement without a
  directory service.
- Adding/removing a shard moves only ~1/N of the sessions (the virtual
  nodes bound the imbalance); :class:`ShardRouter` tracks live
  placements and counts the moves (``shard_rebalances_total``).
- Everything below the session is unchanged: each shard keeps its OWN
  journal, epoch, and admission bucket, so crash safety and back-pressure
  compose with sharding for free.

:class:`ShardedBroker` is the master-side facade: the ``JobBroker`` API
subset ``DistributedPopulation`` uses, implemented over wire
:class:`~.sessions.SessionClient` connections (one per shard, lazily
dialed).  Failover rides the PR-16 reconnect/journal path — a killed
shard's sessions re-attach after restart and its journal re-adopts every
in-flight job; submits that hit the outage window retry until the
reconnect window closes.  Workers multi-home separately (one
``GentunClient`` holds a connection per shard — ``client.py``).

Single-URL deployments never reach this module's routing: a one-element
``broker_urls`` collapses to the exact host/port code path, wire
byte-identical to today (asserted by ``scripts/shard_study.py``).
"""

from __future__ import annotations

import hashlib
import threading
import time
import uuid
from bisect import bisect_right
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from ..telemetry.registry import get_registry as _get_registry

__all__ = [
    "parse_broker_urls",
    "shard_id",
    "ShardRing",
    "ShardRouter",
    "ShardedBroker",
]


def parse_broker_urls(urls: Iterable[Any]) -> List[Tuple[str, int]]:
    """Normalize a ``broker_urls`` list to ``[(host, port), ...]``.

    Accepts ``"host:port"`` strings (an optional ``tcp://`` scheme is
    tolerated) and ``(host, port)`` pairs.  Order is preserved — it is
    part of the ring identity, so every participant must pass the same
    list — and duplicates or malformed entries raise ``ValueError``
    loudly: a typo'd shard list that silently half-works would place
    sessions on brokers nobody is running.
    """
    addrs: List[Tuple[str, int]] = []
    seen = set()
    for url in urls:
        if isinstance(url, (tuple, list)) and len(url) == 2:
            host, port = str(url[0]), url[1]
        elif isinstance(url, str):
            u = url[6:] if url.startswith("tcp://") else url
            host, _, port = u.rpartition(":")
            if not host:
                raise ValueError(f"broker url {url!r} is not 'host:port'")
        else:
            raise ValueError(f"broker url {url!r} is not 'host:port' or (host, port)")
        try:
            port = int(port)
        except (TypeError, ValueError):
            raise ValueError(f"broker url {url!r} has a non-integer port")
        if not host or not 0 < port < 65536:
            raise ValueError(f"broker url {url!r} is not 'host:port'")
        key = (host, port)
        if key in seen:
            raise ValueError(f"duplicate broker url {host}:{port}")
        seen.add(key)
        addrs.append(key)
    if not addrs:
        raise ValueError("broker_urls is empty")
    return addrs


def shard_id(addr: Tuple[str, int]) -> str:
    """The canonical shard label (``"host:port"``) for an address — the
    ring member id, the ``shard_sessions{shard=...}`` label, and the
    gentun_top panel row key."""
    return f"{addr[0]}:{addr[1]}"


def _point(key: str) -> int:
    """Stable 64-bit ring coordinate.  blake2b, NOT ``hash()``: Python's
    string hash is salted per process, and two processes disagreeing on a
    session's home would split one search across two brokers."""
    return int.from_bytes(
        hashlib.blake2b(key.encode("utf-8"), digest_size=8).digest(), "big")


class ShardRing:
    """Consistent-hash ring with virtual nodes.

    Each shard owns ``vnodes`` points on a 64-bit ring; a key's home is
    the first shard point at or clockwise-after the key's own point.
    Virtual nodes smooth the arc lengths so the per-shard session load is
    near-uniform, and membership changes move only the arcs adjacent to
    the changed shard's points (~1/N of all keys).

    Routing (:meth:`home`) is a hash + ``bisect`` over a flat sorted
    array — micro-gated at ≤2% of per-job dispatch cost by
    ``scripts/broker_throughput.py::run_shard_route_gate`` (and routing
    runs per *session placement*, not per job, so the gate is a worst
    case bound).
    """

    def __init__(self, shards: Sequence[str], vnodes: int = 64):
        if vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {vnodes}")
        if not shards:
            raise ValueError("ring needs at least one shard")
        self._vnodes = int(vnodes)
        self._shards: List[str] = []
        self._points: List[int] = []
        self._owners: List[str] = []
        for shard in shards:
            self.add(str(shard))

    # -- membership --------------------------------------------------------

    @property
    def shards(self) -> List[str]:
        return list(self._shards)

    def add(self, shard: str) -> None:
        shard = str(shard)
        if shard in self._shards:
            raise ValueError(f"shard {shard!r} already on the ring")
        self._shards.append(shard)
        self._rebuild()

    def remove(self, shard: str) -> None:
        shard = str(shard)
        if shard not in self._shards:
            raise ValueError(f"shard {shard!r} not on the ring")
        self._shards.remove(shard)
        self._rebuild()

    def _rebuild(self) -> None:
        pairs = sorted(
            (_point(f"{shard}#{i}"), shard)
            for shard in self._shards
            for i in range(self._vnodes)
        )
        self._points = [p for p, _ in pairs]
        self._owners = [s for _, s in pairs]

    # -- routing -----------------------------------------------------------

    def home(self, key: str) -> str:
        """The shard owning ``key`` (deterministic across processes)."""
        if not self._points:
            raise ValueError("ring has no shards")
        i = bisect_right(self._points, _point(str(key)))
        return self._owners[i % len(self._owners)]

    def successors(self, key: str) -> List[str]:
        """Every shard in ring order starting at ``key``'s home — the
        failover *preference* order (informational: failover in this
        codebase re-attaches to the restarted home shard via its journal
        rather than migrating the session)."""
        if not self._points:
            raise ValueError("ring has no shards")
        i = bisect_right(self._points, _point(str(key)))
        out: List[str] = []
        n = len(self._owners)
        for step in range(n):
            owner = self._owners[(i + step) % n]
            if owner not in out:
                out.append(owner)
                if len(out) == len(self._shards):
                    break
        return out

    def census(self, keys: Iterable[str]) -> Dict[str, int]:
        """Keys-per-shard histogram (every shard present, even at 0) —
        the balance column of ``run_shard_curve`` and the tests'
        uniformity assertions."""
        out = {shard: 0 for shard in self._shards}
        for key in keys:
            out[self.home(key)] += 1
        return out


class ShardRouter:
    """Live placement table over a :class:`ShardRing` + its telemetry.

    Tracks which sessions this process placed where, keeps the
    ``shard_sessions{shard}`` gauges current, and counts
    ``shard_rebalances_total`` when a membership change moves a tracked
    session to a new home.  Thread-safe (placements happen from engine
    threads; membership changes from operator paths).
    """

    def __init__(self, ring: ShardRing):
        self.ring = ring
        self._lock = threading.Lock()
        self._homes: Dict[str, str] = {}

    def place(self, session_id: str) -> str:
        sid = str(session_id)
        home = self.ring.home(sid)
        with self._lock:
            self._homes[sid] = home
            self._set_gauges()
        return home

    def forget(self, session_id: str) -> None:
        with self._lock:
            if self._homes.pop(str(session_id), None) is not None:
                self._set_gauges()

    def placements(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._homes)

    def set_shards(self, shards: Sequence[str]) -> int:
        """Replace the ring membership; returns how many tracked sessions
        moved home (each move bumps ``shard_rebalances_total``).  The
        consistent-hash guarantee under test: ~1/N of sessions move when
        one of N shards changes, never a full reshuffle."""
        with self._lock:
            old = dict(self._homes)
            for shard in self.ring.shards:
                if shard not in shards:
                    self.ring.remove(shard)
            for shard in shards:
                if shard not in self.ring.shards:
                    self.ring.add(shard)
            moved = 0
            for sid in self._homes:
                home = self.ring.home(sid)
                if home != old.get(sid):
                    self._homes[sid] = home
                    moved += 1
            if moved:
                _get_registry().counter("shard_rebalances_total").inc(moved)
            self._set_gauges()
            return moved

    def _set_gauges(self) -> None:
        # Caller holds the lock.  One gauge series per shard, including
        # empty shards (a 0 reads differently from a missing row).
        reg = _get_registry()
        counts = {shard: 0 for shard in self.ring.shards}
        for home in self._homes.values():
            counts[home] = counts.get(home, 0) + 1
        for shard, n in counts.items():
            reg.gauge("shard_sessions", shard=shard).set(n)


class ShardedBroker:
    """Master-side facade: the ``JobBroker`` API over N broker shards.

    ``DistributedPopulation(broker_urls=[...])`` installs one of these as
    ``self.broker``; the engines keep calling ``submit`` / ``wait_any`` /
    ``gather`` / ``session_capacity`` exactly as against an embedded
    broker, and the facade routes every call to the owning session's home
    shard over a wire :class:`~.sessions.SessionClient` (one per shard,
    lazily dialed, ``reconnect=True`` so a shard restart re-attaches via
    the PR-16 journal path).

    Failover semantics (DISTRIBUTED.md): results and session state
    survive a shard SIGKILL — the journal re-adopts open jobs and parks
    undelivered results for re-attach.  A ``submit`` that lands IN the
    outage window retries under ``retry_window`` seconds; if the shard
    stays dead past the window the error surfaces to the engine, whose
    ``evaluate_retries`` policy decides (at-least-once end to end).
    """

    def __init__(self, broker_urls: Sequence[Any], token: Optional[str] = None,
                 timeout: float = 10.0, retry_window: float = 60.0,
                 reconnect_max_delay: float = 5.0, vnodes: int = 64):
        self._addrs = parse_broker_urls(broker_urls)
        self._by_shard = {shard_id(a): a for a in self._addrs}
        self.ring = ShardRing(list(self._by_shard), vnodes=vnodes)
        self.router = ShardRouter(self.ring)
        self._token = token
        self._timeout = float(timeout)
        self._retry_window = float(retry_window)
        self._reconnect_max_delay = float(reconnect_max_delay)
        self._lock = threading.Lock()
        self._clients: Dict[str, Any] = {}
        #: job_id -> shard label, for wait_any/gather/cancel routing.
        self._jobs: Dict[str, str] = {}
        #: sessions this facade opened (sid -> shard), re-opened lazily.
        self._sessions: Dict[str, str] = {}
        self._closed = False

    # -- plumbing ----------------------------------------------------------

    @property
    def address(self) -> tuple:
        """First shard's address — the ``broker_address`` a sharded
        master logs (the full list is :attr:`shards`)."""
        return self._addrs[0]

    @property
    def shards(self) -> List[str]:
        return list(self._by_shard)

    def _client(self, shard: str):
        with self._lock:
            client = self._clients.get(shard)
            if client is None:
                from .sessions import SessionClient

                host, port = self._by_shard[shard]
                client = SessionClient(
                    host, port, token=self._token, timeout=self._timeout,
                    reconnect=True, reconnect_window=self._retry_window,
                    reconnect_max_delay=self._reconnect_max_delay)
                self._clients[shard] = client
            return client

    def _retry(self, shard: str, fn, what: str):
        """At-least-once wrapper for one shard call: a connection error
        (shard down, mid-restart) retries until ``retry_window`` closes.
        The underlying :class:`SessionClient` redials in its reader
        thread; this loop just re-issues the request once the link is
        back.  Non-connection errors (auth, unknown session) are
        deterministic and re-raise immediately."""
        deadline = time.monotonic() + self._retry_window
        while True:
            try:
                return fn(self._client(shard))
            except (ConnectionError, OSError, TimeoutError) as e:
                if time.monotonic() >= deadline or self._closed:
                    raise
                # A client whose reconnect window expired is permanently
                # closed: drop it so the next attempt dials fresh.
                with self._lock:
                    client = self._clients.get(shard)
                    if client is not None and getattr(client, "_closed", False):
                        try:
                            client.close()
                        except OSError:
                            pass
                        self._clients.pop(shard, None)
                time.sleep(0.2)
                if time.monotonic() < deadline:
                    continue
                raise ConnectionError(f"{what} to shard {shard} failed: {e}") from e

    def _home(self, session: Optional[str]) -> str:
        from .sessions import DEFAULT_SESSION

        sid = str(session) if session else DEFAULT_SESSION
        return self._sessions.get(sid) or self.router.place(sid)

    def _ensure_session(self, session: Optional[str]) -> str:
        """Open (idempotently) the session on its home shard; returns the
        effective sid.  The implicit default session must be opened
        explicitly over the wire — the broker only lazily creates it for
        in-process submits."""
        from .sessions import DEFAULT_SESSION

        sid = str(session) if session else DEFAULT_SESSION
        if sid not in self._sessions:
            self.open_session(sid)
        return sid

    # -- JobBroker API subset ----------------------------------------------

    @staticmethod
    def new_job_id() -> str:
        return uuid.uuid4().hex

    def open_session(self, session_id: Optional[str] = None, weight: float = 1.0,
                     max_in_flight: Optional[int] = None) -> str:
        # Mint the id HERE when absent: placement needs the id before the
        # wire does (the broker-side generator would pick the shard after
        # the fact).
        sid = str(session_id) if session_id else f"s-{uuid.uuid4().hex[:12]}"
        shard = self._home(sid)
        self._retry(shard, lambda c: c.open_session(
            sid, weight=weight, max_in_flight=max_in_flight), "session_open")
        self._sessions[sid] = shard
        return sid

    def close_session(self, session_id: str) -> None:
        sid = str(session_id)
        shard = self._sessions.pop(sid, None) or self._home(sid)
        self.router.forget(sid)
        try:
            self._retry(shard, lambda c: c.close_session(sid), "session_close")
        except (ConnectionError, OSError, TimeoutError):
            pass  # teardown path: a dead shard cancels the session itself

    def submit(self, payloads: Dict[str, Dict[str, Any]],
               session: Optional[str] = None) -> None:
        sid = self._ensure_session(session)
        shard = self._sessions[sid]
        self._retry(shard, lambda c: c.submit(sid, payloads), "submit")
        for job_id in payloads:
            self._jobs[job_id] = shard

    def _jobs_by_shard(self, job_ids: Iterable[str]) -> Dict[str, List[str]]:
        groups: Dict[str, List[str]] = {}
        for j in job_ids:
            shard = self._jobs.get(str(j))
            if shard is None:
                # Unknown id (submitted by another facade / pre-restart):
                # ask every shard — at most a wasted table lookup each.
                for s in self._by_shard:
                    groups.setdefault(s, []).append(str(j))
            else:
                groups.setdefault(shard, []).append(str(j))
        return groups

    def wait_any(self, job_ids: List[str], timeout: Optional[float] = None
                 ) -> Tuple[Dict[str, float], Dict[str, str]]:
        deadline = None if timeout is None else time.monotonic() + timeout
        groups = self._jobs_by_shard(job_ids)
        if not groups:
            return {}, {}
        while True:
            for shard, ids in groups.items():
                # One session's jobs live on ONE shard, so the common case
                # is a single group and a full-timeout delegate; the
                # multi-shard case polls in short slices.
                if len(groups) == 1:
                    remaining = (None if deadline is None
                                 else max(0.0, deadline - time.monotonic()))
                    slice_t = remaining
                else:
                    slice_t = 0.05
                r, f = self._retry(
                    shard, lambda c, i=ids, t=slice_t: c.wait_any(i, timeout=t),
                    "wait_any")
                if r or f:
                    for j in list(r) + list(f):
                        self._jobs.pop(j, None)
                    return r, f
            if deadline is not None and time.monotonic() >= deadline:
                return {}, {}

    def gather(self, job_ids: List[str], timeout: Optional[float] = None
               ) -> Dict[str, float]:
        from .broker import GatherTimeout, JobFailed

        deadline = None if timeout is None else time.monotonic() + timeout
        want = set(str(j) for j in job_ids)
        results: Dict[str, float] = {}
        failures: Dict[str, str] = {}
        while want:
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                if failures:
                    break  # terminal verdict below, not a timeout
                self.cancel(list(want))
                raise GatherTimeout(
                    f"{len(want)} of {len(job_ids)} job(s) unfinished after "
                    f"{timeout}s", partial=results)
            r, f = self.wait_any(
                sorted(want),
                timeout=min(remaining, 1.0) if remaining is not None else 1.0)
            results.update(r)
            failures.update(f)
            want -= set(r) | set(f)
        if failures:
            job_id = sorted(failures)[0]
            raise JobFailed(
                f"{len(failures)} of {len(job_ids)} job(s) failed permanently "
                f"(first: {job_id}: {failures[job_id]})",
                failures=failures, partial=results)
        return results

    def cancel(self, job_ids) -> None:
        for shard, ids in self._jobs_by_shard(job_ids).items():
            try:
                self._retry(shard, lambda c, i=ids: c.cancel(i), "cancel")
            except (ConnectionError, OSError, TimeoutError):
                pass  # a dead shard's jobs die with it (requeue on restart)
            for j in ids:
                self._jobs.pop(j, None)

    def evaluate(self, payloads: Dict[str, Dict[str, Any]],
                 timeout: Optional[float] = None) -> Dict[str, float]:
        self.submit(payloads)
        return self.gather(list(payloads), timeout=timeout)

    # -- fleet/session sizing (wire ``session_stats``) ---------------------

    def _stats(self, session: Optional[str] = None,
               reset_chips: bool = False) -> Dict[str, Any]:
        sid = self._ensure_session(session)
        shard = self._sessions[sid]
        return self._retry(
            shard, lambda c: c.session_stats(sid, reset_chips=reset_chips),
            "session_stats")

    def session_capacity(self, session_id: Optional[str] = None) -> int:
        try:
            return int(self._stats(session_id).get("capacity", 0))
        except (ConnectionError, OSError, TimeoutError):
            return 0  # sizing is advisory: a dead shard sizes to zero

    def session_prefetch(self, session_id: Optional[str] = None) -> int:
        try:
            return int(self._stats(session_id).get("prefetch", 0))
        except (ConnectionError, OSError, TimeoutError):
            return 0

    def fleet_mesh_pop(self) -> int:
        """Max advertised pop axis across every REACHED shard (shards this
        facade has a session on; fleets multi-home, so any shard sees the
        same workers)."""
        out = 1
        for sid in list(self._sessions):
            try:
                out = max(out, int(self._stats(sid).get("mesh_pop", 1)))
            except (ConnectionError, OSError, TimeoutError):
                continue
        return out

    def reset_chips_seen(self) -> None:
        for sid in list(self._sessions):
            try:
                self._stats(sid, reset_chips=True)
            except (ConnectionError, OSError, TimeoutError):
                continue

    def chips_seen(self) -> int:
        """Max over shards (NOT sum: a multi-homed worker's chips appear
        on every shard it joined)."""
        out = 0
        for sid in list(self._sessions):
            try:
                out = max(out, int(self._stats(sid).get("chips", 0)))
            except (ConnectionError, OSError, TimeoutError):
                continue
        return out

    # -- lifecycle ---------------------------------------------------------

    def stop(self) -> None:
        """Close every shard connection (the facade owns no broker
        process — operators stop shard brokers directly)."""
        self._closed = True
        with self._lock:
            clients, self._clients = dict(self._clients), {}
        for client in clients.values():
            try:
                client.close()
            except OSError:
                pass
