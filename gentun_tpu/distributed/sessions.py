"""Multi-tenant search sessions: one broker, many concurrent searches.

PRs 1-7 built every plane — chaos, telemetry, async engine, pipelined
dispatch, live ops, ASHA, elastic fleet + shared fitness cache — under the
assumption that exactly ONE search owns the broker.  This module removes
that assumption, the system shape ASHA (Li et al., MLSys 2020) was built
for: many concurrent tuning jobs sharing one elastic worker pool (Real et
al., ICML 2017 likewise ran many evolution experiments against one fleet).

Three pieces, all consumed by ``broker.JobBroker``:

- :class:`SessionRegistry` / :class:`SearchSession` — the tenant table.
  Old single-tenant masters never touch it: their jobs ride an IMPLICIT
  default session (:data:`DEFAULT_SESSION`) that is created lazily on
  first untagged submit, keeping every pre-session code path — and wire
  frame — byte-identical.  Tenants attach in-process via
  ``JobBroker.open_session`` / ``DistributedPopulation(session=...)`` or
  over the wire via the OPTIONAL client-role messages (protocol.py
  "Session messages").
- :class:`FairShareScheduler` — a weighted deficit-round-robin queue that
  replaces the broker's single FIFO deque.  Unit job cost (every job is
  one evaluation slot), per-session weights (a weight-2 tenant gets 2× the
  dispatch share of a weight-1 tenant while both are backlogged), and
  work-conservation (an idle tenant's share flows to the backlogged ones
  instead of going unused).  With a single active session it degenerates
  to exactly the old FIFO order.
- :class:`SessionClient` — a blocking TCP client for the wire session
  messages, used by out-of-process tenants (and the session tests): open
  a session, submit tagged jobs, receive results/failures for your own
  session only.

Poison-genome isolation lives in the registry: a genome whose evaluation
terminally fails ``quarantine_after`` times within one session is
quarantined FOR THAT SESSION — later submits of it fail instantly without
touching a worker — while other sessions keep their own independent
verdicts (a genome that crashes tenant A's species may be perfectly fine
for tenant B's).
"""

from __future__ import annotations

import socket
import threading
import time
import uuid
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Set, Tuple

from ..telemetry import lineage as _lineage
from ..telemetry import spans as _tele
from ..telemetry.registry import get_registry as _get_registry
from .protocol import MAX_MESSAGE_BYTES, AuthError, decode, encode

__all__ = [
    "DEFAULT_SESSION",
    "SearchSession",
    "SessionRegistry",
    "FairShareScheduler",
    "SessionClient",
    "UnknownSessionError",
    "AdmissionRejected",
    "genome_key",
]

#: The implicit single-tenant session.  Jobs submitted without a session
#: ride it, its frames carry NO session field (byte-identical to the
#: pre-session wire format), and it is created lazily — so a broker that
#: only ever serves tenant sessions never counts it as a capacity sharer.
DEFAULT_SESSION = "default"


class UnknownSessionError(ValueError):
    """A submit named a session that was never opened, or one already
    closed.  Loud by design (satellite of ISSUE 8): silently dropping a
    mis-addressed job would strand its ``gather``/``wait_any`` forever."""


class AdmissionRejected(RuntimeError):
    """The broker refused a ``session_open``/``submit`` under admission
    control (ISSUE 16): the fleet is saturated or this tenant exceeded
    its token-bucket rate.  The 429-style contract: back off for
    :attr:`retry_after_s` seconds, then retry the SAME request — nothing
    was enqueued, so the retry is side-effect-free."""

    def __init__(self, reason: str, retry_after_s: float):
        super().__init__(f"admission rejected ({reason}); "
                         f"retry after {retry_after_s:.3g}s")
        self.reason = reason
        self.retry_after_s = retry_after_s


# Content address for a genome — canonical implementation now lives with
# the forensics plane (the lineage ledger keys on the same identity the
# quarantine table always used); re-exported here for every existing
# import site.
genome_key = _lineage.genome_key


class SearchSession:
    """One tenant's state: identity, fair-share weight, quota, books.

    Mutated from the broker loop thread (counters, quarantine) and read
    as snapshots from master/HTTP threads — the same discipline as
    ``_Worker``.  ``owner`` is the asyncio writer of the wire client
    currently attached (None for in-process tenants and detached wire
    tenants); results for a remote session are forwarded to it, or parked
    in ``undelivered`` (bounded) until re-attach.
    """

    __slots__ = ("session_id", "weight", "max_in_flight", "remote", "closed",
                 "created_at", "submitted", "completed", "failed", "rejected",
                 "requeued", "poison_counts", "quarantine", "owner",
                 "undelivered", "tag")

    def __init__(self, session_id: str, weight: float = 1.0,
                 max_in_flight: Optional[int] = None, remote: bool = False,
                 tag: Optional[str] = None):
        self.session_id = session_id
        self.weight = max(1e-6, float(weight))
        self.max_in_flight = None if max_in_flight is None else max(1, int(max_in_flight))
        self.remote = remote
        #: Free-form classification ("canary" ⇒ the broker keeps this
        #: session out of tenant-facing SLI series).  Not journaled: a
        #: tagged session is transient by design and reopens fresh after
        #: a broker restart.
        self.tag = str(tag) if tag else None
        self.closed = False
        self.created_at = time.monotonic()
        self.submitted = 0
        self.completed = 0
        self.failed = 0
        self.rejected = 0
        self.requeued = 0
        #: genome_key -> terminal-failure count within THIS session.
        self.poison_counts: Dict[str, int] = {}
        #: genome keys this session refuses to dispatch again.
        self.quarantine: Set[str] = set()
        self.owner = None
        self.undelivered: Deque[Dict[str, Any]] = deque(maxlen=10_000)

    def record_terminal_failure(self, gk: Optional[str],
                                quarantine_after: int,
                                force_quarantine: bool = False) -> bool:
        """Book one terminal evaluation failure against this session.

        Bumps ``failed`` and the genome's poison count; at
        ``quarantine_after`` failures (or immediately under
        ``force_quarantine`` — the crash-isolation path) the genome is
        quarantined for THIS session, surfacing as the
        ``session_quarantined_total`` counter, a ``genome_quarantined``
        telemetry event, and a ``quarantined`` lineage ledger entry.
        Returns whether the genome was NEWLY quarantined.  Called from the
        broker loop thread (the same single-writer discipline as the rest
        of the books).
        """
        self.failed += 1
        if gk is None:
            return False
        n = self.poison_counts.get(gk, 0) + 1
        self.poison_counts[gk] = n
        hit = force_quarantine or n >= quarantine_after
        if not hit or gk in self.quarantine:
            return False
        self.quarantine.add(gk)
        _get_registry().counter("session_quarantined_total",
                                session=self.session_id).inc()
        _tele.record_event("genome_quarantined", {
            "session": self.session_id, "genome": gk, "terminal_failures": n,
            "forced_by_crash": bool(force_quarantine),
        })
        _lineage.record("quarantined", gk, session=self.session_id,
                        terminal_failures=n,
                        forced_by_crash=bool(force_quarantine))
        return True

    def snapshot(self, in_flight: int = 0, queued: int = 0) -> Dict[str, Any]:
        snap = {
            "session": self.session_id,
            "weight": self.weight,
            "max_in_flight": self.max_in_flight,
            "remote": self.remote,
            "closed": self.closed,
            "submitted": self.submitted,
            "completed": self.completed,
            "failed": self.failed,
            "rejected": self.rejected,
            "requeued": self.requeued,
            "quarantined": len(self.quarantine),
            "in_flight": in_flight,
            "queued": queued,
        }
        if self.tag is not None:
            snap["tag"] = self.tag
        return snap


class SessionRegistry:
    """The tenant table.  All methods are thread-safe (one lock around a
    dict); the broker loop holds no session references across awaits, so
    the lock is never contended for long."""

    def __init__(self, quarantine_after: int = 3):
        self._lock = threading.Lock()
        self._sessions: Dict[str, SearchSession] = {}
        self.quarantine_after = max(1, int(quarantine_after))

    def open(self, session_id: Optional[str] = None, weight: float = 1.0,
             max_in_flight: Optional[int] = None,
             remote: bool = False, tag: Optional[str] = None) -> SearchSession:
        """Create a session, or ATTACH to an existing open one (idempotent
        — re-opening updates weight/quota in place, so a reconnecting
        tenant re-asserts its priority).  Re-opening a CLOSED id raises:
        its quarantine verdicts and books are gone, and silently recycling
        the name would mis-attribute them."""
        sid = str(session_id) if session_id else uuid.uuid4().hex[:12]
        with self._lock:
            sess = self._sessions.get(sid)
            if sess is not None:
                if sess.closed:
                    raise UnknownSessionError(f"session {sid!r} is closed")
                sess.weight = max(1e-6, float(weight))
                sess.max_in_flight = (None if max_in_flight is None
                                      else max(1, int(max_in_flight)))
                if tag is not None:
                    sess.tag = str(tag)
                return sess
            sess = SearchSession(sid, weight=weight,
                                 max_in_flight=max_in_flight, remote=remote,
                                 tag=tag)
            self._sessions[sid] = sess
            return sess

    def ensure_default(self) -> SearchSession:
        """The implicit session, created on first untagged submit only —
        so a broker serving explicit tenants never counts "default" as a
        capacity sharer."""
        with self._lock:
            sess = self._sessions.get(DEFAULT_SESSION)
            if sess is None:
                sess = SearchSession(DEFAULT_SESSION)
                self._sessions[DEFAULT_SESSION] = sess
            return sess

    def peek(self, session_id: str) -> Optional[SearchSession]:
        with self._lock:
            return self._sessions.get(session_id)

    def close(self, session_id: str) -> Optional[SearchSession]:
        """Mark closed (no new submits; excluded from capacity shares).
        The broker cancels the session's open jobs separately."""
        with self._lock:
            sess = self._sessions.get(session_id)
            if sess is not None:
                sess.closed = True
                sess.owner = None
            return sess

    def weight(self, session_id: str) -> float:
        with self._lock:
            sess = self._sessions.get(session_id)
            return sess.weight if sess is not None else 1.0

    def list(self) -> List[SearchSession]:
        with self._lock:
            return list(self._sessions.values())

    def open_sessions(self) -> List[SearchSession]:
        with self._lock:
            return [s for s in self._sessions.values() if not s.closed]


class FairShareScheduler:
    """Weighted deficit round-robin over per-session FIFO queues.

    Unit job cost: each dispatch slot costs one deficit credit.  When no
    backlogged+eligible session holds a full credit, every candidate is
    replenished by ``weight / min(candidate weights)`` — so the lightest
    candidate gains exactly 1 per round and a weight-2 session gains 2,
    yielding 2:1 dispatch shares while both stay backlogged.  A session
    whose queue empties forfeits its deficit (work conservation: you
    cannot bank priority while idle), and with ONE active session the
    scheduler is exactly the old single FIFO deque.

    Not thread-safe by itself — owned by the broker loop thread, exactly
    like the deque it replaces.  ``depth``/``session_depth``/``queued``
    are len()/membership snapshot reads, safe from any thread.
    """

    def __init__(self, weight_of: Callable[[str], float]):
        self._weight_of = weight_of
        self._queues: Dict[str, Deque[str]] = {}
        self._order: Deque[str] = deque()  # rotation over backlogged sessions
        self._deficit: Dict[str, float] = {}
        self._session_of: Dict[str, str] = {}  # job_id -> session

    def push(self, session_id: str, job_id: str) -> None:
        q = self._queues.get(session_id)
        if q is None:
            q = self._queues[session_id] = deque()
        if not q:
            self._order.append(session_id)
            self._deficit.setdefault(session_id, 0.0)
        q.append(job_id)
        self._session_of[job_id] = session_id

    def _drop_session(self, sid: str) -> None:
        self._queues.pop(sid, None)
        self._deficit.pop(sid, None)
        try:
            self._order.remove(sid)
        except ValueError:
            pass

    def pop_next(
        self,
        eligible: Callable[[str], bool],
        valid: Callable[[str], bool],
        placeable: Optional[Callable[[str], bool]] = None,
    ) -> Optional[Tuple[str, str]]:
        """The next ``(session, job_id)`` to dispatch, or None when every
        backlogged session is ineligible (quota) or nothing is queued.

        ``valid`` filters dead jobs (cancelled while queued): invalid ids
        are discarded WITHOUT charging the session's deficit — a cancelled
        job must not cost its tenant a dispatch turn.

        ``placeable`` (optional) is the placement-aware dispatch filter
        (broker ``_dispatch``): a job whose head-of-queue id fails it is
        NOT popped — it stays queued, exactly where it was, and the
        session sits this call out (no deficit charge, no rotation); the
        pop moves on to other sessions.  Head-of-line, not scan-the-queue,
        deliberately: intra-session dispatch order stays strictly FIFO,
        which is what keeps requeue/dedup reasoning simple, and the cost
        of a blocked head is bounded — the next mixed-fleet dispatch pass
        offers the head to the other placement class.  ``placeable=None``
        is byte-for-byte the pre-placement behavior.
        """
        blocked: Set[str] = set()
        while True:
            candidates = [sid for sid in self._order
                          if sid not in blocked
                          and self._queues.get(sid) and eligible(sid)]
            if not candidates:
                return None
            chosen = next((sid for sid in candidates
                           if self._deficit.get(sid, 0.0) >= 1.0), None)
            if chosen is None:
                # Replenish one quantum, normalized so the lightest
                # candidate gains exactly 1 — guarantees progress without
                # letting a heavy session burst more than its ratio.
                min_w = min(max(1e-6, self._weight_of(sid)) for sid in candidates)
                for sid in candidates:
                    self._deficit[sid] = (self._deficit.get(sid, 0.0)
                                          + max(1e-6, self._weight_of(sid)) / min_w)
                continue
            q = self._queues[chosen]
            while q:
                # Peek-then-pop: a valid-but-unplaceable head must stay
                # queued (it is NOT cancelled, just wrong for this worker),
                # where invalid heads are popped and discarded exactly as
                # before — peek+pop is equivalent to pop for those paths.
                job_id = q[0]
                if not valid(job_id):
                    q.popleft()
                    self._session_of.pop(job_id, None)
                    continue  # cancelled while queued: free, no deficit cost
                if placeable is not None and not placeable(job_id):
                    blocked.add(chosen)
                    break  # head pinned elsewhere: session waits, queue intact
                q.popleft()
                self._session_of.pop(job_id, None)
                self._deficit[chosen] -= 1.0
                # Rotate the served session to the back so equal-weight
                # tenants interleave instead of draining one at a time.
                try:
                    self._order.remove(chosen)
                except ValueError:  # pragma: no cover - defensive
                    pass
                if q:
                    self._order.append(chosen)
                else:
                    self._drop_session(chosen)
                return chosen, job_id
            if chosen in blocked:
                continue
            # Queue emptied without a valid job: forfeit deficit, retry.
            self._drop_session(chosen)

    def remove(self, job_ids: Set[str]) -> None:
        """Withdraw queued jobs (cancel path).  Eager rebuild of only the
        affected sessions' queues — queues are one generation deep."""
        affected: Set[str] = set()
        for job_id in job_ids:
            sid = self._session_of.pop(job_id, None)
            if sid is not None:
                affected.add(sid)
        for sid in affected:
            q = self._queues.get(sid)
            if q is None:
                continue
            kept = deque(j for j in q if j not in job_ids)
            if kept:
                self._queues[sid] = kept
            else:
                self._drop_session(sid)

    def clear_session(self, session_id: str) -> List[str]:
        """Drop every queued job of one session (close path); returns the
        withdrawn job ids."""
        q = self._queues.get(session_id)
        ids = list(q) if q else []
        for job_id in ids:
            self._session_of.pop(job_id, None)
        self._drop_session(session_id)
        return ids

    def queued(self, job_id: str) -> bool:
        return job_id in self._session_of

    def depth(self) -> int:
        return len(self._session_of)

    def session_depth(self, session_id: str) -> int:
        q = self._queues.get(session_id)
        return len(q) if q else 0


class SessionClient:
    """Blocking TCP client for the wire session messages (protocol.py
    "Session messages"): an out-of-process tenant's handle on a shared
    broker.

    One socket, one background reader thread collecting ``results`` /
    ``fail`` / ``error`` frames into a condition-guarded table —
    :meth:`wait_any` mirrors ``JobBroker.wait_any`` semantics so tenant
    code reads the same whichever side of the wire it runs on.

    With ``reconnect=True`` (ISSUE 16) a dropped connection — a broker
    crash/restart, a cut link — is not fatal: the reader thread redials
    under the same capped decorrelated backoff the worker client uses,
    re-handshakes, and re-opens every session this client had open
    (``session_open`` with an existing id is the broker's idempotent
    re-attach, which also flushes any results that parked broker-side
    during the gap).  Only jobs submitted DURING the outage are lost to
    the caller (``submit`` raises), matching at-least-once semantics.

    With ``broker_urls=[...]`` (ISSUE 18, horizontal sharding) the client
    becomes a ROUTER over N broker shards: each session is homed on
    ``ShardRing.home(session_id)`` and every call for that session goes to
    one lazily-dialed child ``SessionClient`` per shard.  A one-element
    ``broker_urls`` collapses to the plain single-socket path — wire
    byte-identical to passing ``host``/``port`` directly (asserted by
    ``scripts/shard_study.py``).
    """

    def __init__(self, host: Optional[str] = None, port: int = 0,
                 token: Optional[str] = None,
                 timeout: float = 10.0, reconnect: bool = False,
                 reconnect_window: float = 60.0,
                 reconnect_max_delay: float = 5.0,
                 broker_urls: Optional[list] = None):
        if broker_urls:
            from .shard import ShardRing, ShardRouter, parse_broker_urls, shard_id

            if host is not None:
                raise ValueError("pass host/port OR broker_urls, not both")
            addrs = parse_broker_urls(broker_urls)
            if len(addrs) == 1:
                # Single-URL deployment: fall through to the exact
                # host/port path below — no ring, no router, no behavior
                # or wire-byte difference from today.
                host, port = addrs[0]
            else:
                self.host, self.port, self.token = None, 0, token
                self._timeout = float(timeout)
                self._reconnect = bool(reconnect)
                self._reconnect_window = float(reconnect_window)
                self._reconnect_max_delay = float(reconnect_max_delay)
                self._by_shard = {shard_id(a): a for a in addrs}
                self._ring = ShardRing(list(self._by_shard))
                self._router = ShardRouter(self._ring)
                self._children: Dict[str, "SessionClient"] = {}
                self._child_lock = threading.Lock()
                #: session -> home shard label (router placements).
                self._session_home: Dict[str, str] = {}
                #: job -> home shard label, for wait_any/cancel routing.
                self._job_home: Dict[str, str] = {}
                self._user_closed = False
                return
        elif host is None:
            raise TypeError("SessionClient needs host/port or broker_urls")
        self._ring = None  # single-broker mode marker
        self.host, self.port, self.token = host, int(port), token
        self._timeout = float(timeout)
        self._reconnect = bool(reconnect)
        self._reconnect_window = float(reconnect_window)
        self._reconnect_max_delay = float(reconnect_max_delay)
        self._sock = socket.create_connection((host, int(port)), timeout=timeout)
        self._sock.settimeout(None)
        self._rfile = self._sock.makefile("rb")
        self._wlock = threading.Lock()
        self._cond = threading.Condition()
        self._results: Dict[str, float] = {}
        self._failures: Dict[str, str] = {}
        self._errors: Deque[Dict[str, Any]] = deque(maxlen=100)
        #: monotonically counts error frames ever parked — lets a reply
        #: wait ignore stale errors from earlier (async) submits.
        self._error_seq = 0
        self._replies: Deque[Dict[str, Any]] = deque()
        self._closed = False
        self._user_closed = False
        #: sessions this client opened (id -> (weight, max_in_flight, tag))
        #: — the re-attach worklist after a broker restart.
        self._sessions: Dict[str, Tuple[float, Optional[int], Optional[str]]] = {}
        self._send({"type": "hello", "role": "client", "token": token})
        reply = self._recv_direct()
        if reply.get("type") != "welcome":
            if reply.get("type") == "error" and reply.get("code") == "auth":
                raise AuthError(f"broker rejected client: {reply.get('reason')}")
            raise ConnectionError(f"broker rejected client: {reply}")
        #: broker boot epoch (OPTIONAL on welcome; journaled brokers only).
        self._boot_id: Optional[str] = reply.get("boot_id")
        self._reader = threading.Thread(target=self._read_loop,
                                        name="gentun-session-client", daemon=True)
        self._reader.start()

    # -- plumbing ----------------------------------------------------------

    def _send(self, msg: Dict[str, Any]) -> None:
        with self._wlock:
            self._sock.sendall(encode(msg))

    def _recv_direct(self) -> Dict[str, Any]:
        line = self._rfile.readline(MAX_MESSAGE_BYTES + 2)
        if not line:
            raise ConnectionError("broker closed connection")
        return decode(line)

    def _park(self, msg: Dict[str, Any]) -> None:
        """File one inbound frame into the cond-guarded tables.  Caller
        holds ``self._cond``."""
        mtype = msg.get("type")
        if mtype == "results":
            for entry in msg.get("results", ()):
                try:
                    self._results[str(entry["job_id"])] = float(entry["fitness"])
                except (KeyError, TypeError, ValueError):
                    continue
        elif mtype == "fail":
            self._failures[str(msg.get("job_id"))] = str(msg.get("reason", "unknown"))
        elif mtype == "error":
            self._errors.append(msg)
            self._error_seq += 1
        else:  # session_ok and friends
            self._replies.append(msg)

    def _read_loop(self) -> None:
        while True:
            try:
                while True:
                    msg = self._recv_direct()
                    with self._cond:
                        self._park(msg)
                        self._cond.notify_all()
            except (ConnectionError, OSError, ValueError):
                pass
            if self._user_closed or not self._reconnect or not self._reattach():
                with self._cond:
                    self._closed = True
                    self._cond.notify_all()
                return

    def _reattach(self) -> bool:
        """Redial + re-handshake + re-open tracked sessions after the
        connection dropped.  Runs ON the reader thread (no concurrent
        reader exists), so the handshake reads frames directly; any
        ``results`` flushed from broker-side parking while we wait for
        our ``session_ok`` acks are filed into the tables, not dropped.
        True ⇔ the client is live again."""
        from .client import _ReconnectBackoff

        backoff = _ReconnectBackoff(base=0.05,
                                    cap=self._reconnect_max_delay,
                                    seed=f"{self.host}:{self.port}:client")
        deadline = time.monotonic() + self._reconnect_window
        while not self._user_closed and time.monotonic() < deadline:
            try:
                sock = socket.create_connection((self.host, self.port),
                                                timeout=self._timeout)
                sock.settimeout(self._timeout)
                rfile = sock.makefile("rb")
                try:
                    sock.sendall(encode({"type": "hello", "role": "client",
                                         "token": self.token}))
                    reply = decode(rfile.readline(MAX_MESSAGE_BYTES + 2)
                                   or b'{"type":"error"}')
                    if reply.get("type") != "welcome":
                        if (reply.get("type") == "error"
                                and reply.get("code") == "admission"):
                            # Saturated broker: honor the 429 contract.
                            time.sleep(min(
                                float(reply.get("retry_after_s") or 1.0),
                                max(0.0, deadline - time.monotonic())))
                            continue
                        return False  # auth/protocol rejection — permanent
                    for sid, (weight, mif, tag) in list(self._sessions.items()):
                        msg: Dict[str, Any] = {"type": "session_open",
                                               "session": sid,
                                               "weight": float(weight)}
                        if mif is not None:
                            msg["max_in_flight"] = int(mif)
                        if tag is not None:
                            msg["tag"] = str(tag)
                        sock.sendall(encode(msg))
                        while True:  # drain until THIS re-attach acks
                            m = decode(rfile.readline(MAX_MESSAGE_BYTES + 2)
                                       or b"")
                            if m.get("type") == "session_ok":
                                break
                            if (m.get("type") == "error"
                                    and m.get("code") == "session"
                                    and m.get("session") == sid):
                                # The id is closed server-side (our
                                # session_close ack died with the link):
                                # nothing to re-open, stop tracking it.
                                self._sessions.pop(sid, None)
                                break
                            with self._cond:
                                self._park(m)
                                self._cond.notify_all()
                except Exception:
                    try:
                        sock.close()
                    except OSError:
                        pass
                    raise
                sock.settimeout(None)
                with self._wlock:
                    old = self._sock
                    self._sock, self._rfile = sock, rfile
                try:
                    old.close()
                except OSError:
                    pass
                self._boot_id = reply.get("boot_id")
                with self._cond:
                    self._cond.notify_all()
                return True
            except (ConnectionError, OSError, ValueError):
                time.sleep(min(backoff.next_delay(),
                               max(0.0, deadline - time.monotonic())))
        return False

    def _await_reply(self, rtype: str, timeout: float = 10.0,
                     since: int = 0, session: Optional[str] = None
                     ) -> Dict[str, Any]:
        """Wait for a ``rtype`` frame.  Only error frames parked AFTER
        ``since`` (the error-seq snapshot taken before the request was
        sent) and addressed to ``session`` can fail the wait — stale
        errors from earlier fire-and-forget submits stay in the
        :meth:`last_error` buffer where they belong."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while True:
                for i, msg in enumerate(self._replies):
                    if msg.get("type") == rtype:
                        del self._replies[i]
                        return msg
                if self._error_seq > since:
                    fresh = list(self._errors)[-(self._error_seq - since):]
                    for msg in fresh:
                        if (msg.get("code") == "session"
                                and (session is None
                                     or msg.get("session") == session)):
                            raise UnknownSessionError(str(msg.get("reason")))
                        if (msg.get("code") == "admission"
                                and (session is None
                                     or msg.get("session") == session)):
                            raise AdmissionRejected(
                                str(msg.get("reason", "saturated")),
                                float(msg.get("retry_after_s") or 1.0))
                if self._closed:
                    raise ConnectionError("broker connection lost")
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(f"no {rtype!r} reply within {timeout}s")
                self._cond.wait(timeout=min(remaining, 0.5))

    # -- shard routing (ISSUE 18) ------------------------------------------

    def _child(self, shard: str) -> "SessionClient":
        """The lazily-dialed child client for one shard (router mode).  A
        child whose reconnect window expired is permanently closed — drop
        it so the next call dials fresh (the shard may be back by now)."""
        with self._child_lock:
            child = self._children.get(shard)
            if child is not None and child._closed and not child._user_closed:
                try:
                    child.close()
                except OSError:
                    pass
                child = None
            if child is None:
                host, port = self._by_shard[shard]
                child = SessionClient(
                    host, port, token=self.token, timeout=self._timeout,
                    reconnect=self._reconnect,
                    reconnect_window=self._reconnect_window,
                    reconnect_max_delay=self._reconnect_max_delay)
                self._children[shard] = child
            return child

    def _home_of(self, session_id: str) -> str:
        sid = str(session_id)
        home = self._session_home.get(sid)
        if home is None:
            home = self._router.place(sid)
            self._session_home[sid] = home
        return home

    def _jobs_by_shard(self, job_ids: List[str]) -> Dict[str, List[str]]:
        groups: Dict[str, List[str]] = {}
        for j in job_ids:
            shard = self._job_home.get(str(j))
            if shard is None:
                # Unknown id (another client submitted it): ask every
                # DIALED shard — at most a wasted table lookup each.
                with self._child_lock:
                    dialed = list(self._children)
                for s in dialed or list(self._by_shard):
                    groups.setdefault(s, []).append(str(j))
            else:
                groups.setdefault(shard, []).append(str(j))
        return groups

    # -- tenant API --------------------------------------------------------

    def open_session(self, session_id: Optional[str] = None, weight: float = 1.0,
                     max_in_flight: Optional[int] = None,
                     tag: Optional[str] = None) -> str:
        if self._ring is not None:
            # Mint the id client-side when absent: placement needs the id
            # before the wire does.
            sid = str(session_id) if session_id else f"s-{uuid.uuid4().hex[:12]}"
            self._child(self._home_of(sid)).open_session(
                sid, weight=weight, max_in_flight=max_in_flight, tag=tag)
            return sid
        msg: Dict[str, Any] = {"type": "session_open", "weight": float(weight)}
        if session_id:
            msg["session"] = str(session_id)
        if max_in_flight is not None:
            msg["max_in_flight"] = int(max_in_flight)
        if tag is not None:
            # OPTIONAL classification ("canary"): the broker keeps tagged
            # sessions out of tenant-facing SLI series.  Absent ⇒ the frame
            # is byte-identical to the pre-tag protocol.
            msg["tag"] = str(tag)
        with self._cond:
            since = self._error_seq
        self._send(msg)
        sid = str(self._await_reply(
            "session_ok", since=since,
            session=str(session_id) if session_id else None)["session"])
        self._sessions[sid] = (float(weight), None if max_in_flight is None
                               else int(max_in_flight),
                               str(tag) if tag is not None else None)
        return sid

    def close_session(self, session_id: str) -> None:
        if self._ring is not None:
            sid = str(session_id)
            shard = self._session_home.pop(sid, None)
            self._router.forget(sid)
            if shard is not None:
                self._child(shard).close_session(sid)
            return
        with self._cond:
            since = self._error_seq
        self._send({"type": "session_close", "session": str(session_id)})
        self._await_reply("session_ok", since=since, session=str(session_id))
        self._sessions.pop(str(session_id), None)

    def detach(self, session_id: str) -> None:
        """Stop receiving this session's results (they park broker-side in
        the session's bounded undelivered queue until someone re-attaches)."""
        if self._ring is not None:
            self._child(self._home_of(session_id)).detach(session_id)
            return
        with self._cond:
            since = self._error_seq
        self._send({"type": "session_detach", "session": str(session_id)})
        self._await_reply("session_ok", since=since, session=str(session_id))

    def submit(self, session_id: str, payloads: Dict[str, Dict[str, Any]]) -> List[str]:
        """Ship jobs into a session; returns the job ids (caller-supplied
        keys).  A rejected session surfaces via :meth:`wait_any` failures
        or :meth:`last_error` — the error frame is asynchronous."""
        if self._ring is not None:
            shard = self._home_of(session_id)
            ids = self._child(shard).submit(session_id, payloads)
            for j in ids:
                self._job_home[j] = shard
            return ids
        jobs = [{"job_id": job_id, **payload} for job_id, payload in payloads.items()]
        self._send({"type": "submit", "session": str(session_id), "jobs": jobs})
        return [str(j["job_id"]) for j in jobs]

    def cancel(self, job_ids: List[str]) -> None:
        """Best-effort cancel of not-yet-dispatched jobs (the broker's
        ``cancel`` frame; fire-and-forget, like the in-process call)."""
        if self._ring is not None:
            for shard, ids in self._jobs_by_shard(job_ids).items():
                try:
                    self._child(shard).cancel(ids)
                except (ConnectionError, OSError):
                    continue  # a dead shard's queue dies with it
                for j in ids:
                    self._job_home.pop(j, None)
            return
        self._send({"type": "cancel", "jobs": [str(j) for j in job_ids]})

    def session_stats(self, session_id: Optional[str] = None,
                      reset_chips: bool = False) -> Dict[str, Any]:
        """The broker's sizing snapshot for one session (the OPTIONAL
        ``session_stats`` wire message, ISSUE 18): ``capacity`` and
        ``prefetch`` are the session's weighted fleet share; ``mesh_pop``
        and ``chips`` are fleet-wide facts.  ``reset_chips=True`` starts a
        fresh chips-seen observation window broker-side first."""
        if self._ring is not None:
            sid = str(session_id) if session_id else DEFAULT_SESSION
            return self._child(self._home_of(sid)).session_stats(
                sid, reset_chips=reset_chips)
        msg: Dict[str, Any] = {"type": "session_stats"}
        if session_id:
            msg["session"] = str(session_id)
        if reset_chips:
            msg["reset_chips"] = True
        with self._cond:
            since = self._error_seq
        self._send(msg)
        return self._await_reply(
            "session_stats", since=since,
            session=str(session_id) if session_id else None)

    def wait_any(self, job_ids: List[str], timeout: Optional[float] = None
                 ) -> Tuple[Dict[str, float], Dict[str, str]]:
        """Block until ≥1 of ``job_ids`` is terminal; ``(results, failures)``
        drained from the client table (same contract as the broker's)."""
        if self._ring is not None:
            return self._wait_any_routed(job_ids, timeout)
        deadline = None if timeout is None else time.monotonic() + timeout
        want = set(job_ids)
        with self._cond:
            while True:
                done_r = {j: self._results.pop(j) for j in list(want)
                          if j in self._results}
                done_f = {j: self._failures.pop(j) for j in list(want)
                          if j in self._failures}
                if done_r or done_f:
                    return done_r, done_f
                if self._closed:
                    raise ConnectionError("broker connection lost")
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return {}, {}
                self._cond.wait(timeout=min(remaining, 0.5) if remaining is not None else 0.5)

    def _wait_any_routed(self, job_ids: List[str],
                         timeout: Optional[float] = None
                         ) -> Tuple[Dict[str, float], Dict[str, str]]:
        """Router-mode wait_any.  One session's jobs live on ONE shard, so
        the common case is a single group and a full-timeout delegate; ids
        spanning shards poll each home in short slices."""
        deadline = None if timeout is None else time.monotonic() + timeout
        groups = self._jobs_by_shard(job_ids)
        if not groups:
            return {}, {}
        while True:
            for shard, ids in groups.items():
                if len(groups) == 1:
                    slice_t = (None if deadline is None
                               else max(0.0, deadline - time.monotonic()))
                else:
                    slice_t = 0.05
                r, f = self._child(shard).wait_any(ids, timeout=slice_t)
                if r or f:
                    for j in list(r) + list(f):
                        self._job_home.pop(j, None)
                    return r, f
            if deadline is not None and time.monotonic() >= deadline:
                return {}, {}

    def last_error(self) -> Optional[Dict[str, Any]]:
        """The most recent structured ``error`` frame, if any (satellite:
        unknown-session submits answer with one instead of silence)."""
        if self._ring is not None:
            with self._child_lock:
                children = list(self._children.values())
            for child in children:
                err = child.last_error()
                if err is not None:
                    return err
            return None
        with self._cond:
            return self._errors[-1] if self._errors else None

    def close(self) -> None:
        self._user_closed = True
        if self._ring is not None:
            with self._child_lock:
                children, self._children = dict(self._children), {}
            for child in children.values():
                child.close()
            return
        try:
            self._sock.close()
        except OSError:
            pass
