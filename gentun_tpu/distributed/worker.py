"""Worker launcher: ``python -m gentun_tpu.distributed.worker``.

The reference starts workers as hand-written scripts wrapping
``GentunClient`` (gentun examples [PUB]; SURVEY.md §3.3).  This module is
the installable equivalent — point it at the master and a local dataset and
it consumes jobs until killed:

    python -m gentun_tpu.distributed.worker \
        --host <master-ip> --port 5672 --password s3cret \
        --species genetic-cnn --dataset mnist --capacity 8

Host-level mesh worker (ONE worker per host, population sharded across
every local device — DISTRIBUTED.md "Host-level mesh workers"): pass
``--capacity auto`` and the worker derives its window from the local
``(pop, data)`` device mesh (compile bucket × pop-axis size) instead of a
typed-in number, re-advertising it if the device set changes
(``GentunClient.remesh``).  A 4-chip host then joins the fleet as one
member with a mesh-shaped window, not four single-chip members.

All model hyperparameters (``additional_parameters``) arrive from the
master with each job, so the worker needs only its species and its copy of
the training data — genes in, fitness out (SURVEY.md §1).  Jobs from a
multi-fidelity master additionally carry a ``fidelity`` tag
(``protocol.py``); the client cross-checks it against the shipped config
and answers an unknown or mislabeled tag with a structured ``fail`` frame
instead of training a wrong-schedule measurement — a mixed-version fleet
degrades to per-job refusals, never to silent rung poisoning.  Tagless
jobs from pre-ladder masters evaluate unchanged.

Multi-host worker (ONE worker owning a whole TPU pod slice, e.g. a
v5e-32 = 8 hosts × 4 chips — BASELINE config #4): run the same command on
EVERY host of the slice, adding ``--coordinator <host0-ip>:8476``.  On TPU
pods jax infers process count/ids from the pod metadata; on other clusters
pass ``--num-processes 8 --process-id $RANK`` explicitly:

    # on each TPU-VM host of the v5e-32 slice
    python -m gentun_tpu.distributed.worker \
        --host <master-ip> --password s3cret \
        --species genetic-cnn --dataset cifar10 --capacity 32 \
        --coordinator <host0-internal-ip>:8476

Host 0 connects to the master and consumes jobs; the other hosts join its
jitted computations over ICI (the job payloads are broadcast through the
device fabric, never over a side channel).  The fitness mesh then spans
all 32 chips automatically (``jax.devices()`` is global after
``jax.distributed.initialize``).

Operator note: the follower ranks exit when the leader's loop ends (a
shutdown sentinel rides the last broadcast).  If the LEADER process is
killed outright (no chance to send the sentinel), each follower's leader
watchdog (``parallel/multihost.py: start_leader_watchdog``) notices the
dead coordination service within ~10 s and hard-exits that rank with
code 17 — restart the worker command on all hosts of the slice together,
like any SPMD job.  The master side needs no action either way: unacked
jobs redeliver to other workers.
"""

from __future__ import annotations

import argparse
import logging


def _load_dataset(name: str, data_dir=None, n=None):
    import numpy as np

    from ..utils import datasets as ds

    if n is not None and n <= 0:
        # Validate BEFORE the loaders see n: a negative value would raise a
        # raw numpy error (or a huge one allocate) inside the loader.
        raise SystemExit(f"--n must be positive, got {n}")
    # `n` forwards to the loaders that accept it (so npz archives larger
    # than the loader default stay reachable)...
    n_kw = {"n": n} if n is not None else {}
    loaders = {
        "mnist": lambda: ds.load_mnist(**n_kw, data_dir=data_dir),
        "cifar10": lambda: ds.load_cifar10(**n_kw, data_dir=data_dir),
        "cifar100": lambda: ds.load_cifar100(**n_kw, data_dir=data_dir),
        "uci-wine": lambda: ds.load_uci_wine(),
        "uci-binary": lambda: ds.load_uci_binary(),
    }
    if name not in loaders:
        raise SystemExit(f"unknown dataset {name!r}; choose from {sorted(loaders)}")
    if name.startswith("uci-") and data_dir is not None:
        # The UCI tables are fixed sklearn datasets with no npz override —
        # don't let the flag silently no-op.
        raise SystemExit(f"--data-dir is not supported for dataset {name!r}")
    x, y, meta = loaders[name]()
    if n is not None:
        if len(x) < n:
            # Loaders cannot conjure rows an npz archive or sklearn table
            # doesn't have, so undersupply is a loud error here rather than
            # a silently smaller dataset.
            raise SystemExit(f"--n {n} not satisfiable for {name!r} ({len(x)} examples available)")
        if len(x) > n:
            # Only the UCI loaders reach here (the image loaders subsample
            # to `n` themselves); enforce the flag uniformly regardless.
            idx = np.random.default_rng(0).permutation(len(x))[:n]
            x, y = x[idx], y[idx]
    return x, y, meta


def _species(name: str):
    from ..individuals import BoostingIndividual, GeneticCnnIndividual, XgboostIndividual

    table = {
        "genetic-cnn": GeneticCnnIndividual,
        "boosting": BoostingIndividual,
        "xgboost": XgboostIndividual,  # reference 11-gene genome
    }
    if name not in table:
        raise SystemExit(f"unknown species {name!r}; choose from {sorted(table)}")
    return table[name]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m gentun_tpu.distributed.worker",
        description="gentun_tpu fitness worker (owns the data, trains shipped genes)",
    )
    ap.add_argument("--host", default="127.0.0.1", help="master broker host")
    ap.add_argument("--port", type=int, default=5672, help="master broker port")
    ap.add_argument("--broker-urls", default=None, metavar="HOST:PORT,...",
                    help="comma-separated broker shard addresses (horizontal "
                         "sharding — DISTRIBUTED.md 'Horizontal broker "
                         "sharding').  The worker multi-homes: one "
                         "connection, credit window, and backoff per shard, "
                         "so a dead shard never blocks dispatch from healthy "
                         "ones.  Overrides --host/--port; a single address "
                         "behaves exactly like --host/--port")
    ap.add_argument("--password", default=None, help="broker shared token")
    ap.add_argument("--species", default="genetic-cnn", help="genetic-cnn | boosting | xgboost")
    ap.add_argument("--dataset", default="mnist",
                    help="mnist | cifar10 | cifar100 | uci-wine | uci-binary")
    ap.add_argument("--data-dir", default=None,
                    help="directory with {name}.npz overrides (or $GENTUN_TPU_DATA)")
    ap.add_argument("--n", type=int, default=None, help="subsample the dataset to n examples")
    ap.add_argument("--capacity", default="1",
                    help="jobs taken at once; >1 trains the batch as one "
                         "vmapped program.  'auto' switches on host-level "
                         "mesh mode: this ONE worker drives every local "
                         "device through the (pop, data) mesh and derives "
                         "its capacity from the mesh (compile bucket x "
                         "pop-axis size) instead of a typed-in number — "
                         "see DISTRIBUTED.md 'Host-level mesh workers'")
    ap.add_argument("--mesh", default=None, metavar="POPxDATA",
                    help="pin the (pop, data) device-mesh factoring instead "
                         "of auto_mesh's heuristic, e.g. --mesh 4x2 on an "
                         "8-device host.  The axes must multiply to the "
                         "local device count (checked when the count is "
                         "known, and re-checked on remesh); malformed or "
                         "non-factoring values exit loudly.  See "
                         "DISTRIBUTED.md 'Big-genome regime'.")
    ap.add_argument("--prefetch-depth", type=int, default=None,
                    help="jobs queued locally BEYOND capacity so the next "
                         "window is decoded while the current one trains "
                         "(double buffering).  Default: capacity.  0 restores "
                         "the serial pre-pipelining loop; clamped to "
                         "4 x capacity.  See DISTRIBUTED.md 'Pipelined dispatch'.")
    ap.add_argument("--worker-id", default=None)
    ap.add_argument("--n-chips", type=int, default=None,
                    help="override the advertised accelerator chip count "
                         "(default: jax.device_count() for jax species, 1 otherwise)")
    ap.add_argument("--max-jobs", type=int, default=None, help="exit after this many results")
    ap.add_argument("--fitness-store", default=None,
                    help="read-only cross-run fitness cache (utils/fitness_store.py "
                         "JSON): jobs whose genes+config were measured by a prior "
                         "run are answered without retraining.  Not available with "
                         "--coordinator (multihost) — see GentunClient.")
    ap.add_argument("--cache-url", default=None, metavar="URL",
                    help="shared fitness-memoization service "
                         "(distributed/fitness_service.py), e.g. "
                         "http://cache-host:9736: look up each job's genes+"
                         "config before training and publish fresh fitnesses "
                         "back (write-behind).  Layers OVER --fitness-store; "
                         "degrades to local-only when unreachable.  Not "
                         "available with --coordinator (multihost).")
    ap.add_argument("--compile-cache-url", default=None, metavar="URL",
                    help="fleet-wide compiled-executable cache service "
                         "(distributed/compile_service.py), e.g. "
                         "http://cache-host:9737: fetch the fleet's XLA "
                         "cache entries for this platform at join (and "
                         "after remesh) before advertising capacity, and "
                         "publish whatever this worker compiles first "
                         "(write-behind).  Degrades to local compiles when "
                         "unreachable.  Not available with --coordinator "
                         "(multihost).")
    ap.add_argument("--aggregator-url", default=None, metavar="URL",
                    help="fleet metrics aggregator "
                         "(telemetry/aggregator.py), e.g. "
                         "http://agg-host:9100: push this worker's metric "
                         "snapshots there every few seconds under its "
                         "--worker-id, feeding the fleet /metrics, the "
                         "/statusz version-skew table, and the SLO engine "
                         "behind /alertz.  Fail-open with cooldown — "
                         "aggregator downtime never touches evaluation.")
    ap.add_argument("--fault-plan", default=None, metavar="PATH",
                    help="chaos testing: JSON FaultPlan (distributed/faults.py) "
                         "injected into this worker's client hooks")
    ap.add_argument("--preempt", action="store_true",
                    help="advertise this worker as PREEMPTIBLE capacity: the "
                         "broker routes cheap rung-0 probes here and pins "
                         "high-rung promotions to stable workers.  SIGUSR1 "
                         "acts as the preemption deadline signal — the worker "
                         "self-drains through the ordinary SIGTERM drain path "
                         "with the requeue attributed to preemption.  See "
                         "DISTRIBUTED.md 'Autoscaling & preemptible capacity'.")
    ap.add_argument("--preempt-after", type=float, default=None,
                    metavar="SECONDS",
                    help="self-preempt after SECONDS (implies --preempt): a "
                         "deterministic deadline for chaos studies, "
                         "equivalent to receiving SIGUSR1 then")
    ap.add_argument("--wire-v1", action="store_true",
                    help="advertise NO wire capabilities: pin this worker to "
                         "the v1 frame set even against a jobs2-capable "
                         "broker (ops kill switch for the wire fast path — "
                         "see DISTRIBUTED.md 'Wire fast path')")
    ap.add_argument("--telemetry", action="store_true",
                    help="collect spans for evaluated job groups and ship "
                         "them to the master in result frames (equivalent to "
                         "GENTUN_TPU_TELEMETRY=1; see docs/OBSERVABILITY.md)")
    ap.add_argument("--ops-port", type=int, default=None, metavar="PORT",
                    help="serve the live ops plane (/metrics /healthz /statusz "
                         "/debugz/flight) on 127.0.0.1:PORT and arm the flight "
                         "recorder; 0 picks an ephemeral port (logged).  Off "
                         "by default — see docs/OBSERVABILITY.md 'Live ops "
                         "plane'.")
    ap.add_argument("--ops-host", default="127.0.0.1", metavar="ADDR",
                    help="bind address for --ops-port (default 127.0.0.1; "
                         "bind a routable address only on a trusted network "
                         "— the endpoints are unauthenticated)")
    mh = ap.add_argument_group(
        "multi-host",
        "run ONE logical worker across a multi-process jax cluster (e.g. all "
        "hosts of a TPU pod slice).  Launch this command on EVERY host with "
        "the same --coordinator; process 0 talks to the master, the rest "
        "join its computations over ICI.  On TPU pods --num-processes/"
        "--process-id may be omitted (inferred from pod metadata).",
    )
    mh.add_argument("--coordinator", default=None, metavar="HOST:PORT",
                    help="jax.distributed coordinator address (host 0)")
    mh.add_argument("--num-processes", type=int, default=None)
    mh.add_argument("--process-id", type=int, default=None)
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)

    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    # Validate operator-visible knobs HERE, loudly: GentunClient clamps
    # silently (max(1, capacity), prefetch into [0, 4*capacity]) because a
    # library caller may compute them, but a typed-out `--capacity 0` is a
    # mistake the operator should hear about, not a worker that quietly
    # runs with different numbers than its command line says.
    if str(args.capacity).strip().lower() == "auto":
        # Host-level mesh worker: capacity derives from the local device
        # mesh inside GentunClient (after any multihost init below, so a
        # pod-slice worker derives from its GLOBAL device count).
        args.capacity = "auto"
    else:
        try:
            args.capacity = int(args.capacity)
        except ValueError:
            raise SystemExit(
                f"--capacity must be a positive integer or 'auto', got {args.capacity!r}")
        if args.capacity <= 0:
            raise SystemExit(f"--capacity must be a positive integer, got {args.capacity}")
    if args.mesh is not None:
        from ..parallel.mesh import parse_mesh_spec

        try:
            args.mesh = parse_mesh_spec(args.mesh)
        except ValueError as e:
            raise SystemExit(f"--mesh: {e}")
    if args.prefetch_depth is not None and args.prefetch_depth < 0:
        raise SystemExit(f"--prefetch-depth must be >= 0, got {args.prefetch_depth}")
    if args.preempt_after is not None:
        if args.preempt_after <= 0:
            raise SystemExit(
                f"--preempt-after must be > 0 seconds, got {args.preempt_after}")
        args.preempt = True  # a deadline only makes sense on preemptible capacity
    if args.ops_port is not None and not 0 <= args.ops_port <= 65535:
        raise SystemExit(f"--ops-port must be in [0, 65535], got {args.ops_port}")
    if args.cache_url is not None:
        from .fitness_service import parse_cache_url

        try:
            args.cache_url = parse_cache_url(args.cache_url)
        except ValueError as e:
            raise SystemExit(f"--cache-url: {e}")
    if args.aggregator_url is not None:
        from ..telemetry.aggregator import parse_aggregator_url

        try:
            args.aggregator_url = parse_aggregator_url(args.aggregator_url)
        except ValueError as e:
            raise SystemExit(f"--aggregator-url: {e}")
    if args.compile_cache_url is not None:
        from .fitness_service import parse_cache_url

        try:
            args.compile_cache_url = parse_cache_url(args.compile_cache_url)
        except ValueError as e:
            raise SystemExit(f"--compile-cache-url: {e}")
    if args.telemetry:
        from ..telemetry import spans as tele_spans

        tele_spans.enable()
    if args.ops_port is not None:
        from ..telemetry.ops_server import start_ops_server

        ops = start_ops_server(port=args.ops_port, host=args.ops_host)
        logging.getLogger("gentun_tpu.distributed").info(
            "ops plane serving on %s (/metrics /healthz /statusz /debugz/flight)",
            ops.url)
    if (args.num_processes is not None or args.process_id is not None) and args.coordinator is None:
        raise SystemExit("--num-processes/--process-id require --coordinator")
    multihost = args.coordinator is not None
    if multihost and args.fitness_store:
        raise SystemExit("--fitness-store is not supported with --coordinator "
                         "(a store present on one host but not another would "
                         "diverge the ranks' compiled programs)")
    if multihost and args.cache_url:
        raise SystemExit("--cache-url is not supported with --coordinator "
                         "(same rank-divergence hazard as --fitness-store: a "
                         "cache hit on one host but not another would skip "
                         "training on some ranks only)")
    if multihost and args.compile_cache_url:
        raise SystemExit("--compile-cache-url is not supported with "
                         "--coordinator (the XLA cache dir is per-host, so "
                         "the leader cannot prefetch for its followers — a "
                         "warm rank 0 racing cold ranks into the collectives "
                         "would look exactly like a hang)")
    if multihost:
        # Must happen before ANY jax backend init (so before evaluation);
        # after it, jax.devices() is the global pod-slice device list and
        # the fitness mesh spans every host automatically.
        from ..parallel import multihost as mh_mod

        mh_mod.initialize(args.coordinator, args.num_processes, args.process_id)
    x, y, meta = _load_dataset(args.dataset, data_dir=args.data_dir, n=args.n)
    logging.getLogger("gentun_tpu.distributed").info(
        "worker data: %s (%d examples, synthetic=%s)", meta.get("source", args.dataset),
        len(x), meta.get("synthetic"),
    )

    from .client import GentunClient
    from .protocol import AuthError

    injector = None
    if args.fault_plan is not None:
        from .faults import FaultInjector, FaultPlan

        with open(args.fault_plan, "r", encoding="utf-8") as fh:
            injector = FaultInjector(FaultPlan.from_json(fh.read()))
        logging.getLogger("gentun_tpu.distributed").warning(
            "fault injection ACTIVE: %d spec(s) from %s", len(injector.plan.specs), args.fault_plan
        )

    try:
        client = GentunClient(
            _species(args.species),
            x,
            y,
            host=args.host,
            port=args.port,
            password=args.password,
            capacity=args.capacity,
            prefetch_depth=args.prefetch_depth,
            mesh_override=args.mesh,
            worker_id=args.worker_id,
            multihost=multihost,
            n_chips=args.n_chips,
            fitness_store=args.fitness_store,
            cache_url=args.cache_url,
            compile_cache_url=args.compile_cache_url,
            aggregator_url=args.aggregator_url,
            fault_injector=injector,
            wire_caps=() if args.wire_v1 else None,
            preemptible=args.preempt,
            broker_urls=([u.strip() for u in args.broker_urls.split(",") if u.strip()]
                         if args.broker_urls else None),
        )
    except ValueError as e:
        # Config errors the CLI could not pre-validate — notably a --mesh
        # override that does not factor the probed device count (only
        # known here, after any multihost init).  Exit loudly instead of
        # surfacing a traceback.
        raise SystemExit(str(e))
    # Elastic-fleet exit protocol (DISTRIBUTED.md "Elastic fleet"): first
    # SIGTERM/SIGINT asks for an orderly drain — finish the window being
    # trained, hand queued-but-unstarted jobs back to the broker, exit.  A
    # second signal stops without waiting (the broker's disconnect requeue
    # covers whatever was in flight).  Registration fails on non-main
    # threads (library embedding) — skip silently there, drain() is still
    # callable programmatically.
    import signal

    def _on_signal(signum, frame):
        if client.draining:
            logging.getLogger("gentun_tpu.distributed").warning(
                "second signal: stopping without waiting for in-flight work")
            client.shutdown()
        else:
            logging.getLogger("gentun_tpu.distributed").info(
                "drain requested (signal %d): finishing in-flight work, "
                "requeueing the rest; signal again to stop now", signum)
            client.drain()

    # Preemption deadline (DISTRIBUTED.md "Autoscaling & preemptible
    # capacity"): SIGUSR1 — or the --preempt-after timer for deterministic
    # studies — is "your capacity is being reclaimed".  It reuses the
    # drain machinery above verbatim, differing only in the wire-level
    # ``reason`` so the broker's requeue lineage attributes the churn to
    # preemption; a second SIGUSR1 escalates to shutdown like SIGTERM.
    def _on_preempt(signum=None, frame=None):
        if client.draining:
            client.shutdown()
            return
        logging.getLogger("gentun_tpu.distributed").warning(
            "preemption deadline: self-draining (in-flight work finishes, "
            "queued jobs requeue to the fleet)")
        from ..telemetry.registry import get_registry

        get_registry().counter("preemptions_total",
                               worker=client.worker_id).inc()
        client.drain(reason="preempt")

    try:
        signal.signal(signal.SIGTERM, _on_signal)
        signal.signal(signal.SIGINT, _on_signal)
        if args.preempt:
            signal.signal(signal.SIGUSR1, _on_preempt)
    except ValueError:  # pragma: no cover - non-main-thread embedding
        pass
    if args.preempt_after is not None:
        import threading

        timer = threading.Timer(args.preempt_after, _on_preempt)
        timer.daemon = True
        timer.start()
    try:
        done = client.work(max_jobs=args.max_jobs)
    except AuthError as e:
        raise SystemExit(f"fatal: {e}")
    logging.getLogger("gentun_tpu.distributed").info("worker exiting after %d job(s)", done)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
