"""Distribution layer: the master↔worker control plane over DCN.

The rebuild's replacement for the reference's RabbitMQ transport
(``gentun/server.py`` + ``gentun/client.py`` [PUB][BASELINE]; SURVEY.md §1
L2, §5 "Distributed communication backend"): an embedded asyncio TCP/JSON
broker with AMQP-equivalent at-least-once + competing-consumer semantics.
Only genes, hyperparameters, and fitness scalars cross the wire; data and
device collectives stay inside each worker (ICI, via jax).
"""

from .broker import GatherTimeout, JobBroker, JobFailed
from .client import GentunClient
from .faults import FaultInjector, FaultPlan, FaultSpec, MasterKilled
from .fitness_service import FitnessService, FitnessServiceClient, ServiceBackedCache
from .protocol import AuthError
from .server import DistributedGridPopulation, DistributedPopulation
from .journal import (
    JOURNAL_SCHEMA,
    DispatchJournal,
    JournalCorruptError,
    JournalError,
    JournalSchemaError,
    replay_file,
)
from .sessions import (
    DEFAULT_SESSION,
    AdmissionRejected,
    FairShareScheduler,
    SearchSession,
    SessionClient,
    UnknownSessionError,
    genome_key,
)

__all__ = [
    "JobBroker",
    "JobFailed",
    "GatherTimeout",
    "GentunClient",
    "AuthError",
    "DistributedPopulation",
    "DistributedGridPopulation",
    "FaultSpec",
    "FaultPlan",
    "FaultInjector",
    "MasterKilled",
    "FitnessService",
    "FitnessServiceClient",
    "ServiceBackedCache",
    "DEFAULT_SESSION",
    "SearchSession",
    "SessionClient",
    "FairShareScheduler",
    "UnknownSessionError",
    "AdmissionRejected",
    "JOURNAL_SCHEMA",
    "DispatchJournal",
    "JournalError",
    "JournalCorruptError",
    "JournalSchemaError",
    "replay_file",
    "genome_key",
]
