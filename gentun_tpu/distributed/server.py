"""Master-side distribution: a Population that farms fitness out to workers.

Reference parity: ``DistributedPopulation`` (and the [UNCERTAIN]
``DistributedGridPopulation``) in ``gentun/server.py`` [PUB][BASELINE]
(SURVEY.md §2.0 row 10, §3.2).  Preserved semantics:

- constructed WITHOUT training data — workers own the data, the master
  ships only genes + ``additional_parameters`` and receives fitness scalars;
- drop-in replacement for ``Population``: the GA outer loop is unchanged;
- fitness evaluation publishes one job per unevaluated individual and
  blocks until every reply arrives (the per-generation barrier);
- at-least-once delivery with dedup is the broker's job
  (``distributed/broker.py``).

The broker is embedded: constructing a ``DistributedPopulation`` starts a
TCP listener inside the master process (no external RabbitMQ — SURVEY.md
§2.1), and successive generations share it via :meth:`clone_with`.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Mapping, Optional, Sequence, Type

import numpy as np

from ..individuals import Individual
from ..parallel.mesh import SIZE_SMALL, job_size_class
from ..populations import GridPopulation, Population
from ..telemetry import health as _health
from ..telemetry import lineage as _lineage
from ..telemetry import spans as _tele
from ..telemetry.registry import get_registry as _get_registry
from .broker import GatherTimeout, JobBroker, JobFailed
from .sessions import DEFAULT_SESSION

__all__ = ["DistributedPopulation", "DistributedGridPopulation"]

logger = logging.getLogger("gentun_tpu.distributed")


def _params_copier():
    """One defensive payload copy per DISTINCT source dict per submit call.

    A population's individuals overwhelmingly share ONE
    ``additional_parameters`` dict (the run config), yet each payload used
    to take its own ``dict()`` copy — N copies the broker then serializes
    into N identical wire fragments.  Memoizing the copy by source identity
    keeps the caller-isolation contract (payloads never alias a dict the
    caller can mutate) while giving the wire fast path one shared object
    per config, so ``jobs2`` envelope grouping and the fragment cache see
    maximal sharing.  id() keying is safe here: the memo only lives for one
    submit call, during which the source individuals are referenced.
    """
    copies: Dict[int, Dict[str, Any]] = {}

    def copy(src: Mapping[str, Any]) -> Dict[str, Any]:
        c = copies.get(id(src))
        if c is None:
            c = copies[id(src)] = dict(src)
        return c

    return copy


class DistributedPopulation(Population):
    """Population whose fitness sweep runs on remote workers.

    Extra constructor knobs versus :class:`Population` (data args are gone):

    - ``host``/``port``: broker bind address (``port=0`` = ephemeral; read
      the bound address from :attr:`broker_address` to point workers at it).
    - ``user``/``password``: auth parity with the reference's RabbitMQ
      kwargs [PUB]; ``password`` becomes the broker token.
    - ``job_timeout``: per-generation barrier timeout in seconds (None =
      wait forever, the reference's behavior).
    - ``broker``: share an existing started :class:`JobBroker` instead of
      owning one (used by :meth:`clone_with` across generations).
    - ``evaluate_retries``: extra :meth:`evaluate` passes after a
      ``JobFailed``/``GatherTimeout`` before giving up.  Each retry reships
      ONLY the still-unevaluated individuals (finished fitnesses are
      applied before the exception propagates internally), with fresh
      broker attempt counts — so a transient worker glitch or straggler
      timeout no longer kills a 50-generation search (the reference's
      AMQP redelivers forever and never surfaces this).
    - ``failed_policy``: what to do when retries are exhausted and some
      individuals still lack fitness.  ``"raise"`` (default) re-raises —
      today's loud behavior; ``"penalize"`` assigns them the worst
      fitness observed in the generation (never cached — a penalty is not
      a measurement) and lets the search continue, unless NOTHING
      evaluated at all, which still raises.
    - ``fitness_store``: path to a cross-run fitness store
      (``utils/fitness_store.py``).  Loaded at construction (in-memory
      ``fitness_cache`` entries win on collision) and merged back
      atomically at :meth:`close` — a repeated distributed search over
      already-measured genomes ships ZERO jobs.  The store rides
      ``clone_with``, so closing whichever generation's population the
      caller ends up holding saves every fitness the search measured.
    - ``cache_url``: base URL of a shared fitness service
      (``distributed/fitness_service.py``, ``http://host:port``).  The
      population's ``fitness_cache`` becomes a
      :class:`~gentun_tpu.distributed.fitness_service.ServiceBackedCache`:
      local misses read through to the service (a genome ANY run already
      measured completes instantly, never dispatched — PR-3's dispatch-side
      dedup extended across runs) and new measurements publish
      write-behind.  Layers OVER ``fitness_store`` (file entries seed the
      local side; the file still saves at :meth:`close`).  Service downtime
      degrades to local-only with a ``fitness_service_degraded`` telemetry
      event — it never fails the search.  Note: when both ``fitness_cache``
      and ``cache_url`` are given, the wrapped cache is a NEW dict seeded
      from the one passed in (clones still share the wrapper by identity).
    - ``fault_injector``: chaos testing (``distributed/faults.py``).
      Passed through to an owned :class:`JobBroker`; ignored when an
      external ``broker`` is shared (inject on that broker directly).
    - ``straggler_floor_s``/``straggler_k``/``straggler_requeue``: stall
      watchdog tuning for an owned broker (``telemetry/health.py``; active
      only while the ops plane is on — see docs/OBSERVABILITY.md "Live ops
      plane").  Ignored when sharing an external ``broker``.
    - ``session``: multi-tenant search sessions (``distributed/sessions.py``,
      DISTRIBUTED.md "Multi-tenant search sessions").  Naming a session
      opens it on the broker (idempotent) and tags every job this
      population ships with it; ``fleet_capacity``/``fleet_prefetch``
      then report THIS session's fair share of the fleet, so N engines
      sharing one broker via ``broker=`` size themselves to their shares
      with no engine changes.  ``None`` (default) rides the implicit
      single-tenant session — byte-identical pre-session behavior.
    - ``session_weight``/``session_quota``: the session's fair-share
      priority and optional hard in-flight cap (only meaningful with
      ``session``).
    - ``cache_namespace``: optional per-session key prefix for the shared
      fitness service (only meaningful with ``cache_url``).  The DEFAULT
      is no namespace — cross-tenant dedup stays ON, because cache keys
      are content-addressed (a fitness is a property of the genome, not
      the tenant; quotas govern compute, not cache hits).  Set it only to
      ISOLATE a tenant whose measurements must not be shared (different
      data, incompatible species).
    - ``aggregator_url``: optional fleet metrics aggregator
      (``telemetry/aggregator.py``).  The master pushes periodic metric
      snapshots there (role ``master``; the owned broker merges into the
      same per-process pusher) for the life of the population.  Fail-open
      with cooldown — aggregator downtime can never touch a search.
    """

    def __init__(
        self,
        species: Type[Individual],
        individual_list: Optional[Sequence[Individual]] = None,
        size: Optional[int] = None,
        crossover_rate: float = 0.5,
        mutation_rate: float = 0.015,
        maximize: bool = True,
        additional_parameters: Optional[Dict[str, Any]] = None,
        seed: Optional[int] = None,
        rng: Optional[np.random.Generator] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        user: Optional[str] = None,
        password: Optional[str] = None,
        job_timeout: Optional[float] = None,
        max_attempts: int = 3,
        heartbeat_timeout: float = 15.0,
        broker: Optional[JobBroker] = None,
        fitness_cache: Optional[Dict[Any, float]] = None,
        evaluate_retries: int = 0,
        failed_policy: str = "raise",
        fitness_store: Optional[str] = None,
        cache_url: Optional[str] = None,
        speculative_fill=False,
        fault_injector=None,
        straggler_floor_s: float = 30.0,
        straggler_k: float = 4.0,
        straggler_requeue: bool = False,
        session: Optional[str] = None,
        session_weight: float = 1.0,
        session_quota: Optional[int] = None,
        cache_namespace: Optional[str] = None,
        aggregator_url: Optional[str] = None,
        broker_urls: Optional[list] = None,
    ):
        if failed_policy not in ("raise", "penalize"):
            raise ValueError(f"unknown failed_policy {failed_policy!r}")
        self.fitness_store = fitness_store
        if fitness_store:
            from ..utils.fitness_store import load_fitness_cache

            loaded = load_fitness_cache(fitness_store)
            if fitness_cache is None:
                fitness_cache = loaded
            else:
                # Merge IN PLACE so the provided dict keeps its identity
                # (clones share the cache object); live measurements beat
                # stored ones, hence setdefault.
                for k, v in loaded.items():
                    fitness_cache.setdefault(k, v)
        self.cache_url = cache_url
        self.cache_namespace = cache_namespace
        self._cache_client = None
        self._cache_status_fn = None
        if cache_url:
            from .fitness_service import FitnessServiceClient, ServiceBackedCache

            self._cache_client = FitnessServiceClient(cache_url)
            # Wrap AFTER the store merge so file entries seed the local
            # side (they stay local; only new measurements publish).  The
            # wrapper IS the fitness_cache from here on — clones share it
            # by identity like any cache dict.
            fitness_cache = ServiceBackedCache(self._cache_client, fitness_cache,
                                               namespace=cache_namespace)
            cache = fitness_cache
            # One callable object for register AND unregister (removal is
            # identity-checked); closed over the cache, not self, so any
            # clone's close() can evict it.
            self._cache_status_fn = cache.stats
            _health.register_status_provider("fitness_service", self._cache_status_fn)
        # Fleet observability (telemetry/aggregator.py): the master pushes
        # its metric snapshots for as long as this population lives.  The
        # per-process pusher is refcounted and shared per URL, so the owned
        # in-process broker below wiring the same URL merges into one
        # instance (role "master+broker") — never a double-counted fleet.
        self.aggregator_url = aggregator_url
        self._pusher = None
        if aggregator_url:
            from ..telemetry.aggregator import acquire_pusher

            self._pusher = acquire_pusher(aggregator_url, role="master")
        super().__init__(
            species,
            x_train=None,
            y_train=None,
            individual_list=individual_list,
            size=size,
            crossover_rate=crossover_rate,
            mutation_rate=mutation_rate,
            maximize=maximize,
            additional_parameters=additional_parameters,
            seed=seed,
            rng=rng,
            fitness_cache=fitness_cache,
            speculative_fill=speculative_fill,
        )
        self.job_timeout = job_timeout
        self.evaluate_retries = int(evaluate_retries)
        self.failed_policy = failed_policy
        #: populated by every evaluate() call: {"attempts", "retries",
        #: "penalized"} — the GA merges it into the generation history.
        self.eval_stats: Dict[str, int] = {}
        if broker is not None and broker_urls:
            raise ValueError("pass broker= OR broker_urls=, not both")
        if broker is not None:
            self.broker = broker
            self._owns_broker = False
        elif broker_urls:
            # Horizontal sharding (ISSUE 18): this master is a TENANT of
            # N operator-run broker shards — its session is consistent-
            # hashed to ONE home shard and every broker call goes over
            # the wire through the ShardedBroker facade.  Broker-process
            # knobs (heartbeat_timeout, max_attempts, stragglers, fault
            # injection) belong to the shard operators, not this ctor.
            if fault_injector is not None:
                raise ValueError(
                    "fault_injector requires an embedded broker, not broker_urls")
            from .shard import ShardedBroker

            self.broker = ShardedBroker(
                broker_urls, token=password,
                retry_window=max(60.0, float(job_timeout or 0.0)))
            # "Owns" the facade (close() must drop its shard connections);
            # the shard broker PROCESSES are operator-owned and outlive us.
            self._owns_broker = True
        else:
            self.broker = JobBroker(
                host=host,
                port=port,
                token=password,
                heartbeat_timeout=heartbeat_timeout,
                max_attempts=max_attempts,
                fault_injector=fault_injector,
                straggler_floor_s=straggler_floor_s,
                straggler_k=straggler_k,
                straggler_requeue=straggler_requeue,
                aggregator_url=aggregator_url,
            ).start()
            self._owns_broker = True
        # Session tenancy: an explicit session is opened on the broker
        # (idempotent — a clone or a reconnecting master re-attaches) and
        # tags every submit from this population.  _session_arg stays None
        # for the implicit default so submits stay untagged (and the
        # default session is only lazily created broker-side).
        self._session_arg = str(session) if session else None
        self.session = self._session_arg or DEFAULT_SESSION
        self.session_weight = float(session_weight)
        self.session_quota = session_quota
        if self._session_arg is not None:
            self.broker.open_session(self._session_arg, weight=session_weight,
                                     max_in_flight=session_quota)

    # -- lifecycle ---------------------------------------------------------

    @property
    def broker_address(self) -> tuple:
        return self.broker.address

    def close(self) -> None:
        # Persist first (a stopped broker must not lose fitnesses), but a
        # save failure must not leave the listener running either.
        try:
            if self.fitness_store:
                from ..utils.fitness_store import save_fitness_cache

                n = save_fitness_cache(self.fitness_cache, self.fitness_store)
                logger.info("fitness store %s: %d entries after merge", self.fitness_store, n)
        finally:
            if self._cache_client is not None:
                if self._cache_status_fn is not None:
                    _health.unregister_status_provider(
                        "fitness_service", self._cache_status_fn)
                # Flush the write-behind queue so the LAST generation's
                # measurements reach the service too, then stop the flusher.
                self._cache_client.close()
            from .shard import ShardedBroker

            if self._session_arg is not None and (
                    not self._owns_broker
                    or isinstance(self.broker, ShardedBroker)):
                # Release this tenant's slot on the SHARED broker so its
                # fair-share weight stops diluting the neighbors.  (An
                # owned broker is stopping anyway; idempotent either way.
                # A ShardedBroker facade is "owned" but the shard broker
                # PROCESSES are shared — the session must close remotely
                # or its weight dilutes the shard's other tenants forever.)
                self.broker.close_session(self._session_arg)
            if self._owns_broker:
                self.broker.stop()
            if self._pusher is not None:
                # After the broker's own release: the final flush then
                # carries the fully-settled end-of-run counters.
                from ..telemetry.aggregator import release_pusher

                release_pusher(self._pusher)
                self._pusher = None

    def __enter__(self) -> "DistributedPopulation":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- asynchronous (steady-state) evaluation API ------------------------
    #
    # Used by ``algorithms_async.AsyncEvolution`` instead of the barrier:
    # ship → wait for ANY completion → breed a replacement → ship again.
    # Payload construction (genes + additional_parameters + trace) lives
    # here so the wire format has exactly one owner for both modes.

    def fleet_capacity(self) -> int:
        """THIS session's share of the fleet's job slots (0 when none).

        Single-tenant populations (no ``session``) see the full fleet
        total, exactly as before; concurrent tenants see their weighted
        share, which is how unmodified engines size their in-flight
        targets to coexist on one fleet.
        """
        return self.broker.session_capacity(self._session_arg)

    def fleet_prefetch(self) -> int:
        """This session's share of the fleet's prefetch slots.

        The engine's breed-ahead target is ``fleet_capacity() +
        fleet_prefetch()`` — enough in-flight work that every worker holds
        a decoded next window while its current one trains.  0 for a
        fleet of old or ``prefetch_depth=0`` workers, which keeps the
        pre-pipelining in-flight target (and trajectories) unchanged.
        """
        return self.broker.session_prefetch(self._session_arg)

    def _fill_target(self, n_real, params=None):
        """Speculative-fill target, additionally aligned to the fleet's
        widest advertised mesh pop-axis (``JobBroker.fleet_mesh_pop``).

        A host-level mesh worker pads every evaluation window up to its
        pop-axis multiple regardless of what the master ships
        (``models/cnn._prepare_population_setup``) — slots the compile
        bucket alone doesn't predict.  Rounding the fill target to the
        mesh multiple turns that padding into paid-for speculative
        trainings whose fitnesses seed the cache, instead of sliced-away
        waste (``eval_pad_waste_total``).  Fleets with no mesh workers
        get the base bucket target unchanged.

        Big-genome regime: the rounding is per size class.  Non-small
        configs (``parallel.mesh.job_size_class`` on the evaluation
        params — jax-free) run ONE genome per program on the narrow-pop
        ``(1, n)`` mesh, so there is no pop multiple to align to and no
        compile bucket to fill — speculative padding would train extra
        over-budget genomes at full price for nothing.  They keep the
        exact real count (plus only an EXPLICIT integer
        ``speculative_fill``, which remains an operator decision).
        """
        if job_size_class(params) != SIZE_SMALL:
            target = int(n_real)
            if self.speculative_fill is not True and self.speculative_fill:
                target = max(target, int(self.speculative_fill))
            return target
        target = super()._fill_target(n_real, params)
        multiple = self.broker.fleet_mesh_pop()
        if multiple > 1 and target % multiple:
            target += multiple - target % multiple
        return target

    def submit_individuals(self, individuals: Sequence[Individual]) -> List[str]:
        """Ship evaluation jobs without waiting; returns aligned job ids.

        One broker submit per call — the engine breeds every replacement a
        wake-up allows and ships them together, so the dispatch side stays
        one coalesced ``jobs`` frame per worker capacity window even in
        completion-driven mode.
        """
        payloads: Dict[str, Dict[str, Any]] = {}
        ids: List[str] = []
        ctx = _tele.current_context() if _tele.enabled() else None
        # Forensics opt-in rides the trace context (lineage.py): workers
        # only emit per-job device spans when the master is accounting.
        ctx = _lineage.forensic_context(ctx)
        params_copy = _params_copier()
        for ind in individuals:
            job_id = JobBroker.new_job_id()
            payload: Dict[str, Any] = {
                "genes": ind.get_genes(),
                "additional_parameters": params_copy(ind.additional_parameters),
            }
            # OPTIONAL per-job fidelity tag (protocol.py): stamped by the
            # multi-fidelity engine so workers can refuse a mislabeled
            # rung with a structured fail frame instead of training it.
            fidelity = getattr(ind, "_fidelity_tag", None)
            if fidelity is not None:
                payload["fidelity"] = dict(fidelity)
            if ctx is not None:
                payload["trace"] = ctx
            payloads[job_id] = payload
            ids.append(job_id)
        if payloads:
            self.broker.submit(payloads, session=self._session_arg)
        return ids

    def wait_any_results(self, job_ids: Sequence[str], timeout: Optional[float] = None):
        """Block until ≥1 of ``job_ids`` is terminal; ``(results, failures)``."""
        return self.broker.wait_any(list(job_ids), timeout=timeout)

    def cancel_jobs(self, job_ids: Sequence[str]) -> None:
        """Withdraw still-open jobs whose results are no longer wanted."""
        self.broker.cancel(job_ids)

    # -- the distributed fitness sweep ------------------------------------

    def evaluate(self) -> int:
        """Evaluate the population remotely, with bounded failure retries.

        Returns the number of jobs that completed remotely across all
        passes.  Each pass ships only still-unevaluated individuals, so a
        retry after ``JobFailed``/``GatherTimeout`` re-trains exactly the
        failed/unfinished work.  After ``evaluate_retries`` extra passes,
        ``failed_policy`` decides: re-raise, or penalize the stragglers
        with the generation's worst fitness and keep the search alive.
        """
        if not any(not ind.fitness_evaluated for ind in self.individuals):
            # Nothing to do — and crucially, don't reset eval_stats: a
            # follow-up no-op call (get_fittest() evaluates lazily) must not
            # erase the real sweep's retry bookkeeping before the GA logs it.
            return 0
        stats = {"attempts": 0, "retries": 0, "penalized": 0}
        self.eval_stats = stats
        self.broker.reset_chips_seen()
        completed = 0
        while True:
            stats["attempts"] += 1
            try:
                done = completed + self._evaluate_once()
                # chips_seen() = max(current fleet, sweep-long observation):
                # a worker that exits right after its final result still
                # counts, as does a late joiner.  The GA's logger divides the
                # north-star metric by this instead of the master's
                # (jax-less, always-1) local chip count.
                stats["n_chips"] = self.broker.chips_seen()
                return done
            except (JobFailed, GatherTimeout) as e:
                partial = getattr(e, "partial", {}) or {}
                spec_ids = getattr(self, "_spec_job_ids", set())
                completed += len([j for j in partial if j not in spec_ids])
                if stats["attempts"] <= self.evaluate_retries:
                    stats["retries"] += 1
                    logger.warning(
                        "evaluate() pass %d/%d failed (%s); retrying the "
                        "unfinished individuals",
                        stats["attempts"], self.evaluate_retries + 1, e,
                    )
                    continue
                evaluated = [i for i in self.individuals if i.fitness_evaluated]
                if self.failed_policy == "penalize" and evaluated:
                    fits = [i.get_fitness() for i in evaluated]
                    worst = min(fits) if self.maximize else max(fits)
                    for ind in self.individuals:
                        if not ind.fitness_evaluated:
                            ind.set_fitness(worst)  # deliberately NOT cached
                            stats["penalized"] += 1
                    logger.error(
                        "evaluate() exhausted %d pass(es); penalized %d "
                        "unfinished individual(s) with fitness %.6g (%s)",
                        stats["attempts"], stats["penalized"], worst, e,
                    )
                    stats["n_chips"] = self.broker.chips_seen()
                    return completed
                raise

    def _evaluate_once(self) -> int:
        """One ship-and-gather pass (no retry policy).

        This is the reference's population-level fitness override
        (SURVEY.md §3.2): genes out, fitness scalars back, barrier at the
        end of the sweep.  Before anything hits the wire, the fitness cache
        answers already-trained architectures, and duplicates within the
        sweep collapse to one job (``Individual.cache_key`` — SURVEY.md §7
        hard part #1); only genuinely new work reaches the workers.
        """
        tele = _tele.enabled()
        pending = [ind for ind in self.individuals if not ind.fitness_evaluated]
        n_before = len(pending)
        pending = self._fill_from_cache(pending)
        if tele and n_before > len(pending):
            _get_registry().counter(
                "population_cache_hits_total", species=self.species.__name__,
            ).inc(n_before - len(pending))
        if not pending:
            self._drop_predispatch()
            return 0
        adopted = self._adopt_predispatch(pending)
        if adopted is not None:
            by_id, dup_map = adopted
            self._spec_job_ids = set()
            logger.info("adopting %d pre-dispatched job(s) for this sweep", len(by_id))
            return self._gather_apply(list(by_id), by_id, dup_map)
        payloads, by_id, dup_map, rep_job = self._build_payloads(pending)
        if tele and len(pending) > len(payloads):
            _get_registry().counter(
                "population_dedup_collapsed_total", species=self.species.__name__,
            ).inc(len(pending) - len(payloads))
        n_spec = 0
        if self.speculative_fill and payloads:
            # Tail-generation mitigation (VERDICT r4 weak #2): a capacity
            # worker pads a small batch to the compile-shape bucket anyway
            # (models/cnn._pop_bucket) — ship speculative elite-mutant jobs
            # to occupy those otherwise-wasted slots.  Their fitnesses land
            # in the cache only (the individuals are not population
            # members), answering future generations' children for free.
            spec_inds = self._speculative_individuals(
                self._fill_target(len(payloads)) - len(payloads), set(rep_job)
            )
            spec_ids = set()
            params_copy = _params_copier()
            for spec in spec_inds:
                job_id = JobBroker.new_job_id()
                payloads[job_id] = {
                    "genes": spec.get_genes(),
                    "additional_parameters": params_copy(spec.additional_parameters),
                }
                by_id[job_id] = spec
                spec_ids.add(job_id)
                n_spec += 1
            # Remembered for the failure paths: partial-result counting in
            # evaluate() must not credit speculative jobs as population work.
            self._spec_job_ids = spec_ids
        else:
            self._spec_job_ids = set()
        if tele and n_spec:
            _get_registry().counter(
                "population_speculative_total", species=self.species.__name__,
            ).inc(n_spec)
        logger.info(
            "distributing %d fitness evaluations (%d deduplicated, %d speculative)",
            len(payloads),
            len(pending) - (len(payloads) - n_spec),
            n_spec,
        )
        # The barrier covers REAL jobs only: a failed or straggling
        # speculative job must never abort, stall, or burn a retry of a
        # generation whose population work succeeded.  Speculative results
        # are collected best-effort afterwards (same worker batch, so they
        # normally sit in the results channel already).
        real_ids = [j for j in payloads if j not in self._spec_job_ids]
        if _tele.enabled():
            # Cross-process trace propagation (docs/OBSERVABILITY.md): the
            # live master-side span context (normally the generation's
            # `evaluate` span) rides every job payload; workers re-attach
            # it so their train/eval spans join this trace.
            ctx = _lineage.forensic_context(_tele.current_context())
            if ctx is not None:
                for payload in payloads.values():
                    payload["trace"] = ctx
        self.broker.submit(payloads, session=self._session_arg)
        # Speculative jobs don't count as population work: the GA's
        # individuals/hour metric stays a statement about real individuals.
        return self._gather_apply(real_ids, by_id, dup_map)

    def _build_payloads(self, pending: Sequence[Individual]):
        """Wire payloads for ``pending`` with in-sweep dedup.

        Returns ``(payloads, by_id, dup_map, rep_job)``: duplicates within
        the sweep collapse to one representative job
        (``Individual.cache_key`` — SURVEY.md §7 hard part #1); only
        genuinely new work reaches the workers.
        """
        payloads: Dict[str, Dict[str, Any]] = {}
        by_id: Dict[str, Individual] = {}
        dup_map: Dict[str, List[Individual]] = {}
        rep_job: Dict[Any, str] = {}
        params_copy = _params_copier()
        for ind in pending:
            key = self._safe_cache_key(ind)
            if key is not None and key in rep_job:
                dup_map.setdefault(rep_job[key], []).append(ind)
                continue
            job_id = JobBroker.new_job_id()
            if key is not None:
                rep_job[key] = job_id
            payloads[job_id] = {
                "genes": ind.get_genes(),
                "additional_parameters": params_copy(ind.additional_parameters),
            }
            fidelity = getattr(ind, "_fidelity_tag", None)
            if fidelity is not None:
                payloads[job_id]["fidelity"] = dict(fidelity)
            by_id[job_id] = ind
        return payloads, by_id, dup_map, rep_job

    def _gather_apply(
        self,
        real_ids: List[str],
        by_id: Dict[str, Individual],
        dup_map: Dict[str, List[Individual]],
    ) -> int:
        """Barrier + fitness application for one sweep's real jobs."""
        try:
            results = self.broker.gather(real_ids, timeout=self.job_timeout)
        except JobFailed as e:
            # Keep the generation's finished work: apply every fitness that
            # DID come back, then surface the failures.  The broker pruned
            # its state (attempt counts included), so the defined retry is
            # simply calling evaluate() again — only the still-unevaluated
            # (= failed) individuals are reshipped, as fresh jobs.
            self._apply_results(e.partial, by_id, dup_map)
            self._collect_speculative(by_id, timeout=0.0)
            raise JobFailed(
                f"{len(e.failures)} of {len(real_ids)} job(s) failed permanently; "
                f"{len(e.partial)} successful result(s) were applied. "
                f"Call evaluate() again to reship only the failed individuals.",
                failures=e.failures,
                partial=e.partial,
            ) from e
        except GatherTimeout as e:
            # Straggler timeout: keep whatever finished before the deadline;
            # a retry (evaluate() again) reships only the unfinished work.
            self._apply_results(e.partial, by_id, dup_map)
            self._collect_speculative(by_id, timeout=0.0)
            raise
        self._apply_results(results, by_id, dup_map)
        self._collect_speculative(by_id, timeout=10.0)
        return len(real_ids)

    # -- breed-ahead pre-dispatch (pipelined generational mode) ------------

    def predispatch(self) -> int:
        """Ship this population's cache-missed work NOW, without waiting.

        The generational half of the pipelined dispatch plane
        (``GeneticAlgorithm(breed_ahead=True)``): called right after the
        next generation is bred, so its jobs travel while the master
        checkpoints/logs and the workers' prefetch queues refill during
        what used to be the inter-generation bubble.  The next
        ``evaluate()`` call adopts the in-flight jobs instead of
        re-submitting; if the population was mutated in between, the
        stale jobs are cancelled and evaluate() falls back to the normal
        build-and-submit path.  Returns the number of jobs shipped.
        """
        tele = _tele.enabled()
        pending = [ind for ind in self.individuals if not ind.fitness_evaluated]
        n_before = len(pending)
        pending = self._fill_from_cache(pending)
        if tele and n_before > len(pending):
            _get_registry().counter(
                "population_cache_hits_total", species=self.species.__name__,
            ).inc(n_before - len(pending))
        if not pending:
            self._pre = None
            return 0
        payloads, by_id, dup_map, _rep = self._build_payloads(pending)
        if tele and len(pending) > len(payloads):
            _get_registry().counter(
                "population_dedup_collapsed_total", species=self.species.__name__,
            ).inc(len(pending) - len(payloads))
        if tele:
            ctx = _lineage.forensic_context(_tele.current_context())
            if ctx is not None:
                for payload in payloads.values():
                    payload["trace"] = ctx
        self.broker.submit(payloads, session=self._session_arg)
        self._pre = (by_id, dup_map)
        logger.info("pre-dispatched %d job(s) for the next generation", len(payloads))
        return len(payloads)

    def _adopt_predispatch(self, pending: Sequence[Individual]):
        """Return ``(by_id, dup_map)`` if an earlier :meth:`predispatch`
        covers exactly this sweep's pending set; else cancel it and return
        ``None``.  Coverage is checked by object identity — any mutation
        of the population between breed-ahead and evaluate() (caller
        edits, partial retry passes) safely voids the pre-dispatch."""
        pre = getattr(self, "_pre", None)
        self._pre = None
        if pre is None:
            return None
        by_id, dup_map = pre
        covered = {id(ind) for ind in by_id.values()}
        for dups in dup_map.values():
            covered.update(id(d) for d in dups)
        if covered == {id(ind) for ind in pending}:
            return by_id, dup_map
        logger.info("pre-dispatched jobs stale (population changed); cancelling %d", len(by_id))
        self.broker.cancel(list(by_id))
        return None

    def _drop_predispatch(self) -> None:
        """Cancel any outstanding pre-dispatch (nothing pending to adopt it)."""
        pre = getattr(self, "_pre", None)
        self._pre = None
        if pre is not None:
            self.broker.cancel(list(pre[0]))

    def _collect_speculative(self, by_id: Dict[str, Individual], timeout: float) -> None:
        """Best-effort gather of the sweep's speculative jobs into the
        fitness cache.  Failures and stragglers are ignored (and the
        broker's gather prunes/cancels them), never surfaced."""
        spec_ids = getattr(self, "_spec_job_ids", set())
        if not spec_ids:
            return
        try:
            res = self.broker.gather(list(spec_ids), timeout=timeout)
        except (JobFailed, GatherTimeout) as e:
            res = dict(getattr(e, "partial", {}) or {})
            logger.info(
                "speculative job(s) incomplete — ignored (%s; %d result(s) kept)",
                type(e).__name__, len(res),
            )
        self._apply_results(res, by_id, {})

    def _apply_results(
        self,
        results: Dict[str, float],
        by_id: Dict[str, Individual],
        dup_map: Dict[str, List[Individual]],
    ) -> None:
        for job_id, fitness in results.items():
            ind = by_id[job_id]
            ind.set_fitness(fitness)
            key = self._safe_cache_key(ind)
            if key is not None:
                self.fitness_cache[key] = float(fitness)
            for dup in dup_map.get(job_id, []):
                dup.set_fitness(fitness)

    # -- generational continuity ------------------------------------------

    def clone_with(self, individuals: Sequence[Individual]) -> "DistributedPopulation":
        """Next-generation population sharing this one's running broker."""
        clone = DistributedPopulation(
            species=self.species,
            individual_list=list(individuals),
            crossover_rate=self.crossover_rate,
            mutation_rate=self.mutation_rate,
            maximize=self.maximize,
            additional_parameters=self.additional_parameters,
            rng=self.rng,
            job_timeout=self.job_timeout,
            broker=self.broker,
            fitness_cache=self.fitness_cache,
            evaluate_retries=self.evaluate_retries,
            failed_policy=self.failed_policy,
            speculative_fill=self.speculative_fill,
            # Session tenancy rides clones: re-opening is an idempotent
            # attach, so every generation keeps the same tag and share.
            session=self._session_arg,
            session_weight=self.session_weight,
            session_quota=self.session_quota,
        )
        clone.cache_namespace = self.cache_namespace
        # Carry the store path WITHOUT reloading the file every generation:
        # the clone shares this population's cache dict already.
        clone.fitness_store = self.fitness_store
        # Same for the shared-cache client: the ServiceBackedCache flowed in
        # through fitness_cache= above (the ctor only wraps when cache_url is
        # passed, which it isn't here), so hand over the client and the
        # registered status callable — whichever population gets close()d
        # flushes the write-behind queue and evicts the provider exactly once.
        clone.cache_url = self.cache_url
        clone._cache_client = self._cache_client
        clone._cache_status_fn = self._cache_status_fn
        # An embedded broker stays closeable through evolution: every clone
        # of an owning population co-owns it, so close() on whichever
        # population the caller ends up holding (the GA hands back clones)
        # stops the listener.  JobBroker.stop() is idempotent, so original +
        # clones closing in any order is safe.  Externally-provided brokers
        # (broker= at construction) are never owned and never stopped here.
        clone._owns_broker = self._owns_broker
        self._carry_spec_rng(clone)
        return clone


class DistributedGridPopulation(DistributedPopulation):
    """Grid-initialised distributed population (SURVEY.md §2.0 row 10).

    First generation enumerates the cartesian product of ``genes_grid``
    (like :class:`gentun_tpu.populations.GridPopulation`); later generations
    evolve as a plain :class:`DistributedPopulation` via ``clone_with``.
    """

    def __init__(
        self,
        species: Type[Individual],
        genes_grid: Optional[Mapping[str, Sequence[Any]]] = None,
        **kwargs,
    ):
        super().__init__(species, individual_list=[], **kwargs)
        self.populate_from_grid(genes_grid)
