"""Networked shared fitness memoization: never train a genome twice,
fleet-wide.

``utils/fitness_store.py`` already carries measurements across runs via a
shared JSON file — but a file only reaches processes that mount it.  This
module promotes the store to a small network service so *concurrent*
searches, elastic worker fleets, and sequential experiments on different
machines share one content-addressed genome→fitness cache (ROADMAP item 2:
cross-run dedup is "the cheapest throughput there is"; ASHA — Li et al.
2020 — is likewise built around a shared state service feeding an elastic
worker pool).

Three pieces, all stdlib:

- :class:`FitnessService` — a ``ThreadingHTTPServer`` daemon (the
  ``telemetry/ops_server.py`` pattern) holding a bounded LRU of
  ``digest:fingerprint → fitness``.  Entries are addressed by
  ``fitness_store.key_digest`` (64-bit blake2b of the canonical key JSON,
  the PR-1 hash width) **plus** the fidelity fingerprint
  (``fitness_store._key_fingerprint``), so a rung-0 proxy measurement can
  never answer a full-schedule lookup.  Requests carry ``STORE_VERSION``
  and ``FITNESS_PROTOCOL``; a mismatch is refused with HTTP 409 — the same
  all-writers-upgrade-together guard as the file store, enforced at the
  wire instead of at the file.
- :class:`FitnessServiceClient` — read-through lookups and write-behind
  publishes over plain ``urllib``.  Any network failure marks the service
  degraded for a cooldown window: the caller gets a miss (→ local-only
  operation), a ``fitness_service_degraded`` telemetry event records the
  transition, and the search NEVER sees an exception — cache downtime
  must not fail a search, exactly like a corrupt store file.
- :class:`ServiceBackedCache` — a ``dict`` subclass that layers the
  service over any local fitness cache.  Populations and engines consult
  ``fitness_cache`` via ``in``/``[]``/``.get`` and write via ``[k] = v``;
  overriding exactly those four operations extends PR-3's dispatch-side
  dedup through the service: a genome another run already measured
  completes instantly (never dispatched), and every new measurement is
  published for the next run.  In-flight *follower* attachment stays
  within one run — two runs evaluating the same genome at the same moment
  cost at most one duplicate training, after which both publish the same
  pure-function fitness.

Like the ops endpoints, the service is unauthenticated and binds
127.0.0.1 by default; bind a routable address only on a trusted network.
Run it standalone with ``python -m gentun_tpu.distributed.fitness_service
--port 9736``, or in-process via ``FitnessService(...).start()``.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.error
import urllib.request
from collections import OrderedDict, deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import urlparse

from ..telemetry import lineage as _lineage
from ..telemetry import spans as _tele
from ..telemetry.registry import get_registry as _get_registry
from ..utils.fitness_store import (
    FITNESS_PROTOCOL,
    STORE_VERSION,
    _key_fingerprint,
    is_serializable_key,
    key_digest,
)

__all__ = [
    "FitnessService",
    "FitnessServiceClient",
    "ServiceBackedCache",
    "parse_cache_url",
    "wire_key",
]

logger = logging.getLogger("gentun_tpu.distributed")

#: Request-body ceiling, matching the broker's frame ceiling: a publish
#: batch is never larger than one jobs window's worth of results.
_MAX_BODY_BYTES = 4 * 1024 * 1024


def parse_cache_url(url: str) -> str:
    """Validate a ``--cache-url`` value; returns it normalized.

    Raises ``ValueError`` with an operator-readable message on anything
    that is not ``http://host:port[/]`` — the worker CLI converts that to
    a loud ``SystemExit`` (a typo'd URL must not silently degrade a whole
    fleet to local-only caching).
    """
    parsed = urlparse(url)
    if parsed.scheme not in ("http", "https"):
        raise ValueError(
            f"cache url {url!r}: scheme must be http or https "
            f"(got {parsed.scheme or 'none'!r})")
    if not parsed.hostname:
        raise ValueError(f"cache url {url!r}: missing host")
    if parsed.port is None:
        raise ValueError(f"cache url {url!r}: missing port")
    if parsed.path not in ("", "/") or parsed.query or parsed.fragment:
        raise ValueError(
            f"cache url {url!r}: must be scheme://host:port with no "
            "path/query (endpoints are appended by the client)")
    return f"{parsed.scheme}://{parsed.hostname}:{parsed.port}"


def wire_key(key: Any) -> Optional[str]:
    """``digest:fingerprint`` service address for a cache key.

    None for keys that don't survive JSON (same skip rule as the file
    store — a dropped entry only costs a retrain).  The fingerprint rides
    in the address itself, so fidelity isolation needs no server logic:
    proxy and full-schedule measurements of one genome are simply two
    different entries.
    """
    if not is_serializable_key(key):
        return None
    return f"{key_digest(key)}:{_key_fingerprint(key)}"


class _Handler(BaseHTTPRequestHandler):
    """Request handler; ``self.server.service`` is the FitnessService."""

    server_version = "gentun-fitness/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # noqa: D102 - silence stderr chatter
        pass

    def _send_json(self, code: int, obj: Any) -> None:
        body = json.dumps(obj, separators=(",", ":")).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _read_body(self) -> Optional[Any]:
        try:
            n = int(self.headers.get("Content-Length", "0"))
        except ValueError:
            n = -1
        if not 0 < n <= _MAX_BODY_BYTES:
            self._send_json(413, {"error": f"body length {n} out of range"})
            return None
        try:
            return json.loads(self.rfile.read(n).decode())
        except (ValueError, UnicodeDecodeError) as e:
            self._send_json(400, {"error": f"bad json: {e}"})
            return None

    def _check_versions(self, msg: Dict[str, Any]) -> bool:
        """The wire-level all-writers-upgrade-together guard (409 on skew)."""
        version, proto = msg.get("version"), msg.get("protocol")
        if version != STORE_VERSION or proto != FITNESS_PROTOCOL:
            self._send_json(409, {
                "error": "version skew",
                "version": STORE_VERSION,
                "protocol": FITNESS_PROTOCOL,
                "client_version": version,
                "client_protocol": proto,
            })
            return False
        return True

    def do_GET(self):  # noqa: N802 - http.server API
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        svc = self.server.service  # type: ignore[attr-defined]
        if path in ("/", "/healthz"):
            self._send_json(200, {"status": "ok", **svc.stats()})
        elif path == "/statusz":
            self._send_json(200, svc.stats())
        else:
            self._send_json(404, {"error": f"no route {path}"})

    def do_POST(self):  # noqa: N802 - http.server API
        path = self.path.split("?", 1)[0].rstrip("/")
        svc = self.server.service  # type: ignore[attr-defined]
        msg = self._read_body()
        if msg is None:
            return
        if not isinstance(msg, dict) or not self._check_versions(msg):
            if not isinstance(msg, dict):
                self._send_json(400, {"error": "body must be an object"})
            return
        if path == "/v1/lookup":
            keys = msg.get("keys")
            if not isinstance(keys, list):
                self._send_json(400, {"error": "keys must be a list"})
                return
            self._send_json(200, {"hits": svc.lookup(keys)})
        elif path == "/v1/publish":
            entries = msg.get("entries")
            if not isinstance(entries, list):
                self._send_json(400, {"error": "entries must be a list"})
                return
            self._send_json(200, {"stored": svc.publish(entries)})
        elif path == "/v1/dataset/publish":
            space, rows = msg.get("space"), msg.get("rows")
            if not isinstance(space, str) or not isinstance(rows, list):
                self._send_json(400, {"error": "space must be a string and "
                                               "rows a list"})
                return
            self._send_json(200, {"stored": svc.publish_dataset(space, rows)})
        elif path == "/v1/dataset/fetch":
            space = msg.get("space")
            if not isinstance(space, str):
                self._send_json(400, {"error": "space must be a string"})
                return
            self._send_json(200, {
                "rows": svc.fetch_dataset(space, msg.get("limit"))})
        else:
            self._send_json(404, {"error": f"no route {path}"})


class FitnessService:
    """Bounded-LRU genome→fitness cache behind a ThreadingHTTPServer.

    State is a single ``OrderedDict`` under one lock — lookups
    ``move_to_end`` (recently *used* survives, not just recently
    written) and publishes evict from the cold end past ``max_entries``.
    Counters (hits/misses/evictions/puts) are served on ``/statusz`` and,
    when telemetry is enabled in the hosting process, mirrored to the
    metrics registry as ``fitness_service_{hits,misses,evictions}_total``
    so an in-process service surfaces on the master's ``/metrics``.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 max_entries: int = 100_000, max_dataset_rows: int = 50_000):
        if max_entries <= 0:
            raise ValueError(f"max_entries must be positive, got {max_entries}")
        self.max_entries = int(max_entries)
        self.max_dataset_rows = int(max_dataset_rows)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, float]" = OrderedDict()
        # Surrogate training rows, keyed (space, genome, rung) so
        # re-publishes dedup — the side table the rung −1 gate warm-starts
        # from and syncs with at refit boundaries (surrogate.py).  Bounded
        # like the fitness table: oldest rows fall off fleet-wide.
        self._dataset: "OrderedDict[Tuple[str, str, int], Dict[str, Any]]" = OrderedDict()
        self._dataset_puts = 0
        self._hits = 0
        self._misses = 0
        self._evictions = 0
        self._puts = 0
        self._started = time.time()
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.service = self  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    # -- address -----------------------------------------------------------

    @property
    def address(self) -> Tuple[str, int]:
        return self._httpd.server_address[:2]

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "FitnessService":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.25},
            name="fitness-service", daemon=True)
        self._thread.start()
        logger.info("fitness service serving on %s (max %d entries)",
                    self.url, self.max_entries)
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # -- cache ops (also usable in-process, no HTTP) -----------------------

    def lookup(self, keys: List[Any]) -> Dict[str, float]:
        hits: Dict[str, float] = {}
        n_miss = 0
        with self._lock:
            for k in keys:
                if isinstance(k, str) and k in self._entries:
                    self._entries.move_to_end(k)
                    hits[k] = self._entries[k]
                else:
                    n_miss += 1
            self._hits += len(hits)
            self._misses += n_miss
        if _tele.enabled():
            reg = _get_registry()
            if hits:
                reg.counter("fitness_service_hits_total").inc(len(hits))
            if n_miss:
                reg.counter("fitness_service_misses_total").inc(n_miss)
        return hits

    def publish(self, entries: List[Any]) -> int:
        stored = 0
        evicted = 0
        with self._lock:
            for entry in entries:
                if (not isinstance(entry, (list, tuple)) or len(entry) != 2
                        or not isinstance(entry[0], str)):
                    continue
                k, v = entry
                try:
                    self._entries[k] = float(v)
                except (TypeError, ValueError):
                    continue
                self._entries.move_to_end(k)
                stored += 1
            self._puts += stored
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
                evicted += 1
            self._evictions += evicted
        if evicted and _tele.enabled():
            _get_registry().counter("fitness_service_evictions_total").inc(evicted)
        return stored

    def publish_dataset(self, space: str, rows: List[Any]) -> int:
        """Store surrogate training rows under a per-tenant space key.

        A row is ``{"genome": key, "genes": {...}, "rung": r,
        "fitness": f}``; the service treats ``genes`` opaquely (each
        master re-encodes with its own feature map), validating only the
        dedup key and the label.  Rows keyed ``(space, genome, rung)``,
        latest measurement wins."""
        stored = 0
        with self._lock:
            for row in rows:
                if not isinstance(row, dict):
                    continue
                genome = row.get("genome")
                if not isinstance(genome, str) or not isinstance(
                        row.get("genes"), dict):
                    continue
                try:
                    rung = int(row.get("rung", 0))
                    fitness = float(row["fitness"])
                except (KeyError, TypeError, ValueError):
                    continue
                key = (str(space), genome, rung)
                self._dataset[key] = {"genome": genome, "genes": row["genes"],
                                      "rung": rung, "fitness": fitness}
                self._dataset.move_to_end(key)
                stored += 1
            self._dataset_puts += stored
            while len(self._dataset) > self.max_dataset_rows:
                self._dataset.popitem(last=False)
        return stored

    def fetch_dataset(self, space: str, limit: Any = None) -> List[Dict[str, Any]]:
        """The space's rows, oldest first (a bounded trainer keeps the
        freshest when it truncates from the front)."""
        try:
            cap = None if limit is None else max(0, int(limit))
        except (TypeError, ValueError):
            cap = None
        with self._lock:
            rows = [row for (sp, _, _), row in self._dataset.items()
                    if sp == space]
        if cap is not None and len(rows) > cap:
            rows = rows[-cap:]
        return rows

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "entries": len(self._entries),
                "max_entries": self.max_entries,
                "dataset_rows": len(self._dataset),
                "dataset_puts": self._dataset_puts,
                "hits": self._hits,
                "misses": self._misses,
                "evictions": self._evictions,
                "puts": self._puts,
                "uptime_s": round(time.time() - self._started, 3),
                "version": STORE_VERSION,
                "protocol": FITNESS_PROTOCOL,
            }


class FitnessServiceClient:
    """Read-through lookups + write-behind publishes, degradation-safe.

    Every network failure (refused, timeout, 5xx, version skew) marks the
    service down for ``cooldown`` seconds: during the window lookups
    return misses and publishes queue (bounded) without touching the
    socket, so a dead service costs one timeout per cooldown — not one
    per genome.  The down transition emits ONE ``fitness_service_degraded``
    telemetry event and a warning; recovery logs at info.  Nothing in
    this class ever raises into the caller.
    """

    def __init__(self, url: str, timeout: float = 2.0, cooldown: float = 5.0,
                 max_pending: int = 10_000):
        self.url = parse_cache_url(url)
        self.timeout = float(timeout)
        self.cooldown = float(cooldown)
        self._down_until = 0.0
        self._degraded = False
        self._lock = threading.Lock()
        self._hits = 0
        self._misses = 0
        self._degraded_total = 0
        # Write-behind: measurements queue here and a daemon flusher ships
        # them in batches, so a publish never adds an RTT to the search
        # loop.  Bounded: when the service is down for a whole run the
        # queue drops oldest-first (those entries simply stay local).
        self._pending: deque = deque(maxlen=max_pending)
        self._wake = threading.Event()
        self._closed = False
        self._flusher: Optional[threading.Thread] = None

    # -- availability ------------------------------------------------------

    def available(self) -> bool:
        with self._lock:
            return time.monotonic() >= self._down_until

    @property
    def degraded(self) -> bool:
        with self._lock:
            return self._degraded

    def _mark_down(self, err: Exception) -> None:
        with self._lock:
            self._down_until = time.monotonic() + self.cooldown
            first = not self._degraded
            self._degraded = True
            self._degraded_total += 1
        if first:
            logger.warning(
                "fitness service %s unreachable (%s); degrading to "
                "local-only caching, retrying every %.1fs — the search "
                "continues, new measurements stay local until it returns",
                self.url, err, self.cooldown)
            _tele.record_event("fitness_service_degraded", {
                "url": self.url, "error": str(err)[:200],
            })
            if _tele.enabled():
                _get_registry().counter("fitness_service_degraded_total").inc()

    def _mark_up(self) -> None:
        with self._lock:
            was = self._degraded
            self._degraded = False
        if was:
            logger.info("fitness service %s reachable again", self.url)

    # -- http --------------------------------------------------------------

    def _post(self, endpoint: str, payload: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        body = dict(payload)
        body["v"] = 1
        body["version"] = STORE_VERSION
        body["protocol"] = FITNESS_PROTOCOL
        req = urllib.request.Request(
            self.url + endpoint,
            data=json.dumps(body, separators=(",", ":")).encode(),
            headers={"Content-Type": "application/json"},
            method="POST")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                out = json.loads(resp.read().decode())
            self._mark_up()
            return out
        except Exception as e:  # noqa: BLE001 - degradation boundary by design
            self._mark_down(e)
            return None

    # -- API ---------------------------------------------------------------

    def lookup(self, keys: List[str]) -> Dict[str, float]:
        """``{wire_key: fitness}`` for the hits; {} on miss or degradation."""
        if not keys or not self.available():
            return {}
        out = self._post("/v1/lookup", {"keys": list(keys)})
        if out is None:
            return {}
        hits = out.get("hits")
        if not isinstance(hits, dict):
            return {}
        clean: Dict[str, float] = {}
        for k, v in hits.items():
            try:
                clean[k] = float(v)
            except (TypeError, ValueError):
                continue
        with self._lock:
            self._hits += len(clean)
            self._misses += len(keys) - len(clean)
        return clean

    def publish_dataset(self, space: str, rows: List[Dict[str, Any]]) -> Optional[int]:
        """Ship surrogate training rows; ``None`` on degradation/failure.

        Synchronous by design — the rung −1 gate calls this only at refit
        boundaries (every ``refit_every`` completions), never on the
        score-on-breed hot path, and it needs the verdict to decide
        whether to degrade to admit-all (surrogate.py)."""
        if not self.available():
            return None
        out = self._post("/v1/dataset/publish",
                         {"space": str(space), "rows": list(rows)})
        if out is None:
            return None
        try:
            return int(out.get("stored", 0))
        except (TypeError, ValueError):
            return 0

    def fetch_dataset(self, space: str,
                      limit: Optional[int] = None) -> Optional[List[Dict[str, Any]]]:
        """The space's training rows; ``None`` on degradation/failure
        (distinct from ``[]``, a healthy-but-empty space)."""
        if not self.available():
            return None
        payload: Dict[str, Any] = {"space": str(space)}
        if limit is not None:
            payload["limit"] = int(limit)
        out = self._post("/v1/dataset/fetch", payload)
        if out is None:
            return None
        rows = out.get("rows")
        return rows if isinstance(rows, list) else []

    def publish(self, entries: List[Tuple[str, float]]) -> None:
        """Queue entries for the write-behind flusher (never blocks)."""
        if not entries or self._closed:
            return
        self._pending.extend(entries)
        if self._flusher is None:
            with self._lock:
                if self._flusher is None and not self._closed:
                    self._flusher = threading.Thread(
                        target=self._flush_loop, name="fitness-publish",
                        daemon=True)
                    self._flusher.start()
        self._wake.set()

    def _drain_batch(self, cap: int = 512) -> List[Tuple[str, float]]:
        batch: List[Tuple[str, float]] = []
        while self._pending and len(batch) < cap:
            try:
                batch.append(self._pending.popleft())
            except IndexError:  # pragma: no cover - racing producer
                break
        return batch

    def _flush_loop(self) -> None:
        while True:
            self._wake.wait(timeout=0.5)
            self._wake.clear()
            if self._closed and not self._pending:
                return
            if not self._pending:
                continue
            if not self.available():
                if self._closed:
                    return  # closing while degraded: entries stay local
                time.sleep(min(0.5, self.cooldown))
                continue
            batch = self._drain_batch()
            if batch and self._post(
                    "/v1/publish",
                    {"entries": [[k, float(v)] for k, v in batch]}) is None:
                # Failed mid-flight: requeue so a transient blip doesn't
                # drop measurements (deque maxlen bounds the worst case).
                self._pending.extendleft(reversed(batch))

    def flush(self, timeout: float = 5.0) -> bool:
        """Best-effort wait for the write-behind queue to drain."""
        deadline = time.monotonic() + timeout
        self._wake.set()
        while self._pending and time.monotonic() < deadline:
            if not self.available():
                return False
            time.sleep(0.02)
        return not self._pending

    def close(self, flush_timeout: float = 2.0) -> None:
        """Flush what we can, then stop the flusher thread."""
        self.flush(timeout=flush_timeout)
        self._closed = True
        self._wake.set()
        t = self._flusher
        if t is not None:
            t.join(timeout=1.0)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            total = self._hits + self._misses
            return {
                "url": self.url,
                "hits": self._hits,
                "misses": self._misses,
                "hit_rate": round(self._hits / total, 4) if total else None,
                "degraded": self._degraded,
                "degraded_total": self._degraded_total,
                "pending_publish": len(self._pending),
            }


class ServiceBackedCache(dict):
    """A fitness cache that reads through to, and publishes to, the service.

    Drop-in for any ``Population.fitness_cache`` (it IS a dict, so
    checkpoints iterate it and ``clone_with`` shares it by identity
    unchanged).  Local entries always win — the service is only consulted
    on a local miss, and every hit is adopted locally so the second
    touch of a key never pays an RTT.  Writes go local first, then to the
    write-behind queue.  Only JSON-serializable keys ever reach the wire;
    the rest behave exactly like a plain dict entry.

    Client-side hit/miss counters land in the metrics registry
    (``fitness_service_{hits,misses}_total``) when telemetry is on, so
    the MASTER's ``/metrics`` and ``/statusz`` show its own hit rate even
    when the service runs on another machine.
    """

    def __init__(self, client: FitnessServiceClient,
                 seed: Optional[Dict[Any, float]] = None,
                 namespace: Optional[str] = None):
        super().__init__(seed or {})
        self.client = client
        self.namespace = str(namespace) if namespace else None
        self._wire_keys: Dict[Any, Optional[str]] = {}

    def _wire_key(self, key: Any) -> Optional[str]:
        try:
            wk = self._wire_keys[key]
        except KeyError:
            wk = wire_key(key)
            # An explicit namespace opts a tenant OUT of cross-tenant
            # dedup: its service entries live under a disjoint key prefix.
            # Default (None) keeps content-addressed sharing on.
            if wk is not None and self.namespace is not None:
                wk = f"{self.namespace}/{wk}"
            self._wire_keys[key] = wk
        except TypeError:  # unhashable key: nothing upstream produces one,
            return None    # but a cache must never crash a search
        return wk

    def _service_get(self, key: Any):
        """Service lookup on local miss → fitness or None; adopts hits."""
        wk = self._wire_key(key)
        if wk is None:
            return None
        hits = self.client.lookup([wk])
        if _tele.enabled():
            reg = _get_registry()
            if wk in hits:
                reg.counter("fitness_service_hits_total").inc()
            else:
                reg.counter("fitness_service_misses_total").inc()
        if wk in hits:
            fitness = float(hits[wk])
            super().__setitem__(key, fitness)
            # Lineage: a service hit means some OTHER search already paid
            # for this training — identity here is the wire key (the
            # fitness-cache content address), not genome_key.
            _lineage.record("cache_hit", wk, source="service")
            return fitness
        return None

    # -- the four operations populations/engines actually use --------------

    def __contains__(self, key: Any) -> bool:
        if super().__contains__(key):
            return True
        return self._service_get(key) is not None

    def get(self, key: Any, default: Any = None) -> Any:
        if super().__contains__(key):
            return super().__getitem__(key)
        hit = self._service_get(key)
        return default if hit is None else hit

    def __getitem__(self, key: Any) -> Any:
        if super().__contains__(key):
            return super().__getitem__(key)
        hit = self._service_get(key)
        if hit is None:
            raise KeyError(key)
        return hit

    def __setitem__(self, key: Any, value: Any) -> None:
        super().__setitem__(key, float(value))
        wk = self._wire_key(key)
        if wk is not None:
            self.client.publish([(wk, float(value))])

    def rebase(self, mapping: Dict[Any, float]) -> None:
        """Replace local contents, keep the service backing (checkpoint
        resume rebuilds ``fitness_cache`` from the saved state; without
        this hook the restore would silently discard the service layer)."""
        super().clear()
        super().update(mapping)

    def stats(self) -> Dict[str, Any]:
        return {**self.client.stats(), "local_entries": len(self)}


def main(argv=None) -> int:
    """Standalone service: ``python -m gentun_tpu.distributed.fitness_service``."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m gentun_tpu.distributed.fitness_service",
        description="shared genome→fitness memoization service "
                    "(point masters/workers at it with --cache-url)")
    ap.add_argument("--host", default="127.0.0.1",
                    help="bind address (default 127.0.0.1; the endpoints "
                         "are unauthenticated — bind a routable address "
                         "only on a trusted network)")
    ap.add_argument("--port", type=int, default=9736,
                    help="listen port (0 picks an ephemeral port, logged)")
    ap.add_argument("--max-entries", type=int, default=100_000,
                    help="LRU capacity before cold entries evict")
    args = ap.parse_args(argv)
    if not 0 <= args.port <= 65535:
        raise SystemExit(f"--port must be in [0, 65535], got {args.port}")
    if args.max_entries <= 0:
        raise SystemExit(f"--max-entries must be positive, got {args.max_entries}")
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    svc = FitnessService(host=args.host, port=args.port,
                         max_entries=args.max_entries).start()
    print(f"fitness service on {svc.url} (ctrl-C to stop)", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        svc.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
