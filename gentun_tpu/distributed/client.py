"""Worker-side client: owns the data, trains genes shipped by the master.

Reference parity: ``GentunClient`` in ``gentun/client.py`` [PUB][BASELINE]
(SURVEY.md §2.0 row 11, §3.3).  Preserved behaviors:

- the worker holds ``(x_train, y_train)``; only genes + hyperparameters
  arrive, only fitness scalars leave;
- ``work()`` is a blocking consume loop: pop job → rebuild individual from
  genes → ``get_fitness()`` (the hot path) → reply → ack.  Here the ack IS
  the ``result`` message (ack-after-work): a worker that dies mid-job never
  acks, and the broker redelivers (at-least-once, SURVEY.md §5);
- evaluation errors are reported (``fail``) rather than crashing the loop,
  and the broker decides between redelivery and giving up.

TPU-first extension: ``capacity > 1`` asks the broker for several jobs at
once; jobs sharing one config are evaluated as a single vmapped population
program via ``Population.evaluate`` (``models/cnn.py``), which is how one
TPU worker keeps its chip saturated even mid-generation.  Heartbeats run on
a side thread so a minutes-long jitted train step doesn't make a healthy
worker look dead.
"""

from __future__ import annotations

import logging
import socket
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Type

from ..individuals import Individual
from ..populations import Population
from ..telemetry import health as _health
from ..telemetry import lineage as _lineage
from ..telemetry import spans as _tele
from ..telemetry.registry import get_registry as _get_registry
from .protocol import (
    MAX_MESSAGE_BYTES,
    WIRE_CAPS,
    AuthError,
    ProtocolError,
    coalesce_results,
    decode,
    encode,
    expand_jobs2,
    parse_caps,
)

__all__ = ["GentunClient"]

logger = logging.getLogger("gentun_tpu.distributed")


class _ReconnectBackoff:
    """Capped exponential backoff with decorrelated jitter.

    A fixed reconnect delay synchronizes a fleet: every worker that lost
    the same master retries in lockstep, stampeding the reborn broker at
    the exact same instants forever.  Decorrelated jitter (the AWS
    formula: ``sleep_{n+1} = min(cap, uniform(base, 3 * sleep_n))``)
    spreads the fleet out while still backing off exponentially toward
    the cap.  The stream is seeded from the worker id — deterministic
    per worker (reproducible chaos runs), decorrelated across a fleet —
    and :meth:`reset` re-arms the base delay after any successful
    connection.
    """

    def __init__(self, base: float, cap: float, seed: str):
        import random

        self._base = max(1e-3, float(base))
        self._cap = max(self._base, float(cap))
        self._rng = random.Random(seed)  # str-seeded: stable across runs
        self._next = self._base

    def reset(self) -> None:
        self._next = self._base

    def next_delay(self) -> float:
        d = self._next
        self._next = min(self._cap, self._rng.uniform(self._base, 3.0 * d))
        return d


class _ShardConn:
    """One worker↔shard connection (multi-homed worker, ISSUE 18).

    Everything a single-homed ``GentunClient`` keeps as instance state —
    socket, read stream, granted caps, boot epoch — lives HERE per shard,
    plus the pieces that make shard independence real:

    - ``backoff``: this connection's OWN reconnect backoff (the satellite
      fix — one flapping shard inflating its delay toward the cap must
      never slow redials to healthy shards), seeded per (worker, shard)
      so a fleet's reconnects stay decorrelated per shard too.
    - ``gen``: redial generation.  Batches are enqueued tagged with the
      gen that received them; a batch whose gen is stale by evaluation
      time came from a dead connection — the broker already requeued
      those jobs at disconnect, so evaluating them would only duplicate
      work the fleet is already redoing.
    """

    __slots__ = ("host", "port", "shard", "sock", "rfile", "write_lock",
                 "handshaken", "boot_id", "caps", "backoff", "gen", "dead")

    def __init__(self, host: str, port: int, backoff: _ReconnectBackoff):
        self.host, self.port = host, int(port)
        self.shard = f"{host}:{port}"
        self.sock: Optional[socket.socket] = None
        self.rfile = None
        self.write_lock = threading.Lock()
        self.handshaken = False
        self.boot_id: Optional[str] = None
        self.caps: frozenset = frozenset()
        self.backoff = backoff
        self.gen = 0
        #: terminal auth rejection — never redialed again.
        self.dead = False


class GentunClient:
    """Connects to the master's broker and evaluates individuals forever.

    Parameters mirror the reference constructor
    (``GentunClient(IndividualCls, x_train, y_train, host, user, password)``
    [PUB]); ``user`` is accepted for signature parity but unused, ``password``
    maps to the broker token.

    - ``species``: the Individual subclass to rebuild from wire genes.
    - ``capacity``: max jobs held at once (1 = reference semantics; >1 lets
      a TPU worker train a whole batch in one compiled program).  The
      string ``"auto"`` switches on **host-mesh mode**: this worker is one
      HOST driving all of its local devices through the ``(pop, data)``
      evaluation mesh, and capacity is DERIVED from that mesh
      (``parallel.mesh.host_worker_capacity``: compile bucket × pop-axis
      size) instead of typed in — so the dispatch window is always a
      shape the compiled evaluator wants, re-advertised via
      :meth:`remesh` when the device set changes.
    - ``mesh_devices``: override the probed device count host-mesh mode
      derives from (default ``jax.device_count()``).  For tests and chaos
      drills — jax cannot simulate gaining or losing a device in-process —
      and for non-jax species that want mesh-derived windows anyway.
    - ``mesh_override``: pin the ``(pop, data)`` factoring instead of the
      heuristic — a ``"POPxDATA"`` string (the worker's ``--mesh`` flag)
      or a tuple.  Malformed or non-factoring values raise ``ValueError``
      at the point the device count is known, and :meth:`remesh`
      re-validates against the post-change count.
    - ``prefetch_depth``: jobs queued locally BEYOND ``capacity`` so the
      next window is already decoded when the current one finishes
      (double buffering — a background receive thread feeds a local
      ready-queue while the evaluate loop trains, hiding the
      results→breed→dispatch round trip).  ``None`` (default) means
      ``capacity``; ``0`` restores the exact pre-pipelining serial loop
      (bit-identical frame sequence).  Clamped to ``[0, 4 × capacity]``,
      mirroring the broker's own clamp.  An old broker that ignores the
      hello field simply never grants the extra credit — the worker
      degrades to the serial flow without protocol errors.
    - ``heartbeat_interval``: seconds between pings from the side thread.
    - ``reconnect_delay``: INITIAL delay after a lost connection; subsequent
      attempts back off exponentially with decorrelated jitter up to
      ``reconnect_max_delay`` (and reset to the initial delay on success),
      so a fleet's reconnects never stampede a restarted broker in lockstep.
    - ``fault_injector``: optional ``distributed.faults.FaultInjector`` for
      deterministic chaos testing; ``None`` (default) is zero-cost.
    - ``compile_cache_url``: the fleet-wide compiled-executable cache
      (``distributed/compile_service.py``).  At join and after
      :meth:`remesh` — before capacity is (re-)advertised — the worker
      prefetches the fleet's XLA cache entries for its platform
      fingerprint into the local cache dir, and publishes whatever it
      compiles first.  A malformed URL raises ``ValueError`` here (the
      worker CLI converts it to ``SystemExit``); service downtime never
      fails a search, it only costs recompiles.
    - ``multihost``: this worker is ONE logical worker spanning a
      multi-process jax cluster (``jax.distributed`` already initialized —
      see ``parallel/multihost.py``).  Process 0 alone owns the broker
      connection; every process executes the same evaluation program, with
      job payloads broadcast over the device fabric.  Off by default so
      single-host workers (and non-jax species) never touch a jax backend
      just to consume jobs.
    """

    def __init__(
        self,
        species: Type[Individual],
        x_train,
        y_train,
        host: str = "127.0.0.1",
        port: int = 5672,
        user: Optional[str] = None,
        password: Optional[str] = None,
        capacity=1,
        prefetch_depth: Optional[int] = None,
        mesh_devices: Optional[int] = None,
        mesh_override=None,
        heartbeat_interval: float = 3.0,
        reconnect_delay: float = 1.0,
        reconnect_max_delay: float = 30.0,
        worker_id: Optional[str] = None,
        multihost: bool = False,
        n_chips: Optional[int] = None,
        fitness_store: Optional[str] = None,
        cache_url: Optional[str] = None,
        compile_cache_url: Optional[str] = None,
        aggregator_url: Optional[str] = None,
        fault_injector=None,
        wire_caps: Optional[tuple] = None,
        preemptible: bool = False,
        broker_urls: Optional[list] = None,
    ):
        self.species = species
        self.x_train = x_train
        self.y_train = y_train
        self.host = host
        self.port = int(port)
        self.token = password
        # Host-mesh mode (capacity="auto"): the host is the unit of fleet
        # membership.  The mesh shape is remembered so the hello/advertise
        # frames can carry it and the pipelined re-chunker can align
        # windows to the pop-axis multiple (zero padding waste, one
        # compiled batch shape).
        self._mesh_shape: Optional[tuple] = None  # (pop, data) axis sizes
        self._mesh_devices: Optional[int] = None
        # Operator mesh override (worker ``--mesh POPxDATA``): pins the
        # (pop, data) factoring instead of the heuristic.  Accepted as a
        # "POPxDATA" string or a (pop, data) tuple; malformed values raise
        # ValueError here (the worker CLI converts to SystemExit).  The
        # override is installed process-wide (``parallel.mesh
        # .set_mesh_override``) so the evaluator's ``auto_mesh`` honors it
        # without touching the wire config — cache keys and fitness
        # fingerprints stay unchanged — and it is re-validated against the
        # live device count on every capacity derivation (join, remesh).
        self._mesh_override: Optional[tuple] = None
        if mesh_override is not None:
            from ..parallel.mesh import parse_mesh_spec, set_mesh_override

            if isinstance(mesh_override, str):
                mesh_override = parse_mesh_spec(mesh_override)
            self._mesh_override = (int(mesh_override[0]), int(mesh_override[1]))
            set_mesh_override(self._mesh_override)  # validates positivity
        self._mesh_auto = isinstance(capacity, str)
        if self._mesh_auto:
            if str(capacity).strip().lower() != "auto":
                raise ValueError(
                    f"capacity must be a positive integer or 'auto', got {capacity!r}")
            capacity = self._derive_mesh_capacity(mesh_devices)
        self.capacity = max(1, int(capacity))
        #: True when the operator pinned prefetch explicitly — remesh()
        #: then respects it instead of tracking the derived capacity.
        self._prefetch_explicit = prefetch_depth is not None
        if prefetch_depth is None:
            prefetch_depth = self.capacity
        self.prefetch_depth = max(0, min(int(prefetch_depth), 4 * self.capacity))
        self.heartbeat_interval = float(heartbeat_interval)
        self.reconnect_delay = float(reconnect_delay)
        self.reconnect_max_delay = float(reconnect_max_delay)
        self.worker_id = worker_id or f"{socket.gethostname()}-{uuid.uuid4().hex[:8]}"
        # Preemptible capacity (protocol.py "Preemptible-capacity field"):
        # advertised on hello/advertise so the broker's placement routes
        # cheap rung-0 probes here and pins promotions to stable members.
        # False is the wire default — a stable worker never sends the key.
        self.preemptible = bool(preemptible)
        # Drain attribution for the NEXT drain frame ("drain"|"preempt");
        # "drain" is the wire default and is never sent explicitly.
        self._drain_reason = "drain"
        self._injector = fault_injector
        # Wire fast path (protocol.py "Wire fast path"): capabilities this
        # worker ADVERTISES on hello; what the broker GRANTS comes back on
        # welcome and gates which frame types may arrive.  ``wire_caps=()``
        # pins the v1 frame set (ops kill switch, mixed-fleet tests).
        self._wire_caps = tuple(WIRE_CAPS if wire_caps is None else wire_caps)
        self._broker_caps: frozenset = frozenset()
        # Broker boot epoch (OPTIONAL on welcome; only journaled brokers
        # send one).  Echoed back on results/fail frames so a restarted
        # broker can tell a live completion from a stale pre-crash one.
        self._boot_id: Optional[str] = None
        # Memoized wire-telemetry handles + 1-in-N encode sampling state
        # (same memoize-or-die discipline as the broker's).
        self._wire_counters: Dict[str, tuple] = {}
        self._encode_hist = None
        self._encode_samples = 0
        self._n_chips = None if n_chips is None else max(1, int(n_chips))
        self.multihost = bool(multihost)
        # Worker-side cross-run fitness reuse (VERDICT r4 weak #6): the store
        # is loaded ONCE, read-only, and seeds every evaluation Population's
        # fitness cache — cache keys embed additional_parameters, so reuse is
        # training-config-exact.  New measurements accumulate in memory (so a
        # repeated genome later in the same session also hits) but are never
        # written back; persistence stays the master's job.
        if fitness_store and multihost:
            # Followers replay the leader's batches; a store file present on
            # one host but not another would diverge the compiled program
            # shapes mid-collective.  Refuse loudly instead.
            raise ValueError("fitness_store is not supported for multihost workers")
        if fitness_store:
            from ..utils.fitness_store import load_fitness_cache

            self._store_cache: Optional[dict] = load_fitness_cache(fitness_store)
            # Snapshot of what the FILE held: the live dict also accumulates
            # this session's measurements (deliberately — later repeats hit
            # without retraining), but only file entries count as cross-run
            # reuse in the log.
            self._store_keys = frozenset(self._store_cache)
            logger.info(
                "worker fitness store %s: %d entries loaded (read-only)",
                fitness_store, len(self._store_cache),
            )
        else:
            self._store_cache = None
            self._store_keys = frozenset()
        # Networked shared fitness cache (distributed/fitness_service.py):
        # layers read-through/write-behind service access over whatever the
        # local store loaded, so a genome ANY run already measured is
        # answered without training — and every new measurement is
        # published for the rest of the fleet.  Refused for multihost
        # workers for the same reason as fitness_store: a service hit on
        # one host but not another would diverge the ranks' compiled
        # programs mid-collective.
        self._cache_client = None
        if cache_url:
            if multihost:
                raise ValueError("cache_url is not supported for multihost workers")
            from .fitness_service import FitnessServiceClient, ServiceBackedCache

            self._cache_client = FitnessServiceClient(cache_url)
            self._store_cache = ServiceBackedCache(
                self._cache_client, self._store_cache or {})
        # Fleet-wide compile cache (distributed/compile_service.py):
        # prefetch the fleet's compiled artifacts into the local XLA cache
        # dir at join (and after remesh) so this worker loads instead of
        # compiling, and publish whatever it compiles first.  Refused for
        # multihost workers: the cache dir is per-host, so the leader
        # cannot prefetch for its followers — a warm rank 0 racing cold
        # ranks into the collectives would look exactly like a hang.
        self._compile_client = None
        if compile_cache_url:
            if multihost:
                raise ValueError(
                    "compile_cache_url is not supported for multihost workers")
            from .compile_service import CompileServiceClient

            self._compile_client = CompileServiceClient(
                compile_cache_url,
                probe_devices=getattr(species, "uses_jax", False))
        # Fleet observability (telemetry/aggregator.py): the URL is only
        # validated here (loud ValueError → SystemExit in the CLI); the
        # pusher itself starts with work() and stops when work() returns,
        # under this worker's id as the fleet instance label.
        self._aggregator_url = None
        if aggregator_url:
            from ..telemetry.aggregator import parse_aggregator_url

            self._aggregator_url = parse_aggregator_url(aggregator_url)
        self._pusher = None
        if self.multihost:
            from ..parallel import multihost as mh  # imports jax (opt-in only)

            self._mh = mh
            self._is_leader = mh.is_leader()
        else:
            self._mh = None
            self._is_leader = True

        self._sock: Optional[socket.socket] = None
        self._rfile = None
        self._write_lock = threading.Lock()
        self._stop = threading.Event()
        self._handshaken = threading.Event()  # gates heartbeats until welcome
        self._jobs_done = 0
        self._last_batch_end: Optional[float] = None  # worker_idle_s anchor
        # Elastic membership: drain() arms this; the consume loops notice
        # at the next batch boundary, announce the drain to the broker
        # (returning queued-but-unstarted jobs), and work() exits cleanly.
        self._drain_req = threading.Event()
        self._work_stop: Optional[threading.Event] = None
        # Multi-homing (ISSUE 18, horizontal broker sharding): with
        # ``broker_urls=[...]`` of length >1 this worker holds ONE
        # connection per shard — per-connection receive threads, per-shard
        # credit windows and backoff — so a stalled or dead shard can
        # never block dispatch on healthy shards.  A one-element list
        # collapses to the plain host/port path, wire byte-identical.
        self._addrs: Optional[List[tuple]] = None
        self._conns: List[_ShardConn] = []
        if broker_urls:
            from .shard import parse_broker_urls

            addrs = parse_broker_urls(broker_urls)
            self.host, self.port = addrs[0]
            if len(addrs) > 1:
                if self.multihost:
                    # One leader connection is the multihost contract —
                    # followers replay ITS batches; two shards' interleaved
                    # windows would diverge the ranks' compiled programs.
                    raise ValueError(
                        "broker_urls multi-homing is not supported for "
                        "multihost workers")
                if self._injector is not None:
                    # Frame-counted fault schedules assume one connection;
                    # shard chaos drills kill brokers instead (chaos_run.py
                    # shard_kill).
                    raise ValueError(
                        "fault_injector is not supported with multi-shard "
                        "broker_urls")
                self._addrs = addrs

    # -- host-mesh capacity ------------------------------------------------

    def _derive_mesh_capacity(self, n_devices: Optional[int] = None) -> int:
        """Capacity from the local device mesh (host-mesh mode).

        ``parallel.mesh.host_worker_capacity``: factor the devices into
        the ``(pop, data)`` mesh the evaluator will build, then size the
        window to compile bucket × pop-axis — a shape that shards with
        zero padding and is already in the compile cache after the first
        window.  ``n_devices=None`` probes ``jax.device_count()`` (the
        GLOBAL count: a multihost worker's mesh spans its whole slice),
        which requires a jax species; tests and non-jax species pass the
        count explicitly.  Records the shape for the hello/advertise
        frames, the re-chunker, and the ``mesh_*`` gauges.
        """
        from ..parallel.mesh import host_worker_capacity

        if n_devices is None:
            if not getattr(self.species, "uses_jax", False):
                raise ValueError(
                    f"capacity='auto' derives from the local device mesh, but "
                    f"species {self.species.__name__} never initializes a jax "
                    f"backend — pass mesh_devices= or an integer capacity")
            import jax  # the fitness path initializes this backend anyway

            n_devices = max(1, int(jax.device_count()))
        pop_o, data_o = self._mesh_override or (None, None)
        capacity, pop_axis, data_axis = host_worker_capacity(
            n_devices, pop_axis=pop_o, data_axis=data_o)
        self._mesh_devices = int(n_devices)
        self._mesh_shape = (pop_axis, data_axis)
        reg = _get_registry()
        reg.gauge("mesh_pop_axis").set(pop_axis)
        reg.gauge("mesh_data_axis").set(data_axis)
        logger.info(
            "host-mesh worker %s: %d device(s) -> mesh (pop=%d, data=%d), "
            "derived capacity %d", self.worker_id if hasattr(self, "worker_id")
            else "?", n_devices, pop_axis, data_axis, capacity)
        return capacity

    def _mesh_advert(self) -> Optional[Dict[str, int]]:
        """The OPTIONAL ``mesh`` wire field (protocol.py "Host-mesh
        field"), or None for per-chip workers."""
        if self._mesh_shape is None:
            return None
        return {"pop": self._mesh_shape[0], "data": self._mesh_shape[1],
                "devices": self._mesh_devices or 0}

    def remesh(self, n_devices: Optional[int] = None) -> None:
        """Re-derive capacity from the current device mesh and re-advertise.

        The elastic half of host-mesh mode: when the host's device set
        changes (a chip lost to hardware fault, a co-tenant releasing
        devices, a restarted runtime finding fewer cores), the worker's
        window must follow — the broker clamps credit immediately on the
        ``advertise`` frame, in-flight jobs finish unaffected.
        ``n_devices`` overrides the probe (tests / chaos drills).  Only
        meaningful in host-mesh mode (``capacity="auto"``).
        """
        if not self._mesh_auto:
            raise ValueError("remesh() requires host-mesh mode (capacity='auto')")
        capacity = self._derive_mesh_capacity(n_devices)
        if self._prefetch_explicit:
            prefetch = min(self.prefetch_depth, 4 * capacity)
        else:
            prefetch = capacity  # the derived-window double-buffer default
        if self._compile_client is not None:
            # A remesh changes the mesh shape, i.e. the compile shapes the
            # next window needs.  Warm the local XLA cache BEFORE the
            # advertise frame restores credit, so the first post-remesh
            # window loads instead of compiling.
            self._compile_client.prefetch()
        self.advertise(capacity=capacity, prefetch_depth=prefetch)

    # -- connection --------------------------------------------------------

    def _fleet_chips(self) -> int:
        """Accelerator chips this logical worker spans, for the ``hello`` frame.

        The master divides its throughput metric by the connected fleet's
        chip total (``individuals/hour/chip`` — SURVEY.md §5 "Metrics"), so
        the advertisement must be honest: ``jax.device_count()`` is GLOBAL
        (``local_device_count × process_count``), which is exactly one
        multi-host worker's slice-wide chip count.  Species that never touch
        jax report 1 and never trigger a backend init here.  Override with
        the ``n_chips`` constructor kwarg.
        """
        if self._n_chips is None:
            if getattr(self.species, "uses_jax", False):
                import jax  # the fitness path initializes this backend anyway

                self._n_chips = max(1, int(jax.device_count()))
            else:
                self._n_chips = 1
        return self._n_chips

    def _connect(self) -> None:
        if self._injector is not None:
            self._injector.client_connect(self)  # may delay or refuse
        n_chips = self._fleet_chips()  # before the socket: may compile-init jax
        sock = socket.create_connection((self.host, self.port), timeout=10.0)
        sock.settimeout(None)
        self._sock = sock
        self._rfile = sock.makefile("rb")
        try:
            backend = self.species.fitness_backend()
        except Exception:  # never let an advisory field block the handshake
            backend = None
        hello = {
            "type": "hello",
            "worker_id": self.worker_id,
            "token": self.token,
            "capacity": self.capacity,
            "prefetch_depth": self.prefetch_depth,
            "n_chips": n_chips,
            "backend": backend,
        }
        mesh = self._mesh_advert()
        if mesh is not None:
            # OPTIONAL advisory field (protocol.py "Host-mesh field"):
            # old brokers ignore unknown hello keys.
            hello["mesh"] = mesh
        if self.preemptible:
            # OPTIONAL placement hint (protocol.py "Preemptible-capacity
            # field"): only ever sent as ``true`` — absent means stable,
            # so a stable worker's hello is byte-identical to before.
            hello["preemptible"] = True
        if self._wire_caps:
            # OPTIONAL capability advertisement (protocol.py "Wire fast
            # path"): old brokers ignore it and keep speaking v1 frames.
            hello["caps"] = list(self._wire_caps)
        self._send(hello)
        reply = self._recv()
        if reply.get("type") != "welcome":
            if reply.get("type") == "error" and reply.get("code") == "auth":
                raise AuthError(f"broker rejected credentials: {reply.get('reason')}")
            raise ConnectionError(f"broker rejected worker: {reply}")
        # What the broker GRANTED (old brokers grant nothing); only frames
        # in this set may arrive, so a v1 broker never surprises us.
        self._broker_caps = parse_caps(reply)
        # Journaled brokers stamp their boot epoch on welcome; we echo it
        # on every result so post-restart the new epoch can vet stale ones.
        self._boot_id = reply.get("boot_id")
        self._handshaken.set()
        # A reconnect gap is downtime, not a dispatch bubble: don't let it
        # pollute the worker_idle_s histogram.
        self._last_batch_end = None
        logger.info("worker %s connected to %s:%d", self.worker_id, self.host, self.port)

    def _close(self) -> None:
        self._handshaken.clear()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock = None
        self._rfile = None

    def _graceful_close(self) -> None:
        """FIN, then drain, then close — never RST away unread results.

        A plain ``close()`` on a socket whose receive buffer still holds
        unread broker frames emits RST, which destroys our just-sent
        result frames before the broker reads them.  Shut down the write
        side first (FIN queued AFTER the results), then read the
        connection to EOF so nothing is left unread, then close.

        Cost (ADVICE r4, accepted tradeoff): if the broker holds the
        connection open after our FIN, each ``recv`` may stall up to the
        2 s timeout before we give up and close anyway — a worst-case 2 s
        added to a clean ``work()`` teardown (reconnect-path closes don't
        come through here).  The stock broker responds to FIN by closing,
        so the drain normally completes in one round-trip.
        """
        sock = self._sock
        if sock is None:
            return
        try:
            sock.shutdown(socket.SHUT_WR)
            sock.settimeout(2.0)
            while sock.recv(4096):
                pass
        except OSError:
            pass  # broker already gone: nothing left to protect
        finally:
            self._close()

    def _send(self, msg: Dict[str, Any]) -> None:
        if self._injector is not None and self._injector.client_send(self, msg):
            return
        # Wire telemetry mirrors the broker's: per-type byte/frame counters
        # on every send, encode latency sampled 1-in-64 (coalesced results
        # frames arrive pre-encoded, so the sampled cost is honest about
        # the fast path).
        self._encode_samples += 1
        if (self._encode_samples & 63) == 0:
            t0 = time.perf_counter()
            data = encode(msg)
            if self._encode_hist is None:
                self._encode_hist = _get_registry().histogram(
                    "frame_encode_seconds", side="worker")
            self._encode_hist.observe(time.perf_counter() - t0)
        else:
            data = encode(msg)
        self._raw_send(data)
        mtype = str(msg.get("type"))
        handles = self._wire_counters.get(mtype)
        if handles is None:
            reg = _get_registry()
            handles = (reg.counter("wire_bytes_sent_total", type=mtype),
                       reg.counter("wire_frames_sent_total", type=mtype))
            self._wire_counters[mtype] = handles
        handles[0].inc(len(data))
        handles[1].inc()

    def _raw_send(self, data: bytes) -> None:
        with self._write_lock:
            sock = self._sock
            if sock is None:
                raise OSError("not connected")
            sock.sendall(data)

    def _recv(self, rfile=None) -> Dict[str, Any]:
        # `rfile` pins the read to ONE connection's stream: the pipelined
        # receiver thread captures it at spawn so a thread that outlives a
        # reconnect can never steal frames from the NEW connection.
        rfile = self._rfile if rfile is None else rfile
        line = rfile.readline(MAX_MESSAGE_BYTES + 2)
        if not line:
            raise ConnectionError("broker closed connection")
        msg = decode(line)
        if self._injector is not None:
            msg = self._injector.client_recv(self, msg)  # may delay or raise
        return msg

    # -- multi-home connection plumbing (ISSUE 18) --------------------------

    def _send_conn(self, conn: _ShardConn, msg: Dict[str, Any]) -> None:
        """Send one frame on ONE shard's connection (manager threads,
        heartbeats, credit replenish — anything that must not depend on
        which conn the evaluator currently has bound)."""
        data = encode(msg)
        with conn.write_lock:
            sock = conn.sock
            if sock is None:
                raise OSError("not connected")
            sock.sendall(data)
        mtype = str(msg.get("type"))
        handles = self._wire_counters.get(mtype)
        if handles is None:
            reg = _get_registry()
            handles = (reg.counter("wire_bytes_sent_total", type=mtype),
                       reg.counter("wire_frames_sent_total", type=mtype))
            self._wire_counters[mtype] = handles
        handles[0].inc(len(data))
        handles[1].inc()

    def _bind_conn(self, conn: _ShardConn) -> None:
        """Point the shared send path (``_send``/``_raw_send`` and the
        boot-epoch echo in ``_evaluate_batch``) at ONE shard for the
        duration of a batch.  Safe because the evaluator is the only
        thread that touches ``self._sock`` in multi-home mode — managers
        and heartbeats use conn-scoped sends."""
        self._sock = conn.sock
        self._rfile = conn.rfile
        self._boot_id = conn.boot_id
        self._broker_caps = conn.caps

    def _connect_conn(self, conn: _ShardConn) -> None:
        """Dial + handshake one shard (the multi-home mirror of
        :meth:`_connect`), with the OPTIONAL ``homes`` hello rider so the
        shard's ``/statusz`` reads this worker's capacity correctly."""
        n_chips = self._fleet_chips()  # before the socket: may compile-init jax
        sock = socket.create_connection((conn.host, conn.port), timeout=10.0)
        sock.settimeout(None)
        rfile = sock.makefile("rb")
        try:
            backend = self.species.fitness_backend()
        except Exception:  # never let an advisory field block the handshake
            backend = None
        hello = {
            "type": "hello",
            "worker_id": self.worker_id,
            "token": self.token,
            "capacity": self.capacity,
            "prefetch_depth": self.prefetch_depth,
            "n_chips": n_chips,
            "backend": backend,
            # OPTIONAL multi-home advertisement (protocol.py "Multi-home
            # field"): only multi-homed workers send it.
            "homes": len(self._addrs or ()) or 1,
        }
        mesh = self._mesh_advert()
        if mesh is not None:
            hello["mesh"] = mesh
        if self.preemptible:
            hello["preemptible"] = True
        if self._wire_caps:
            hello["caps"] = list(self._wire_caps)
        try:
            sock.sendall(encode(hello))
            line = rfile.readline(MAX_MESSAGE_BYTES + 2)
            if not line:
                raise ConnectionError(f"shard {conn.shard} closed during handshake")
            reply = decode(line)
        except BaseException:
            try:
                sock.close()
            except OSError:
                pass
            raise
        if reply.get("type") != "welcome":
            try:
                sock.close()
            except OSError:
                pass
            if reply.get("type") == "error" and reply.get("code") == "auth":
                raise AuthError(
                    f"shard {conn.shard} rejected credentials: {reply.get('reason')}")
            raise ConnectionError(f"shard {conn.shard} rejected worker: {reply}")
        conn.caps = parse_caps(reply)
        conn.boot_id = reply.get("boot_id")
        with conn.write_lock:
            conn.sock, conn.rfile = sock, rfile
        conn.gen += 1
        conn.handshaken = True
        self._handshaken.set()
        self._last_batch_end = None  # reconnect gap ≠ dispatch bubble
        logger.info("worker %s connected to shard %s", self.worker_id, conn.shard)

    def _close_conn(self, conn: _ShardConn) -> None:
        conn.handshaken = False
        with conn.write_lock:
            sock, conn.sock, conn.rfile = conn.sock, None, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _graceful_close_conn(self, conn: _ShardConn) -> None:
        """Teardown close for one shard: FIN, drain, close — the same
        RST-avoidance dance as :meth:`_graceful_close`."""
        conn.handshaken = False
        with conn.write_lock:
            sock, conn.sock, conn.rfile = conn.sock, None, None
        if sock is None:
            return
        try:
            sock.shutdown(socket.SHUT_WR)
            sock.settimeout(2.0)
            while sock.recv(4096):
                pass
        except OSError:
            pass
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _heartbeat_loop(self) -> None:
        """Pings from a side thread keep liveness visible during training.

        Only pings once the hello/welcome handshake is done (a ping as the
        first frame would be a protocol violation), and survives any race
        with ``_close`` nulling the socket mid-send.
        """
        while not self._stop.is_set():
            time.sleep(self.heartbeat_interval)
            if self._conns:
                # Multi-home fan-out: ping every live shard on ITS OWN
                # connection (liveness is per-shard — one stalled shard
                # must not mark this worker stale everywhere).  Beat on
                # any delivered ping: the worker process is alive iff at
                # least one shard can hear it.
                delivered = False
                for conn in list(self._conns):
                    if conn.dead or not conn.handshaken:
                        continue
                    try:
                        self._send_conn(conn, {"type": "ping"})
                    except Exception:
                        continue  # that shard's manager will redial
                    delivered = True
                if delivered:
                    _health.beat("worker_heartbeat")
                continue
            if not self._handshaken.is_set():
                continue
            inj = self._injector
            if inj is not None and inj.heartbeats_suppressed():
                continue  # injected hang: go silent while holding jobs
            try:
                # Pings bypass the send hook: they fire on wall-clock time,
                # so routing them through the injector would make fault
                # schedules (counted in frames) nondeterministic.  The ping
                # fault is `hang` (suppression above), not a frame fault.
                self._raw_send(encode({"type": "ping"}))
            except Exception:
                pass  # main loop will notice and reconnect
            else:
                # Beat only on a DELIVERED ping: an injected hang (above)
                # or dead socket leaves this worker's /healthz stale, the
                # same silence the broker's reaper sees.
                _health.beat("worker_heartbeat")

    # -- the consume loop --------------------------------------------------

    def work(self, max_jobs: Optional[int] = None, stop_event: Optional[threading.Event] = None) -> int:
        """Blocking consume loop (reference ``GentunClient.work()`` [PUB]).

        Returns the number of jobs completed (useful for tests); runs until
        ``stop_event`` is set or ``max_jobs`` results have been sent.

        Multi-host mode: process 0 runs this loop against the broker and
        broadcasts each received batch; processes > 0 never touch the
        socket — they loop on the broadcast and run the identical
        evaluation program, keeping every rank's jitted computations (and
        their ICI collectives) in lockstep.  A ``None`` broadcast is the
        shutdown sentinel, sent when the leader's loop exits for any reason.
        """
        if self.multihost and not self._is_leader:
            return self._work_follower()
        stop = stop_event or threading.Event()
        self._work_stop = stop  # shutdown() handle for signal-driven exits
        self._stop = threading.Event()
        self._jobs_done = 0  # each work() call gets a fresh budget
        # Ops-plane registration (dict writes, inert while the plane is
        # off): the ping thread's beat gates this process's /healthz — it
        # pings even during a long jitted train step, so only a genuinely
        # hung or disconnected worker goes stale.  The consume/evaluate
        # beats are advisory (a long compile legitimately silences them).
        _health.register_source(
            "worker_heartbeat", timeout=max(5.0, 4.0 * self.heartbeat_interval))
        _health.register_status_provider("worker", self._ops_status)
        if self._aggregator_url and self._pusher is None:
            from ..telemetry.aggregator import acquire_pusher

            self._pusher = acquire_pusher(
                self._aggregator_url, role="worker", instance=self.worker_id)
        hb = threading.Thread(target=self._heartbeat_loop, name="gentun-heartbeat", daemon=True)
        hb.start()
        if self._compile_client is not None:
            # Join-time warmup, BEFORE the first connect advertises
            # capacity: fetch the fleet's compiled artifacts so the first
            # dispatched window loads from the XLA disk cache instead of
            # compiling.  The hook lets models/_prepare_population_setup
            # trigger publish scans right after potential first compiles.
            from ..utils.xla_cache import register_publish_hook

            self._compile_client.prefetch()
            register_publish_hook(self._compile_client.publish_hook)
        backoff = _ReconnectBackoff(self.reconnect_delay, self.reconnect_max_delay, self.worker_id)
        try:
            if self._addrs is not None:
                # Multi-homed consume (ISSUE 18): one manager thread per
                # shard feeds a shared ready-queue; reconnect/backoff state
                # lives per connection inside each _ShardConn.
                self._work_multihome(stop, max_jobs)
            else:
                self._work_single(stop, max_jobs, backoff)
        finally:
            self._stop.set()
            self._graceful_close()
            if self._cache_client is not None:
                self._cache_client.close()
            if self._compile_client is not None:
                # close() unregisters the publish hook, runs a final scan
                # (catching entries the last batch wrote) and flushes.
                self._compile_client.close()
            _health.unregister_status_provider("worker", self._ops_status)
            _health.unregister_source("worker_heartbeat")
            if self._pusher is not None:
                from ..telemetry.aggregator import release_pusher

                release_pusher(self._pusher)
                self._pusher = None
            if self.multihost:
                self._mh.broadcast_payload(None)  # release the followers
        return self._jobs_done

    def _work_single(self, stop: threading.Event, max_jobs: Optional[int],
                     backoff: _ReconnectBackoff) -> None:
        """The single-connection consume/reconnect loop — the historical
        ``work()`` body, bit-identical frame flow."""
        while (not stop.is_set() and not self._drain_req.is_set()
               and (max_jobs is None or self._jobs_done < max_jobs)):
            try:
                self._connect()
                backoff.reset()  # a completed handshake re-arms the base delay
                self._consume(stop, max_jobs)
            except AuthError:
                # Deterministic rejection: reconnecting with the same
                # token can never succeed, so fail loudly instead of
                # spinning in the reconnect loop forever.
                logger.error("worker %s: broker rejected credentials; giving up", self.worker_id)
                raise
            except (ConnectionError, OSError, ProtocolError) as e:
                if (stop.is_set() or self._drain_req.is_set()
                        or (max_jobs is not None and self._jobs_done >= max_jobs)):
                    break
                delay = backoff.next_delay()
                logger.info("worker %s reconnecting in %.2gs after: %s", self.worker_id, delay, e)
                self._close()
                time.sleep(delay)

    def _work_multihome(self, stop: threading.Event,
                        max_jobs: Optional[int]) -> None:
        """Multi-homed consume (ISSUE 18): one manager thread per shard.

        Each :class:`_ShardConn` gets a daemon manager that owns its
        connect/receive/redial cycle end to end and feeds decoded batches
        into ONE shared ready-queue tagged ``(conn, gen, batch)``; this
        thread evaluates from the queue, acks each batch's credit back to
        the shard that dispatched it, and never blocks on any single
        shard's link — the per-shard independence the sharding design
        requires (a SIGKILLed shard costs only its own in-flight window,
        which its journal requeues).
        """
        import queue as _queue

        ready_q: "_queue.Queue" = _queue.Queue()
        self._conns = [
            _ShardConn(host, port, _ReconnectBackoff(
                self.reconnect_delay, self.reconnect_max_delay,
                f"{self.worker_id}:{host}:{port}"))
            for host, port in (self._addrs or ())
        ]
        _get_registry().gauge(
            "worker_homes", worker=self.worker_id).set(len(self._conns))
        for conn in self._conns:
            threading.Thread(
                target=self._shard_manager, args=(conn, stop, ready_q),
                name=f"gentun-shard-{conn.shard}", daemon=True).start()
        try:
            self._consume_multihome(stop, max_jobs, ready_q)
        finally:
            self._stop.set()  # managers: no more redials
            for conn in self._conns:
                self._graceful_close_conn(conn)
            # The shared send path may still point at a closed shard
            # socket; null it so work()'s _graceful_close is a no-op.
            self._sock = None
            self._rfile = None

    def _shard_manager(self, conn: _ShardConn, stop: threading.Event,
                       ready_q) -> None:
        """Own one shard's connection: dial, handshake, advertise the full
        credit window, then pump decoded batches into the shared queue.
        Redials under the conn's OWN backoff — a flapping shard inflates
        only its own delay (satellite regression: test_shard.py)."""
        while not (stop.is_set() or self._stop.is_set()
                   or self._drain_req.is_set()):
            try:
                self._connect_conn(conn)
                conn.backoff.reset()
                # Per-broker credit (ISSUE 18): each shard gets this
                # worker's FULL window — the worker picks work first-ready
                # across shards, so per-shard under-use costs nothing,
                # while a partitioned advertisement would idle the worker
                # whenever one shard had no tenants.
                self._send_conn(conn, {
                    "type": "ready",
                    "credit": self.capacity + self.prefetch_depth})
                gen = conn.gen
                rfile = conn.rfile  # pin: never read a future connection
                while True:
                    msg = self._recv(rfile=rfile)
                    if msg["type"] in ("jobs", "jobs2"):
                        jobs = (list(msg["jobs"]) if msg["type"] == "jobs"
                                else expand_jobs2(msg))
                        for chunk in self._chunk_jobs(jobs):
                            ready_q.put((conn, gen, chunk))
                    elif msg["type"] != "welcome":
                        logger.warning("unexpected message %r", msg["type"])
            except AuthError as e:
                # Terminal for THIS shard only: a healthy shard keeps this
                # worker alive; the consume loop raises only when every
                # shard has rejected us.
                conn.dead = True
                logger.error("worker %s: shard %s rejected credentials",
                             self.worker_id, conn.shard)
                ready_q.put((conn, conn.gen, e))
                return
            except (ConnectionError, OSError, ProtocolError) as e:
                if (stop.is_set() or self._stop.is_set()
                        or self._drain_req.is_set()):
                    break
                self._close_conn(conn)
                delay = conn.backoff.next_delay()
                logger.info("worker %s reconnecting to shard %s in %.2gs after: %s",
                            self.worker_id, conn.shard, delay, e)
                if stop.wait(delay):
                    break

    def _consume_multihome(self, stop: threading.Event,
                           max_jobs: Optional[int], ready_q) -> None:
        import queue as _queue

        while not stop.is_set() and (max_jobs is None or self._jobs_done < max_jobs):
            _health.beat("worker_consume")
            if self._drain_req.is_set():
                # Drain fan-out: hand every locally-queued batch back to
                # the shard that dispatched it, and announce the drain on
                # EVERY live connection so no shard redispatches here.
                returned: Dict[str, List[str]] = {}
                while True:
                    try:
                        conn, gen, item = ready_q.get_nowait()
                    except _queue.Empty:
                        break
                    if isinstance(item, list) and gen == conn.gen:
                        returned.setdefault(conn.shard, []).extend(
                            str(j["job_id"]) for j in item if "job_id" in j)
                for conn in self._conns:
                    if conn.dead or not conn.handshaken:
                        continue
                    self._announce_drain(returned.get(conn.shard, []), conn=conn)
                return
            try:
                conn, gen, item = ready_q.get(timeout=0.25)
            except _queue.Empty:
                continue
            if isinstance(item, BaseException):
                if all(c.dead for c in self._conns):
                    raise item  # every shard rejected this worker
                continue
            if gen != conn.gen or conn.sock is None:
                # Stale batch from a dead connection: the broker already
                # requeued these jobs at disconnect — evaluating them here
                # would only duplicate work the fleet is redoing.
                continue
            self._bind_conn(conn)
            try:
                self._evaluate_batch(item)
                # Replenish exactly this batch's credit AT ITS SHARD.
                self._send_conn(conn, {"type": "ready", "credit": len(item)})
            except (ConnectionError, OSError, ProtocolError) as e:
                logger.info("worker %s: shard %s link lost mid-batch: %s",
                            self.worker_id, conn.shard, e)
                if conn.gen == gen:
                    # Gen guard: the manager may have redialed already —
                    # never close a NEWER connection than the one we used.
                    self._close_conn(conn)

    def _ops_status(self) -> Dict[str, Any]:
        """The ``/statusz`` "worker" block when the ops plane runs inside
        a worker process (``--ops-port``)."""
        out = {
            "worker_id": self.worker_id,
            "capacity": self.capacity,
            "prefetch_depth": self.prefetch_depth,
            "jobs_done": self._jobs_done,
            "connected": self._handshaken.is_set(),
            "draining": self._drain_req.is_set(),
            "multihost": self.multihost,
            # Wire fast path: advertised vs broker-granted capabilities
            # (empty grant ⇔ a v1 broker on the other end).
            "wire_caps": sorted(self._wire_caps),
            "wire_caps_granted": sorted(self._broker_caps),
        }
        if self._mesh_shape is not None:
            # Host-mesh mode: the shape capacity was derived from.
            out["mesh"] = {"pop": self._mesh_shape[0],
                           "data": self._mesh_shape[1],
                           "devices": self._mesh_devices,
                           "derived_capacity": self._mesh_auto}
        # Padding-waste split (big-genome regime): slots trained and sliced
        # away on the pop axis vs batch lanes GSPMD pads on the data axis —
        # the two ways a misaligned schedule burns device time.
        _reg = _get_registry()
        out["pad_waste"] = {
            "pop": _reg.counter("eval_pad_waste_total").value,
            "data": _reg.counter("eval_data_pad_waste_total").value,
        }
        if self._cache_client is not None:
            out["fitness_service"] = self._cache_client.stats()
        if self._compile_client is not None:
            out["compile_cache"] = self._compile_client.stats()
        if self._conns:
            # Multi-home panel (ISSUE 18): one row per shard connection.
            out["homes"] = [{
                "shard": c.shard,
                "connected": c.handshaken,
                "dead": c.dead,
                "boot_id": c.boot_id,
                "wire_caps_granted": sorted(c.caps),
            } for c in self._conns]
        return out

    # -- elastic membership -------------------------------------------------

    @property
    def draining(self) -> bool:
        """True once :meth:`drain` or :meth:`shutdown` has been requested."""
        return self._drain_req.is_set()

    def drain(self, reason: str = "drain") -> None:
        """Request an orderly exit (elastic membership; thread-safe).

        The consume loop notices at its next batch boundary: the window
        currently training FINISHES and its results are delivered, any
        batches still queued locally are returned to the broker by id
        (redelivered to the rest of the fleet immediately), and
        :meth:`work` returns.  A worker blocked waiting for its first jobs
        in the serial (``prefetch_depth=0``) flow only notices when a
        frame arrives — use :meth:`shutdown` for an immediate hard stop.

        ``reason`` attributes the drain on the wire ("drain"|"preempt");
        the broker stamps it on the requeue lineage events so preemption
        churn is separable from operator drains.  Anything else degrades
        to "drain" broker-side.
        """
        if reason == "preempt":
            self._drain_reason = "preempt"
        self._drain_req.set()

    def shutdown(self) -> None:
        """Hard stop: set work()'s stop event (the broker's disconnect
        requeue covers everything in flight).  Thread-safe; the escalation
        path when a drain cannot complete (no more jobs coming)."""
        self._drain_req.set()  # don't reconnect on the way out
        stop = self._work_stop
        if stop is not None:
            stop.set()

    def advertise(self, capacity: Optional[int] = None,
                  prefetch_depth: Optional[int] = None) -> None:
        """Re-advertise capacity/prefetch to the broker (elastic membership).

        Updates the local values (the next evaluation window re-chunks to
        the new capacity) and sends the OPTIONAL ``advertise`` frame; an
        old broker logs-and-ignores it, leaving hello-time values in
        force.  Best-effort — a send failure surfaces on the next frame.
        """
        if capacity is not None:
            self.capacity = max(1, int(capacity))
        if prefetch_depth is not None:
            self.prefetch_depth = max(
                0, min(int(prefetch_depth), 4 * self.capacity))
        frame = {
            "type": "advertise",
            "capacity": self.capacity,
            "prefetch_depth": self.prefetch_depth,
        }
        mesh = self._mesh_advert()
        if mesh is not None:
            frame["mesh"] = mesh  # host-mesh shape rides along (OPTIONAL)
        if self.preemptible:
            frame["preemptible"] = True  # placement hint (OPTIONAL)
        try:
            self._send(frame)
        except OSError:
            pass  # reconnect hello re-advertises everything anyway

    def _announce_drain(self, unstarted_job_ids: List[str],
                        conn: Optional[_ShardConn] = None) -> None:
        """Send the ``drain`` frame; never raises (broker death during a
        drain just means the disconnect requeue does the whole job).
        ``conn`` routes the frame to ONE shard in multi-home mode."""
        frame: Dict[str, Any] = {"type": "drain",
                                 "requeue": list(unstarted_job_ids)}
        if self._drain_reason != "drain":
            # OPTIONAL attribution — the default is never sent, so an
            # operator drain's frame is byte-identical to before.
            frame["reason"] = self._drain_reason
        try:
            if conn is not None:
                self._send_conn(conn, frame)
            else:
                self._send(frame)
        except OSError:
            pass
        logger.info("worker %s draining: returned %d queued job(s)%s",
                    self.worker_id, len(unstarted_job_ids),
                    f" to shard {conn.shard}" if conn is not None else "")

    def _work_follower(self) -> int:
        """Non-leader ranks: evaluate what the leader broadcasts, reply never.

        The return value counts EVALUATIONS PERFORMED on this rank, which
        can exceed the leader's completed-job count when a connection drop
        makes the broker redeliver a batch (followers evaluate it twice,
        the leader replies once).  ``max_jobs`` does not apply here — the
        leader decides when the worker is done via the shutdown sentinel.
        """
        self._jobs_done = 0
        # Bounded exit if the leader dies without sending the sentinel
        # (SIGKILL/OOM): probe its coordination-service port and hard-exit
        # nonzero within ~10 s instead of hanging in the collective until
        # the runtime's own timeout (``parallel/multihost.py``).
        watchdog_stop = self._mh.start_leader_watchdog()
        try:
            while True:
                jobs = self._mh.broadcast_payload(None)
                if jobs is None:
                    return self._jobs_done
                self._evaluate_batch(jobs)
        finally:
            watchdog_stop.set()

    def _consume(self, stop: threading.Event, max_jobs: Optional[int]) -> None:
        if self.prefetch_depth == 0:
            self._consume_serial(stop, max_jobs)
        else:
            self._consume_pipelined(stop, max_jobs)

    def _consume_serial(self, stop: threading.Event, max_jobs: Optional[int]) -> None:
        """The pre-pipelining loop, preserved verbatim for ``prefetch_depth=0``.

        One ``ready`` → one blocking read → one evaluation per iteration:
        the worker sits idle for a full results→breed→dispatch round trip
        between windows, but the frame sequence is exactly the historical
        one — the bit-identity anchor for determinism and chaos tests.
        """
        while not stop.is_set() and (max_jobs is None or self._jobs_done < max_jobs):
            _health.beat("worker_consume")
            if self._drain_req.is_set():
                # Serial flow holds nothing locally: announce with an empty
                # requeue list (credit already granted is covered by the
                # disconnect requeue) and exit at this batch boundary.
                self._announce_drain([])
                return
            self._send({"type": "ready", "credit": self.capacity})
            # The broker delivers everything our credit allows as ONE `jobs`
            # frame (credit-based prefetch), so a capacity-N worker receives
            # its whole batch in a single blocking read — no drain window, no
            # read timeouts through the buffered reader, and the batch trains
            # as one vmapped program whatever the network latency was.
            # (Batches near the protocol size cap arrive split into several
            # frames, trained one frame per loop iteration — see protocol.py.)
            jobs = self._await_jobs()
            if self.multihost:
                # Ship the batch to every rank BEFORE evaluating: all
                # processes must enter the same jitted programs together.
                self._mh.broadcast_payload(jobs)
            self._evaluate_batch(jobs)

    def _consume_pipelined(self, stop: threading.Event, max_jobs: Optional[int]) -> None:
        """Double-buffered consume: receive decodes while evaluate trains.

        A background thread owns THIS connection's read side and feeds a
        local ready-queue of decoded job batches; the evaluate loop drains
        it.  The initial ``ready`` advertises the full window
        (``capacity + prefetch_depth``), so the broker keeps a next window
        queued at the worker while the current one trains — when a batch
        finishes, its successor is already decoded and the next program
        enqueues immediately (jax async dispatch overlaps host-side decode
        and result framing with device compute).  Each completed batch
        replenishes exactly its own credit, holding broker-side credit at
        the window ceiling.

        Fault composition: the receiver thread forwards its terminal
        exception through the queue, so broker death or an injected recv
        fault re-raises in this loop and takes the normal ``work()``
        reconnect path.  Batches still sitting in the local queue at
        disconnect are simply dropped — the broker's requeue-on-disconnect
        covers every dispatched-unacked job, queued-but-unstarted ones
        included (at-least-once, unchanged).
        """
        import queue as _queue

        rfile = self._rfile  # pin: never read a future connection's stream
        ready_q: "_queue.Queue" = _queue.Queue()

        def _receiver() -> None:
            try:
                while True:
                    msg = self._recv(rfile=rfile)
                    if msg["type"] in ("jobs", "jobs2"):
                        # Over-subscribed credit can coalesce up to
                        # capacity + prefetch_depth jobs into one frame;
                        # evaluate in capacity-sized (mesh-aligned)
                        # programs so prefetch changes WHEN work is
                        # decoded, never the compiled batch shape — or a
                        # poison genome's all-or-nothing blast radius
                        # (ack-after-work failure reporting stays per
                        # evaluation group).  A jobs2 frame expands its
                        # shared envelope once (protocol.py "Wire fast
                        # path") before the same chunking.
                        for chunk in self._chunk_frame(msg):
                            ready_q.put(chunk)
                    elif msg["type"] != "welcome":
                        logger.warning("unexpected message %r", msg["type"])
            except BaseException as e:  # forwarded, re-raised by the consumer
                ready_q.put(e)

        rx = threading.Thread(target=_receiver, name="gentun-recv", daemon=True)
        rx.start()
        # The receiver exits via its pinned rfile: when work() closes this
        # socket (reconnect or teardown), the blocked readline raises/EOFs
        # and the thread dies with it — no separate stop signal needed.
        self._send({"type": "ready", "credit": self.capacity + self.prefetch_depth})
        while not stop.is_set() and (max_jobs is None or self._jobs_done < max_jobs):
            _health.beat("worker_consume")
            if self._drain_req.is_set():
                # Batch boundary: the window we were evaluating has already
                # been acked.  Hand every batch still queued locally back to
                # the broker by id — those jobs redeliver to the rest of the
                # fleet NOW instead of waiting out our disconnect.
                unstarted: List[str] = []
                while True:
                    try:
                        item = ready_q.get_nowait()
                    except _queue.Empty:
                        break
                    if isinstance(item, list):
                        unstarted.extend(
                            str(j["job_id"]) for j in item if "job_id" in j)
                self._announce_drain(unstarted)
                return
            try:
                item = ready_q.get(timeout=0.25)
            except _queue.Empty:
                continue  # poll stop/max_jobs while the fleet is idle
            if isinstance(item, BaseException):
                raise item
            jobs = item
            if self.multihost:
                # Ship the batch to every rank BEFORE evaluating: all
                # processes must enter the same jitted programs together.
                self._mh.broadcast_payload(jobs)
            self._evaluate_batch(jobs)
            self._send({"type": "ready", "credit": len(jobs)})

    def _chunk_jobs(self, jobs: List[Dict[str, Any]]) -> List[List[Dict[str, Any]]]:
        """Split a ``jobs`` frame into evaluation-window batches.

        Windows are ``capacity``-sized; in host-mesh mode the window is
        additionally aligned DOWN to the mesh pop-axis multiple.  A
        capacity that is not a pop-multiple would pad EVERY window to the
        next multiple (``eval_pad_waste_total`` climbing forever) and
        alternate the compiled batch shape between full and tail windows;
        aligning down keeps every full window on ONE cached compile shape
        with zero padding.  Only a frame's final partial chunk can land
        off-multiple — it buckets and pads exactly as a small generation
        tail always has.  Per-chip workers (integer capacity, no mesh)
        keep the historical capacity-sized chunking bit-for-bit.

        Big-genome regime: jobs are first partitioned by size class
        (``parallel.mesh.job_size_class`` on the wire config — jax-free,
        micro-gated) so a window never mixes mesh shapes.  Small jobs keep
        the windowed chunking above; big/micro jobs get the per-class
        window ``host_worker_capacity`` derives for them — exactly 1, one
        genome per ``(1, n_devices)`` data-sharded program — and are
        emitted AFTER the small windows so each frame flips the mesh shape
        at most once (``mesh_reshapes_total``).  With no ``device_budget``
        in any job's config every job classifies small and the historical
        chunking is bit-for-bit unchanged.
        """
        from ..parallel.mesh import SIZE_SMALL, job_size_class

        n_dev = self._mesh_devices or 1
        small = []
        narrow = []
        for job in jobs:
            params = job.get("additional_parameters") if isinstance(job, dict) else None
            if job_size_class(params, n_dev) == SIZE_SMALL:
                small.append(job)
            else:
                narrow.append([job])
        step = self.capacity
        pop = self._mesh_shape[0] if self._mesh_shape else 1
        if pop > 1 and step % pop:
            step = max(pop, step - step % pop)
        chunks = [small[i:i + step] for i in range(0, len(small), step)]
        chunks.extend(narrow)
        return chunks

    def _chunk_frame(self, msg: Dict[str, Any]) -> List[List[Dict[str, Any]]]:
        """Expand one ``jobs``/``jobs2`` frame and chunk it for evaluation.

        A frame marked ``packed: true`` was sized broker-side as ONE
        mesh-aligned evaluation window (cross-session window packing,
        DISTRIBUTED.md) — it must come back from ``_chunk_jobs`` as
        exactly one chunk.  If it does not, the broker's capacity mirror
        (``_pack_step``) and this worker's advertisement disagree: log
        loudly, bump ``packed_window_resplit_total``, and evaluate the
        chunks anyway — degraded amortization, never dropped work.
        """
        jobs = (list(msg["jobs"]) if msg["type"] == "jobs"
                else expand_jobs2(msg))
        chunks = self._chunk_jobs(jobs)
        if msg.get("packed") is True and len(chunks) > 1:
            logger.error(
                "packed window of %d job(s) re-split into %d evaluation "
                "chunks on worker %s (capacity %d): broker and worker "
                "disagree on the window size; evaluating anyway",
                len(jobs), len(chunks), self.worker_id, self.capacity)
            _get_registry().counter("packed_window_resplit_total").inc()
        return chunks

    def _await_jobs(self) -> List[Dict[str, Any]]:
        while True:
            msg = self._recv()
            if msg["type"] == "jobs":
                return list(msg["jobs"])
            if msg["type"] == "jobs2":
                return expand_jobs2(msg)
            # Only "welcome" (handshake replay after reconnect) is benign;
            # the broker never replies to pings.
            if msg["type"] != "welcome":
                logger.warning("unexpected message %r", msg["type"])

    # -- evaluation --------------------------------------------------------

    def _evaluate_batch(self, jobs: List[Dict[str, Any]]) -> None:
        """Rebuild individuals from wire genes and train them.

        Jobs sharing identical ``additional_parameters`` go through
        ``Population.evaluate`` so the species' batched (vmapped) path is
        used when available; singletons fall back to ``get_fitness()``.
        """
        # worker_idle_s: the gap between consecutive evaluation batches on
        # this connection — the dispatch bubble the pipelined consume loop
        # exists to hide.  Anchored at the previous batch's END so training
        # time never counts as idleness; reconnect gaps are excluded
        # (anchor reset in _connect).
        _health.beat("worker_evaluate")
        t_start = time.monotonic()
        if _tele.enabled() and self._last_batch_end is not None:
            idle = t_start - self._last_batch_end
            _tele.record_span(
                "worker_idle", self._last_batch_end, idle,
                trace=jobs[0].get("trace") if jobs else None,
                attrs={"worker": self.worker_id},
            )
            _get_registry().histogram("worker_idle_s").observe(idle)
        # Grouping stays client-side (rather than delegating wholly to
        # Population.evaluate) so a raising group fails ONLY its own jobs;
        # the key matches populations._group_by_params: _freeze, collision-
        # free for numpy-array params, with unhashables isolated.
        from ..individuals import _freeze

        groups: Dict[Any, List[Dict[str, Any]]] = {}
        for job in jobs:
            try:
                # no_memo jobs (protocol.py "Canary messages": the canary
                # plane's dedup bypass) must never share a Population — and
                # therefore a fitness cache — with memoizing jobs.
                key = (_freeze(job.get("additional_parameters") or {}),
                       bool(job.get("no_memo")))
                hash(key)
            except TypeError:
                key = ("__unhashable__", id(job))
            groups.setdefault(key, []).append(job)

        for group in groups.values():
            params = group[0].get("additional_parameters") or {}
            # ONE defensive copy per evaluation group, shared by every
            # individual and the Population (wire fast path: a jobs2 window
            # already shares one decoded params object; this keeps the v1
            # path at one copy too instead of N+1).  Evaluators treat
            # additional_parameters as read-only — the grouping above keys
            # on its VALUE, so a mutating evaluator was already broken.
            shared_params = dict(params)
            individuals = []
            ok_jobs = []
            for job in group:
                # OPTIONAL per-job fidelity tag (protocol.py "Multi-fidelity
                # field"): validated BEFORE the individual is built, so an
                # unknown or mislabeled tag answers with a structured fail
                # frame — one lost job the master retries or re-routes — and
                # never a poison-genome crash or, worse, a wrong-schedule
                # fitness silently poisoning a rung.  Tagless jobs (old
                # masters) skip the check entirely.
                reason = self._check_fidelity(job)
                if reason is not None:
                    logger.warning("job %s rejected: %s", job["job_id"], reason)
                    self._try_send_fail(job["job_id"], reason)
                    continue
                try:
                    ind = self.species(
                        x_train=self.x_train,
                        y_train=self.y_train,
                        genes=job["genes"],
                        additional_parameters=shared_params,
                    )
                    individuals.append(ind)
                    ok_jobs.append(job)
                except Exception as e:  # bad genes off the wire
                    logger.exception("job %s: cannot build individual", job["job_id"])
                    self._try_send_fail(job["job_id"], f"build: {e!r}")
            if not individuals:
                continue
            # Canary dedup bypass: a no_memo group neither consults nor
            # publishes to the shared fitness store — every evaluation is
            # real, so a sealed golden genome keeps exercising the full
            # training path instead of memoizing after its first probe.
            no_memo = bool(group[0].get("no_memo"))
            pop = Population(
                self.species,
                x_train=self.x_train,
                y_train=self.y_train,
                individual_list=individuals,
                additional_parameters=shared_params,
                # None ⇒ fresh per-group cache (a no_memo group gets one too)
                fitness_cache=None if no_memo else self._store_cache,
            )
            try:
                inj = self._injector
                if inj is not None:
                    for job in ok_jobs:
                        inj.worker_pre_eval(self, job)
                # Count true store-FILE hits BEFORE evaluating: `trained`
                # alone can't distinguish store answers from in-batch dedup,
                # and same-session accumulated measurements aren't cross-run
                # reuse — this log exists to prove the latter.
                store_hits = 0
                if self._store_cache is not None and not no_memo:
                    store_hits = sum(
                        1 for ind in individuals
                        if pop._safe_cache_key(ind) in self._store_keys
                    )
                captured: Optional[List[Dict[str, Any]]] = None
                if _tele.enabled():
                    # Adopt the master's trace context off the job payload,
                    # collect every span this group produces (the `eval`
                    # wrapper plus Population.evaluate's nested `train` and
                    # any model-level compile/train/eval), and ship them
                    # home in the first result frame of the group.
                    eval_attrs: Dict[str, Any] = {"jobs": len(individuals)}
                    # Tenant attribution (protocol.py "Session messages"):
                    # a session-tagged group labels its worker-side spans.
                    session = ok_jobs[0].get("session")
                    if session:
                        eval_attrs["session"] = str(session)
                    t_eval0 = time.monotonic()
                    with _tele.attach(ok_jobs[0].get("trace")), _tele.capture() as captured:
                        with _tele.span("eval", eval_attrs):
                            pop.evaluate()
                        # Search forensics (telemetry/lineage.py): when the
                        # master stamped the forensics flag into the trace,
                        # split the group's device time into one `device`
                        # span per job — (session, genome, rung, worker)
                        # attribution cells.  Emitted INSIDE the capture so
                        # they ship home and the broker bills them (an
                        # in-process ledger write here would double-count).
                        if _lineage.wants_device_spans(ok_jobs[0].get("trace")):
                            share = (time.monotonic() - t_eval0) / len(ok_jobs)
                            for i, job in enumerate(ok_jobs):
                                _lineage.emit_device(
                                    share,
                                    # jobs2 entries carry the broker's
                                    # already-computed genome key; v1 jobs
                                    # fall back to hashing locally.
                                    job.get("gk") or _lineage.genome_key(job["genes"]),
                                    rung=(job.get("fidelity") or {}).get("rung", 0),
                                    session=str(session) if session else None,
                                    worker=self.worker_id,
                                    job=job["job_id"],
                                    start_monotonic=t_eval0 + i * share)
                    for rec in captured:
                        rec.setdefault("src", self.worker_id)
                else:
                    pop.evaluate()
                if store_hits:
                    logger.info(
                        "fitness store answered %d/%d job(s) without training",
                        store_hits, len(individuals),
                    )
                entries = []
                for job, ind in zip(ok_jobs, individuals):
                    fitness = ind.get_fitness()
                    if inj is not None and inj.take_fitness_corrupt(job["job_id"]):
                        # fitness_corrupt (faults.py): the eval succeeded but
                        # the reported number is wrong — the silent-corruption
                        # class only the canary's bit-equality check catches.
                        fitness = inj.corrupt_fitness(fitness)
                    entry = {"job_id": job["job_id"], "fitness": fitness}
                    if job.get("session"):
                        # Echo the tenant tag (OPTIONAL; the broker keys on
                        # job_id — the echo is for wire-level attribution).
                        entry["session"] = job["session"]
                    entries.append(entry)
                    self._jobs_done += 1
                if self._is_leader and entries:
                    # The whole capacity window acks as ONE `results` frame
                    # (protocol.coalesce_results) instead of a TCP frame per
                    # job — the worker-side half of the batched-dispatch
                    # contract, and the lever on the tail-regime RPC floor.
                    # The group's span report (capped well under the frame
                    # limit; spans are ~200 bytes each) rides the first frame.
                    for msg in coalesce_results(entries, spans=captured[:500] if captured else None):
                        if self._boot_id is not None:
                            # Epoch echo (OPTIONAL): lets a journal-restarted
                            # broker drop results minted under a prior boot.
                            msg["boot"] = self._boot_id
                        self._send(msg)
                    for entry in entries:
                        logger.info("job %s done: fitness %.6g", entry["job_id"], entry["fitness"])
            except Exception as e:
                # Evaluation is all-or-nothing per group: report every job so
                # the broker can redeliver (ack-after-work semantics).
                logger.exception("batch evaluation failed")
                for job in ok_jobs:
                    self._try_send_fail(job["job_id"], f"evaluate: {e!r}")
        self._last_batch_end = time.monotonic()
        if self._compile_client is not None:
            # Publish-after-first-compile for every species (the models-
            # layer hook only covers the jax CNN path): one dir-mtime stat
            # when nothing changed, a write-behind enqueue when the batch
            # just wrote new XLA cache entries.
            self._compile_client.scan_publish()

    @staticmethod
    def _check_fidelity(job: Dict[str, Any]) -> Optional[str]:
        """None when the job's fidelity tag is absent or checks out;
        otherwise the structured-``fail`` reason string.

        The tag's fingerprint must match what this worker computes from
        the SHIPPED ``additional_parameters`` — a mismatch means the
        master's rung label and the training schedule in the payload
        disagree (a mixed-version fleet, or a relabeled overlay), and
        training it would file a wrong-fidelity fitness under the rung's
        cache key.  Unknown tag versions are refused the same way rather
        than guessed at.
        """
        tag = job.get("fidelity")
        if tag is None:
            return None  # old master — pre-ladder protocol, evaluate as-is
        if not isinstance(tag, dict) or tag.get("v") != 1:
            return (f"fidelity: unknown tag version {tag!r}; this worker "
                    f"understands v=1 — upgrade the fleet together")
        from ..utils.fitness_store import fidelity_fingerprint

        expected = fidelity_fingerprint(job.get("additional_parameters") or {})
        if tag.get("fingerprint") != expected:
            return (f"fidelity: tag fingerprint {tag.get('fingerprint')!r} does "
                    f"not match the shipped config ({expected}) at rung "
                    f"{tag.get('rung')} — refusing a mislabeled schedule")
        return None

    def _try_send_fail(self, job_id: str, reason: str) -> None:
        if not self._is_leader:
            return  # follower ranks hold no connection; the leader reports
        try:
            msg = {"type": "fail", "job_id": job_id, "reason": reason[:2000]}
            if self._boot_id is not None:
                msg["boot"] = self._boot_id
            self._send(msg)
        except OSError:
            pass  # connection gone; broker requeues via disconnect path
