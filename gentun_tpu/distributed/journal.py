"""Crash-safe dispatch journal: the broker's write-ahead record of truth.

ISSUE 16 makes :class:`~.broker.JobBroker` restartable without losing a
search.  Nearly all dispatch state was already re-derivable — checkpoint
schema v4 holds the population, the lineage ledger holds genome history,
and PR-15's ``JobWire`` payloads are deterministic re-encodes — but the
broker's *routing* state (which sessions exist, which jobs are open,
which were dispatched and to whom, which results are parked undelivered)
lived only in the loop thread's dicts.  This module persists exactly that
state as an append-only JSONL journal with a periodic compacted snapshot,
so ``JobBroker(journal_path=...)`` replays to the pre-crash dispatch
picture and requeues every in-flight job through the existing
at-least-once path.

Design constraints, in order:

1. **Hot-path cost ≤ 2% of per-job dispatch cost** (gated by
   ``scripts/broker_throughput.py::run_journal_gate``).  The per-dispatch
   record is a pre-formatted ``%``-string append onto an in-memory list —
   no dict build, no ``json.dumps`` — and fsync is *batched*: a periodic
   flusher (the broker loop's journal task) does one
   ``writelines+flush+fsync`` per interval, never per record.  A large
   buffer triggers an inline non-fsync drain purely to bound memory.
2. **Torn tails must never poison replay.**  A crash (or the
   ``journal_io_error`` fault) can leave a partial final line.  Replay
   discards a torn LAST record loudly (log + ``journal_torn_tail_total``)
   and keeps everything before it; a corrupt record anywhere *else* in
   the file raises :class:`JournalCorruptError` — that is real damage,
   not a crash artifact, and silently skipping it could resurrect a
   completed job.
3. **Newer schemas are refused loudly** (:data:`JOURNAL_SCHEMA` fence):
   an old broker replaying a newer journal raises
   :class:`JournalSchemaError` instead of guessing at records it does not
   understand.

Record grammar (one JSON object per line, single-char ``t`` type tag)::

    meta {schema, boot, epoch}      first record of every broker boot
    so   {sid, w, q, r}             session open/attach (weight, quota, remote)
    sc   {sid}                      session closed
    sub  {j, sid, gk, p}            job submitted (full payload: re-warms the
                                    fragment cache + rebuilds exact wire bytes)
    d    {j}                        job dispatched to a worker (hot path)
    c    {j, f, pk}                 job completed (fitness; pk=1 if the result
                                    was parked in the session's undelivered
                                    queue rather than delivered)
    fl   {sid}                      a re-attached owner drained the session's
                                    undelivered queue (clears parked results)
    x    {j, r}                     job terminally failed
    q    {j}                        job requeued (informational — replay
                                    treats any sub without c/x as open)
    cx   {js}                       jobs cancelled (list)
    g    {sid, gk}                  genome quarantined for a session

Replay folds ``snapshot ∘ tail``: the compacted snapshot (written
atomically to ``<path>.snap`` via tmp+rename) captures the folded state
at compaction time; the journal is then truncated and re-seeded with a
fresh ``meta``.  Compaction replays the journal's *own* file offline —
there is no second live mirror of broker state to keep consistent.
"""

from __future__ import annotations

import json
import logging
import math
import os
import re
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from ..telemetry.registry import get_registry as _get_registry

__all__ = [
    "JOURNAL_SCHEMA",
    "JournalError",
    "JournalCorruptError",
    "JournalSchemaError",
    "ReplayState",
    "DispatchJournal",
    "replay_file",
]

logger = logging.getLogger("gentun_tpu.distributed")

#: Journal format version.  Bump on any record-grammar change; replay
#: refuses schemas NEWER than this loudly (fence), and accepts older ones
#: (all fields are optional-with-defaults, the protocol.py convention).
JOURNAL_SCHEMA = 1

#: Record types, for the ``journal_records_total{type}`` counter family.
RECORD_TYPES = ("meta", "so", "sc", "sub", "d", "c", "fl", "x", "q", "cx", "g")

#: Ids safe to splice into a hand-built record verbatim.  Broker-minted
#: ids (uuid hex) always match; anything else — job/session ids are
#: caller- and wire-provided arbitrary strings — takes the ``json.dumps``
#: path below so a quote, backslash, or newline can never tear a journal
#: line (or forge extra records).
_PLAIN_ID = re.compile(r"[A-Za-z0-9_.\-]*\Z").match


def _jid(s: str) -> str:
    """JSON-quote an id for a hand-built record (see :data:`_PLAIN_ID`)."""
    return '"%s"' % s if _PLAIN_ID(s) else json.dumps(s)


def _jfloat(f: float) -> str:
    """JSON-format a fitness.  ``repr`` of a non-finite float is bare
    ``nan``/``inf``, which ``json.loads`` rejects — so non-finite values
    are journaled as quoted strings and restored to float on replay."""
    return repr(f) if math.isfinite(f) else '"%s"' % repr(f)


def _unjfloat(f: Any) -> Any:
    """Inverse of :func:`_jfloat` for replayed ``c`` records."""
    return float(f) if isinstance(f, str) else f


class JournalError(RuntimeError):
    """Base class for journal replay failures."""


class JournalCorruptError(JournalError):
    """A record *before* the final line failed to parse — real corruption,
    not a crash-torn tail.  Replay refuses to guess."""


class JournalSchemaError(JournalError):
    """The journal (or snapshot) was written by a NEWER broker than this
    one.  Refused loudly: silently replaying records this version does not
    understand could drop or resurrect jobs."""


class ReplayState:
    """The folded journal: everything a restarted broker needs to re-adopt
    its pre-crash dispatch state.

    ``sessions`` maps sid -> ``{w, q, r, closed, quarantine, parked}``
    (weight, max_in_flight, remote flag, closed flag, quarantined genome
    keys, parked undelivered result frames).  ``jobs`` maps open job_id ->
    ``{sid, gk, p, d}`` (session, genome key, full payload, dispatched
    flag).  Every open job is *suspect* after a crash — the broker
    requeues all of them through the at-least-once path regardless of the
    dispatched flag (the flag only feeds the requeued-vs-queued books).
    """

    __slots__ = ("schema", "boot_id", "epoch", "sessions", "jobs",
                 "records", "torn_tail")

    def __init__(self) -> None:
        self.schema = JOURNAL_SCHEMA
        self.boot_id: Optional[str] = None
        self.epoch = 0
        self.sessions: Dict[str, Dict[str, Any]] = {}
        self.jobs: Dict[str, Dict[str, Any]] = {}
        self.records: Dict[str, int] = {}
        self.torn_tail = False

    # -- folding -----------------------------------------------------------

    def _session(self, sid: str) -> Dict[str, Any]:
        sess = self.sessions.get(sid)
        if sess is None:
            sess = self.sessions[sid] = {
                "w": 1.0, "q": None, "r": False, "closed": False,
                "quarantine": set(), "parked": [],
            }
        return sess

    def apply(self, rec: Dict[str, Any]) -> None:
        """Fold one journal record into the state.  Unknown types are
        ignored (an OLDER journal can never contain them thanks to the
        schema fence; a same-schema unknown type would be a bug we prefer
        to survive)."""
        t = rec.get("t")
        self.records[t] = self.records.get(t, 0) + 1
        if t == "meta":
            schema = int(rec.get("schema", 1))
            if schema > JOURNAL_SCHEMA:
                raise JournalSchemaError(
                    f"journal schema {schema} is newer than this broker's "
                    f"{JOURNAL_SCHEMA}; refusing to replay")
            self.schema = schema
            self.boot_id = rec.get("boot")
            self.epoch = int(rec.get("epoch", self.epoch or 1))
        elif t == "so":
            sess = self._session(str(rec["sid"]))
            sess["w"] = float(rec.get("w", 1.0))
            sess["q"] = rec.get("q")
            sess["r"] = bool(rec.get("r", False))
            sess["closed"] = False
        elif t == "sc":
            sid = str(rec["sid"])
            sess = self._session(sid)
            sess["closed"] = True
            sess["parked"] = []
            # A closed session's jobs are cancelled by the broker; the cx
            # record that follows pops them.  Defensive sweep anyway:
            for job_id in [j for j, job in self.jobs.items()
                           if job["sid"] == sid]:
                self.jobs.pop(job_id, None)
        elif t == "sub":
            sid = str(rec.get("sid", "default"))
            self._session(sid)  # implicit (default) sessions have no "so"
            self.jobs[str(rec["j"])] = {
                "sid": sid,
                "gk": rec.get("gk"),
                "p": rec.get("p") or {},
                "d": False,
            }
        elif t == "d":
            job = self.jobs.get(str(rec.get("j")))
            if job is not None:
                job["d"] = True
        elif t == "c":
            job = self.jobs.pop(str(rec.get("j")), None)
            if job is not None and rec.get("pk"):
                sess = self._session(job["sid"])
                if sess["r"] and not sess["closed"]:
                    sess["parked"].append({
                        "type": "results", "session": job["sid"],
                        "results": [{"job_id": str(rec.get("j")),
                                     "fitness": _unjfloat(rec.get("f"))}],
                    })
        elif t == "fl":
            self._session(str(rec["sid"]))["parked"] = []
        elif t == "x":
            self.jobs.pop(str(rec.get("j")), None)
        elif t == "q":
            job = self.jobs.get(str(rec.get("j")))
            if job is not None:
                job["d"] = False
        elif t == "cx":
            for job_id in rec.get("js", ()):
                self.jobs.pop(str(job_id), None)
        elif t == "g":
            self._session(str(rec["sid"]))["quarantine"].add(str(rec.get("gk")))

    # -- (de)hydration for the compacted snapshot --------------------------

    def to_snapshot(self) -> Dict[str, Any]:
        return {
            "schema": JOURNAL_SCHEMA,
            "epoch": self.epoch,
            "boot": self.boot_id,
            "sessions": {
                sid: {**sess, "quarantine": sorted(sess["quarantine"])}
                for sid, sess in self.sessions.items()
            },
            "jobs": self.jobs,
        }

    @classmethod
    def from_snapshot(cls, snap: Dict[str, Any]) -> "ReplayState":
        schema = int(snap.get("schema", 1))
        if schema > JOURNAL_SCHEMA:
            raise JournalSchemaError(
                f"snapshot schema {schema} is newer than this broker's "
                f"{JOURNAL_SCHEMA}; refusing to replay")
        state = cls()
        state.schema = schema
        state.epoch = int(snap.get("epoch", 0))
        state.boot_id = snap.get("boot")
        for sid, sess in (snap.get("sessions") or {}).items():
            state.sessions[str(sid)] = {
                "w": float(sess.get("w", 1.0)),
                "q": sess.get("q"),
                "r": bool(sess.get("r", False)),
                "closed": bool(sess.get("closed", False)),
                "quarantine": set(sess.get("quarantine") or ()),
                "parked": list(sess.get("parked") or ()),
            }
        for job_id, job in (snap.get("jobs") or {}).items():
            state.jobs[str(job_id)] = {
                "sid": str(job.get("sid", "default")),
                "gk": job.get("gk"),
                "p": job.get("p") or {},
                "d": bool(job.get("d", False)),
            }
        return state


def _read_tail(path: str) -> Tuple[List[Dict[str, Any]], bool]:
    """Parse the JSONL journal at ``path``.  Returns ``(records,
    torn_tail)``.  A final line that is incomplete (no trailing newline)
    or unparseable is a crash artifact: dropped loudly.  Damage anywhere
    else raises :class:`JournalCorruptError`."""
    with open(path, "rb") as fh:
        raw = fh.read()
    if not raw:
        return [], False
    lines = raw.split(b"\n")
    torn: Optional[bytes] = None
    if lines[-1] != b"":
        torn = lines.pop()          # no trailing newline: torn mid-write
    else:
        lines.pop()                 # drop the empty split artifact
    records: List[Dict[str, Any]] = []
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
            if not isinstance(rec, dict) or "t" not in rec:
                raise ValueError("not a journal record")
        except ValueError as e:
            if i == len(lines) - 1 and torn is None:
                torn = line         # complete line, torn payload
                break
            raise JournalCorruptError(
                f"journal record {i + 1} of {path} is corrupt "
                f"(not a crash-torn tail): {e}") from e
        records.append(rec)
    if torn is not None:
        logger.warning(
            "discarding torn journal tail (%d bytes) from %s — "
            "crash mid-append; replay continues from the previous record",
            len(torn), path)
        _get_registry().counter("journal_torn_tail_total").inc()
        return records, True
    return records, False


def replay_file(path: str) -> ReplayState:
    """Fold ``<path>.snap`` (if present) and the journal tail at ``path``
    into a :class:`ReplayState`.  Missing files replay to an empty state —
    a fresh broker with ``journal_path`` set starts at epoch 0 and boots
    into epoch 1."""
    snap_path = path + ".snap"
    if os.path.exists(snap_path):
        with open(snap_path, "r", encoding="utf-8") as fh:
            state = ReplayState.from_snapshot(json.load(fh))
    else:
        state = ReplayState()
    if os.path.exists(path):
        records, torn = _read_tail(path)
        for rec in records:
            state.apply(rec)
        state.torn_tail = torn
    return state


class DispatchJournal:
    """Append-only writer with batched fsync and offline compaction.

    Thread discipline mirrors the broker: every ``record_*`` call happens
    on the broker loop thread (or before the loop starts, during replay
    adoption) — the internal lock exists only for the ``status()``
    snapshot read from HTTP/ops threads and for the flusher.  ``flush``
    is called by the broker's periodic journal task; the hot path only
    appends pre-formatted strings to a list.
    """

    #: Inline (non-fsync) drain threshold — bounds buffer memory, never
    #: adds an fsync to the dispatch path.
    MAX_BUFFER = 4096
    #: Compact once this many records accumulate in the live file.
    COMPACT_EVERY = 50_000

    def __init__(self, path: str, fsync_interval: float = 0.05,
                 fault_injector: Any = None):
        self.path = path
        self.fsync_interval = float(fsync_interval)
        self._injector = fault_injector
        self._lock = threading.Lock()
        self._buf: List[str] = []
        self._fh = None
        self._wedged = False
        self._abandoned = False
        #: Set by an injected ``broker_crash`` fault; the broker's journal
        #: task turns it into an abrupt :meth:`JobBroker.kill`.
        self.crash_requested = False
        self._last_fsync = time.monotonic()
        self._records_since_compact = 0
        self._records_total: Dict[str, int] = {}
        self.boot_id = uuid.uuid4().hex[:12]
        self.epoch = 1
        self.replay_seconds = 0.0

    # -- boot --------------------------------------------------------------

    def open(self, state: Optional[ReplayState] = None) -> None:
        """Open for append.  With a replayed ``state`` the journal is
        immediately compacted to a snapshot of the *adopted* state (so the
        new boot's file starts from truth, not a replayed history) and the
        epoch advances past the replayed one."""
        if state is not None and state.epoch:
            self.epoch = state.epoch + 1
        if state is not None:
            state.epoch = self.epoch
            state.boot_id = self.boot_id
            self._write_snapshot(state.to_snapshot())
            self._fh = open(self.path, "w", encoding="utf-8")
        else:
            self._fh = open(self.path, "a", encoding="utf-8")
        self._append(json.dumps({"t": "meta", "schema": JOURNAL_SCHEMA,
                                 "boot": self.boot_id, "epoch": self.epoch},
                                separators=(",", ":")), "meta")
        self.flush()

    # -- hot-path appends --------------------------------------------------

    def _append(self, line: str, rtype: str) -> None:
        if self._wedged or self._abandoned:
            return
        self._buf.append(line)
        self._records_total[rtype] = self._records_total.get(rtype, 0) + 1
        self._records_since_compact += 1
        if len(self._buf) >= self.MAX_BUFFER:
            self._drain(fsync=False)

    def record_dispatch(self, job_id: str) -> None:
        """THE hot-path record — one per dispatched job.  Pre-formatted
        ``%``-string, no dict or dumps (see ``run_journal_gate``)."""
        self._append('{"t":"d","j":%s}' % _jid(job_id), "d")

    def record_submit(self, job_id: str, sid: str, gk: Optional[str],
                      payload: Dict[str, Any]) -> None:
        self._append(json.dumps(
            {"t": "sub", "j": job_id, "sid": sid, "gk": gk, "p": payload},
            separators=(",", ":"), default=str), "sub")

    def record_complete(self, job_id: str, fitness: float,
                        parked: bool = False) -> None:
        self._append('{"t":"c","j":%s,"f":%s,"pk":%d}'
                     % (_jid(job_id), _jfloat(float(fitness)),
                        1 if parked else 0), "c")

    def record_fail(self, job_id: str, reason: str) -> None:
        self._append(json.dumps({"t": "x", "j": job_id, "r": reason},
                                separators=(",", ":")), "x")

    def record_requeue(self, job_id: str) -> None:
        self._append('{"t":"q","j":%s}' % _jid(job_id), "q")

    def record_cancel(self, job_ids: List[str]) -> None:
        self._append(json.dumps({"t": "cx", "js": list(job_ids)},
                                separators=(",", ":")), "cx")

    def record_session_open(self, sid: str, weight: float,
                            max_in_flight: Optional[int],
                            remote: bool) -> None:
        self._append(json.dumps(
            {"t": "so", "sid": sid, "w": weight, "q": max_in_flight,
             "r": remote}, separators=(",", ":")), "so")

    def record_session_close(self, sid: str) -> None:
        self._append('{"t":"sc","sid":%s}' % _jid(sid), "sc")

    def record_flush(self, sid: str) -> None:
        self._append('{"t":"fl","sid":%s}' % _jid(sid), "fl")

    def record_quarantine(self, sid: str, gk: str) -> None:
        self._append(json.dumps({"t": "g", "sid": sid, "gk": gk},
                                separators=(",", ":")), "g")

    # -- durability --------------------------------------------------------

    def _drain(self, fsync: bool) -> None:
        """Write the buffer out.  The ``journal_write`` fault hook can
        inject a torn write here: a prefix of the pending bytes lands on
        disk and the journal wedges (drops every later append) — the
        deterministic stand-in for a crash mid-``write(2)``."""
        if not self._buf or self._fh is None or self._wedged:
            return
        # Swap FIRST (atomic store), then serialize: an append racing from
        # another thread lands in the fresh list, never in the void.
        buf, self._buf = self._buf, []
        data = "\n".join(buf) + "\n"
        if self._injector is not None:
            spec = self._injector.journal_write(self)
            if spec is not None and spec.kind == "broker_crash":
                # SIGKILL analog at the drain point: NOTHING reaches the
                # disk and every later append is void.
                self._abandoned = True
                self.crash_requested = True
                logger.warning("journal %s: injected broker crash at drain",
                               self.path)
                return
            if spec is not None and spec.kind == "journal_io_error":
                torn = data[:max(1, int(len(data) * float(
                    getattr(spec, "fraction", 0.5))))]
                try:
                    self._fh.write(torn)
                    self._fh.flush()
                except (OSError, ValueError):
                    pass
                self._wedged = True
                logger.warning("journal %s wedged by injected io error "
                               "(torn write of %d/%d bytes)",
                               self.path, len(torn), len(data))
                return
        try:
            self._fh.write(data)
            self._fh.flush()
            if fsync:
                os.fsync(self._fh.fileno())
                self._last_fsync = time.monotonic()
        except (OSError, ValueError):
            self._wedged = True
            logger.exception("journal %s write failed; wedging", self.path)

    def flush(self) -> None:
        """Batched fsync point — called by the broker's periodic journal
        task (and at clean shutdown), never per record."""
        with self._lock:
            self._drain(fsync=True)

    def maybe_compact(self) -> bool:
        if self._records_since_compact < self.COMPACT_EVERY:
            return False
        self.compact()
        return True

    def compact(self) -> None:
        """Fold the live file into ``<path>.snap`` and truncate.  Replays
        our own file offline — no live mirror of broker state to keep in
        sync.  Runs on the broker loop (rare; file is bounded by
        ``COMPACT_EVERY``)."""
        with self._lock:
            self._drain(fsync=True)
            if self._wedged or self._abandoned or self._fh is None:
                return
            state = replay_file(self.path)
            state.epoch = self.epoch
            state.boot_id = self.boot_id
            self._write_snapshot(state.to_snapshot())
            self._fh.close()
            self._fh = open(self.path, "w", encoding="utf-8")
            self._records_since_compact = 0
            self._buf.append(json.dumps(
                {"t": "meta", "schema": JOURNAL_SCHEMA, "boot": self.boot_id,
                 "epoch": self.epoch}, separators=(",", ":")))
            self._records_total["meta"] = self._records_total.get("meta", 0) + 1
            self._drain(fsync=True)

    def _write_snapshot(self, snap: Dict[str, Any]) -> None:
        tmp = self.path + ".snap.tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(snap, fh, separators=(",", ":"), default=str)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self.path + ".snap")

    # -- lifecycle ---------------------------------------------------------

    def abandon(self) -> None:
        """SIGKILL analog: drop the un-fsynced buffer on the floor and stop
        writing — the crash took whatever had not reached the disk."""
        with self._lock:
            self._buf = []
            self._abandoned = True
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None

    def close(self) -> None:
        """Clean shutdown: final batched fsync, then close."""
        with self._lock:
            self._drain(fsync=True)
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None

    # -- observability -----------------------------------------------------

    @property
    def wedged(self) -> bool:
        return self._wedged

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "path": self.path,
                "boot_id": self.boot_id,
                "epoch": self.epoch,
                "records_total": dict(self._records_total),
                "records_buffered": len(self._buf),
                "last_fsync_lag_s": round(
                    time.monotonic() - self._last_fsync, 3),
                "replay_seconds": self.replay_seconds,
                "wedged": self._wedged,
            }
