"""SLO-driven fleet autoscaler: the loop that closes sensors → actuators.

The fleet has had complete *sensors* since the aggregation PR (burn-rate
SLOs behind ``/alertz``, per-instance time-series rings) and complete
*actuators* since elastic membership (drain/join/advertise, live
capacity) — this daemon is the wire between them, the last unbuilt
control loop of ROADMAP item 3.  It is a sibling of the fitness/compile/
aggregator services: stdlib HTTP, zero third-party deps, runs standalone
(``python -m gentun_tpu.distributed.autoscaler --port 9092``) or
in-process for tests and studies.

The control loop, once per ``poll_interval``:

1. ``reap()`` the backend (collect members that already exited).
2. Read the aggregator's ``/alertz`` snapshot — in-process object or
   HTTP, the daemon never computes its own judgment.  Hysteresis is
   *borrowed* from the SLO state machine: an alert only reaches
   ``firing`` after its rule's ``for_s`` hold and only clears after
   ``clear_for_s``, so the autoscaler inherits exactly the damping the
   rules declare instead of inventing a second, disagreeing one.
3. Scale up while the saturation rule fires (stock:
   ``queue_depth_growth``), down while the idleness rule fires (stock:
   ``worker_idle_ratio``); saturation wins when both fire.  On top of
   the borrowed hysteresis: min/max-fleet clamps, a ``cooldown_s``
   between consecutive decisions, and edge detection via the alert's
   monotonic ``transition_seq`` — a poller that never sees the same
   firing episode twice cannot double-act on it, and a fire→clear→fire
   cycle between two polls still reads as a fresh edge.
4. Every decision lands as a ``{"type": "scale"}`` telemetry record —
   triggering rule, ``transition_seq``, ring evidence (the tail of the
   triggering series), from/to sizes, outcome — and in a bounded
   in-memory ring served on ``/decisionz``.  A fleet that never needs
   scaling writes nothing.

Backends implement the 4-method :class:`FleetBackend` protocol.  The
first real one, :class:`LocalProcessBackend`, spawns/SIGTERMs actual
``gentun-worker`` processes — SIGTERM is the worker's orderly-drain
signal, so a scale-down hands every prefetched-unstarted job back to
the broker before the process exits (the drain-race tier-1 test pins
this).  Studies plug in thread- or callback-backed fakes.

Metrics (docs/OBSERVABILITY.md): ``autoscaler_decisions_total{action,
rule}``, ``fleet_target_size``, ``scale_decision_seconds``.
"""

from __future__ import annotations

import json
import logging
import signal
import subprocess
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

from ..telemetry import spans as _tele
from ..telemetry.registry import get_registry as _get_registry

__all__ = [
    "FleetBackend",
    "LocalProcessBackend",
    "AutoscalerDaemon",
    "main",
]

logger = logging.getLogger("gentun_tpu.distributed")

#: Decisions kept for ``/decisionz`` (the durable copy is telemetry.jsonl).
_DECISION_RING = 256

#: Ring-evidence points attached to each decision record: enough to see
#: the breach shape without bloating every record with a full ring.
_EVIDENCE_TAIL = 16


class FleetBackend:
    """What the autoscaler scales: a pool of fleet members.

    Four methods, all called from the daemon's control-loop thread only:

    - :meth:`size` — members currently alive (spawned and not reaped).
    - :meth:`spawn` — start ``n`` new members; returns how many started.
    - :meth:`drain` — ask ``n`` members to exit ORDERLY (for processes:
      SIGTERM, the worker's drain signal — in-flight work finishes and
      queued jobs requeue); returns how many were signaled.  Members
      keep counting in :meth:`size` until they actually exit.
    - :meth:`reap` — collect members that exited; returns how many left
      since the last call.

    A backend never decides — it only executes.  Implementations must
    not block the loop for long (spawn is a fork/exec, drain a signal).
    """

    def size(self) -> int:
        raise NotImplementedError

    def spawn(self, n: int) -> int:
        raise NotImplementedError

    def drain(self, n: int) -> int:
        raise NotImplementedError

    def reap(self) -> int:
        raise NotImplementedError

    def describe(self) -> Dict[str, Any]:
        """Backend block for ``/statusz``; override for richer detail."""
        return {"kind": type(self).__name__, "size": self.size()}


class LocalProcessBackend(FleetBackend):
    """The first real backend: a local pool of worker PROCESSES.

    ``argv`` is the full worker command (e.g. ``[sys.executable, "-m",
    "gentun_tpu.distributed.worker", "--port", "5672", ...]``); every
    spawn runs it verbatim, so whether members join as preemptible
    capacity is the operator's ``--preempt`` in the template, not a
    backend concern.  Drain sends SIGTERM — the worker CLI's first-signal
    orderly-drain path — to the NEWEST living members first (LIFO), so
    the longest-lived members, with their warm compile caches, survive a
    shrink.  Nothing is ever SIGKILLed here: a member that ignores its
    drain is the operator's supervisor's problem, and killing it would
    bypass the requeue handshake the drain exists for.
    """

    def __init__(self, argv: List[str]):
        if not argv:
            raise ValueError("LocalProcessBackend needs a non-empty argv")
        self.argv = list(argv)
        self._procs: List[subprocess.Popen] = []
        self._spawned_total = 0
        self._reaped_total = 0

    def size(self) -> int:
        return len(self._procs)

    def spawn(self, n: int) -> int:
        started = 0
        for _ in range(max(0, n)):
            try:
                self._procs.append(subprocess.Popen(self.argv))
            except OSError:
                logger.exception("autoscaler spawn failed: %s", self.argv)
                break
            started += 1
        self._spawned_total += started
        return started

    def drain(self, n: int) -> int:
        signaled = 0
        for proc in reversed(self._procs):
            if signaled >= max(0, n):
                break
            if proc.poll() is not None:
                continue  # already exited; reap() collects it
            try:
                proc.send_signal(signal.SIGTERM)
            except OSError:
                continue  # died between poll and signal: reap's problem
            signaled += 1
        return signaled

    def reap(self) -> int:
        live = [p for p in self._procs if p.poll() is None]
        reaped = len(self._procs) - len(live)
        self._procs = live
        self._reaped_total += reaped
        return reaped

    def describe(self) -> Dict[str, Any]:
        return {
            "kind": "LocalProcessBackend",
            "argv": self.argv,
            "size": self.size(),
            "pids": [p.pid for p in self._procs],
            "spawned_total": self._spawned_total,
            "reaped_total": self._reaped_total,
        }


# -- HTTP plane --------------------------------------------------------------


class _Handler(BaseHTTPRequestHandler):
    """Request handler; ``self.server.autoscaler`` is the daemon."""

    server_version = "gentun-autoscaler/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # noqa: D102 - silence stderr chatter
        pass

    def _send_json(self, code: int, obj: Any) -> None:
        body = json.dumps(obj, separators=(",", ":")).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 - http.server API
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        asc = self.server.autoscaler  # type: ignore[attr-defined]
        if path in ("/", "/healthz"):
            self._send_json(200, {"status": "ok", **asc.stats()})
        elif path == "/statusz":
            self._send_json(200, asc.statusz())
        elif path == "/decisionz":
            self._send_json(200, asc.decisionz())
        else:
            self._send_json(404, {"error": f"no route {path}"})


# -- the daemon --------------------------------------------------------------


class AutoscalerDaemon:
    """Watches ``/alertz``, issues spawn/drain decisions to a backend.

    Parameters
    ----------
    backend:
        The :class:`FleetBackend` to actuate.
    aggregator:
        An in-process :class:`~gentun_tpu.telemetry.aggregator.
        MetricsAggregator` (tests, studies) — or None with
        ``aggregator_url`` set for HTTP polling.  Exactly one source.
    aggregator_url:
        ``http://host:port`` of a remote aggregator.
    min_fleet, max_fleet:
        Hard clamps on the target size; decisions never leave the range.
    step:
        Members added/removed per decision.
    cooldown_s:
        Minimum seconds between consecutive scale decisions — the
        autoscaler's own damping ON TOP of the SLO machine's
        ``for_s/clear_for_s`` hysteresis.
    scale_up_rule, scale_down_rule:
        Rule names watched for saturation / idleness.  The stock pair
        (``queue_depth_growth``, ``worker_idle_ratio``) matches
        ``telemetry.slo.default_rules``.
    repeat_while_firing:
        When True (default) a still-firing alert keeps stepping the
        fleet once per cooldown window; False acts on fresh
        ``transition_seq`` edges only (deterministic decision counts for
        studies).
    """

    def __init__(
        self,
        backend: FleetBackend,
        aggregator=None,
        aggregator_url: Optional[str] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        min_fleet: int = 1,
        max_fleet: int = 8,
        step: int = 1,
        cooldown_s: float = 30.0,
        poll_interval: float = 2.0,
        scale_up_rule: str = "queue_depth_growth",
        scale_down_rule: str = "worker_idle_ratio",
        repeat_while_firing: bool = True,
        serve_http: bool = True,
    ):
        if (aggregator is None) == (aggregator_url is None):
            raise ValueError(
                "exactly one of aggregator / aggregator_url is required")
        if min_fleet < 0 or max_fleet < max(1, min_fleet):
            raise ValueError(
                f"bad fleet clamps: min={min_fleet} max={max_fleet}")
        if step < 1:
            raise ValueError(f"step must be >= 1, got {step}")
        self.backend = backend
        self._agg = aggregator
        self._agg_url = aggregator_url.rstrip("/") if aggregator_url else None
        self.min_fleet = int(min_fleet)
        self.max_fleet = int(max_fleet)
        self.step = int(step)
        self.cooldown_s = float(cooldown_s)
        self.poll_interval = float(poll_interval)
        self.scale_up_rule = scale_up_rule
        self.scale_down_rule = scale_down_rule
        self.repeat_while_firing = bool(repeat_while_firing)
        self._decisions: List[Dict[str, Any]] = []
        self._decisions_total = 0
        self._poll_errors = 0
        self._polls = 0
        #: Last transition_seq ACTED ON per (rule, subject): the edge
        #: cursor.  Strictly monotonic on the engine side, so "seq I
        #: haven't seen" ⇔ "edge since my last act", poll races included.
        self._acted_seq: Dict[Tuple[str, str], int] = {}
        self._last_decision_t = 0.0
        self._started = time.time()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._http_thread: Optional[threading.Thread] = None
        if serve_http:
            self._httpd = ThreadingHTTPServer((host, port), _Handler)
            self._httpd.daemon_threads = True
            self._httpd.autoscaler = self  # type: ignore[attr-defined]

    # -- address -----------------------------------------------------------

    @property
    def address(self) -> Optional[Tuple[str, int]]:
        return self._httpd.server_address[:2] if self._httpd else None

    @property
    def url(self) -> Optional[str]:
        addr = self.address
        return f"http://{addr[0]}:{addr[1]}" if addr else None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "AutoscalerDaemon":
        self._stop.clear()
        if self._httpd is not None:
            self._http_thread = threading.Thread(
                target=self._httpd.serve_forever,
                kwargs={"poll_interval": 0.25},
                name="autoscaler-http", daemon=True)
            self._http_thread.start()
        self._thread = threading.Thread(
            target=self._loop, name="autoscaler", daemon=True)
        self._thread.start()
        logger.info(
            "autoscaler serving on %s (fleet [%d, %d], step %d, cooldown "
            "%.1fs, rules up=%s down=%s)", self.url or "<no http>",
            self.min_fleet, self.max_fleet, self.step, self.cooldown_s,
            self.scale_up_rule, self.scale_down_rule)
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
        if self._http_thread is not None:
            self._http_thread.join(timeout=5.0)
            self._http_thread = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "AutoscalerDaemon":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_interval):
            try:
                self.decide_once()
            except Exception:  # noqa: BLE001 - the loop must survive anything
                logger.exception("autoscaler decision pass failed")

    # -- aggregator reads --------------------------------------------------

    def _fetch_json(self, endpoint: str) -> Optional[Dict[str, Any]]:
        try:
            with urllib.request.urlopen(
                    f"{self._agg_url}{endpoint}", timeout=5.0) as resp:
                return json.loads(resp.read().decode())
        except Exception:  # aggregator down: skip the tick, fail open
            self._poll_errors += 1
            logger.debug("autoscaler poll failed: %s", endpoint, exc_info=True)
            return None

    def _alertz(self) -> Optional[Dict[str, Any]]:
        if self._agg is not None:
            return self._agg.alertz()
        return self._fetch_json("/alertz")

    def _ring_tail(self, series: str) -> List[List[float]]:
        """Evidence: the tail of the triggering rule's series ring."""
        if self._agg is not None:
            ringz = self._agg.ringz(name=series)
        else:
            ringz = self._fetch_json(f"/ringz?name={series}") or {}
        points: List[List[float]] = []
        for sp in ringz.get("series") or []:
            points.extend(sp.get("points") or [])
        points.sort()
        return points[-_EVIDENCE_TAIL:]

    # -- the decision ------------------------------------------------------

    @staticmethod
    def _firing(snapshot: Dict[str, Any], rule: str) -> List[Dict[str, Any]]:
        return [a for a in snapshot.get("alerts") or []
                if a.get("rule") == rule and a.get("state") == "firing"]

    def _rule_series(self, snapshot: Dict[str, Any], rule: str) -> Optional[str]:
        for r in snapshot.get("rules") or []:
            if r.get("name") == rule:
                return r.get("series")
        return None

    def decide_once(self, now: Optional[float] = None) -> Optional[Dict[str, Any]]:
        """One control-loop pass; returns the decision record, if any.

        Public so tests and the study harness can drive the loop
        deterministically (exactly like ``MetricsAggregator.
        evaluate_slos``); the background thread calls nothing else.
        """
        now = time.time() if now is None else float(now)
        self._polls += 1
        self.backend.reap()
        snapshot = self._alertz()
        if snapshot is None:
            return None
        up = self._firing(snapshot, self.scale_up_rule)
        down = self._firing(snapshot, self.scale_down_rule)
        # Saturation beats idleness: a backlogged fleet with one idle
        # worker must grow, not shrink.
        action, alerts = ("up", up) if up else ("down", down) if down else (None, [])
        if action is None:
            return None
        # Edge-or-repeat gating: a transition_seq this cursor has not
        # acted on is always actionable (a fresh firing episode, even if
        # fire+clear+fire landed between two polls); a seq already acted
        # on re-triggers only in repeat_while_firing mode.  Cooldown
        # applies to both — it is the flap guard between decisions.
        trigger = None
        for a in alerts:
            key = (a["rule"], a.get("subject", "fleet"))
            if self._acted_seq.get(key, -1) < a.get("transition_seq", 0):
                trigger = a
                break
        if trigger is None and not self.repeat_while_firing:
            return None
        if now - self._last_decision_t < self.cooldown_s:
            return None
        trigger = trigger or alerts[0]
        size = self.backend.size()
        if action == "up":
            target = min(self.max_fleet, size + self.step)
        else:
            target = max(self.min_fleet, size - self.step)
        if target == size:
            return None  # clamped to a no-op: not a decision, no record
        t0 = time.perf_counter()
        if target > size:
            moved = self.backend.spawn(target - size)
            outcome = f"spawned {moved}"
        else:
            moved = self.backend.drain(size - target)
            outcome = f"drained {moved}"
        series = self._rule_series(snapshot, trigger["rule"])
        record = {
            "type": "scale",
            "action": action,
            "rule": trigger["rule"],
            "subject": trigger.get("subject", "fleet"),
            "transition_seq": trigger.get("transition_seq", 0),
            "firing_since": trigger.get("firing_since", 0.0),
            "value": trigger.get("value"),
            "threshold": trigger.get("threshold"),
            "evidence": self._ring_tail(series) if series else [],
            "from": size,
            "to": target,
            "outcome": outcome,
            "t": now,
        }
        self._acted_seq[(trigger["rule"], trigger.get("subject", "fleet"))] = (
            trigger.get("transition_seq", 0))
        self._last_decision_t = now
        self._decisions.append(record)
        if len(self._decisions) > _DECISION_RING:
            del self._decisions[: len(self._decisions) - _DECISION_RING]
        self._decisions_total += 1
        reg = _get_registry()
        reg.counter("autoscaler_decisions_total",
                    action=action, rule=trigger["rule"]).inc()
        reg.gauge("fleet_target_size").set(target)
        reg.histogram("scale_decision_seconds").observe(
            time.perf_counter() - t0)
        if _tele.enabled():
            _tele.emit_record(record)
        logger.info(
            "autoscaler scale %s: %d -> %d (%s; rule %s seq %d value %s)",
            action, size, target, outcome, trigger["rule"],
            record["transition_seq"], record["value"])
        return record

    # -- read side ---------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        return {
            "uptime_s": round(time.time() - self._started, 3),
            "polls": self._polls,
            "poll_errors": self._poll_errors,
            "decisions_total": self._decisions_total,
            "fleet_size": self.backend.size(),
        }

    def statusz(self) -> Dict[str, Any]:
        return {
            "status": "ok",
            **self.stats(),
            "config": {
                "min_fleet": self.min_fleet,
                "max_fleet": self.max_fleet,
                "step": self.step,
                "cooldown_s": self.cooldown_s,
                "poll_interval": self.poll_interval,
                "scale_up_rule": self.scale_up_rule,
                "scale_down_rule": self.scale_down_rule,
                "repeat_while_firing": self.repeat_while_firing,
                "aggregator": (self._agg_url if self._agg_url
                               else "<in-process>"),
            },
            "backend": self.backend.describe(),
            "acted_seq": {f"{r}/{s}": q
                          for (r, s), q in sorted(self._acted_seq.items())},
            "last_decision": self._decisions[-1] if self._decisions else None,
        }

    def decisionz(self) -> Dict[str, Any]:
        return {"decisions": list(self._decisions),
                "total": self._decisions_total}


# -- standalone entrypoint ---------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m gentun_tpu.distributed.autoscaler`` — run the daemon."""
    import argparse
    import shlex

    ap = argparse.ArgumentParser(
        prog="python -m gentun_tpu.distributed.autoscaler",
        description="SLO-driven fleet autoscaler (watches /alertz, "
                    "spawns/drains gentun-worker processes)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=9092,
                    help="ops plane bind port (/statusz /decisionz /healthz)")
    ap.add_argument("--aggregator-url", required=True, metavar="URL",
                    help="the fleet aggregator to watch, e.g. "
                         "http://agg-host:9100 (its /alertz is the ONLY "
                         "judgment source — the daemon never computes SLOs)")
    ap.add_argument("--worker-cmd", required=True, metavar="CMD",
                    help="full worker command, shlex-split, run verbatim "
                         "per spawned member — include --preempt here to "
                         "grow with preemptible capacity, e.g. "
                         "\"python -m gentun_tpu.distributed.worker --port "
                         "5672 --preempt\"")
    ap.add_argument("--min-fleet", type=int, default=1)
    ap.add_argument("--max-fleet", type=int, default=8)
    ap.add_argument("--step", type=int, default=1,
                    help="members added/removed per decision")
    ap.add_argument("--cooldown", type=float, default=30.0,
                    help="seconds between consecutive scale decisions "
                         "(flap guard on top of the SLO for_s/clear_for_s "
                         "hysteresis)")
    ap.add_argument("--poll-interval", type=float, default=2.0)
    ap.add_argument("--scale-up-rule", default="queue_depth_growth")
    ap.add_argument("--scale-down-rule", default="worker_idle_ratio")
    ap.add_argument("--edge-only", action="store_true",
                    help="act only on fresh alert transitions (default: a "
                         "still-firing alert keeps stepping once per "
                         "cooldown window)")
    ap.add_argument("--spawn-initial", action="store_true",
                    help="spawn min-fleet members at startup (default: "
                         "adopt whatever the operator already runs)")
    ap.add_argument("--telemetry", action="store_true",
                    help="emit {type: scale} records to the telemetry sink "
                         "(GENTUN_TPU_TELEMETRY=1 equivalent)")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)

    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    if args.telemetry:
        _tele.enable()
    from ..telemetry.aggregator import parse_aggregator_url

    try:
        agg_url = parse_aggregator_url(args.aggregator_url)
        backend = LocalProcessBackend(shlex.split(args.worker_cmd))
        daemon = AutoscalerDaemon(
            backend,
            aggregator_url=agg_url,
            host=args.host, port=args.port,
            min_fleet=args.min_fleet, max_fleet=args.max_fleet,
            step=args.step, cooldown_s=args.cooldown,
            poll_interval=args.poll_interval,
            scale_up_rule=args.scale_up_rule,
            scale_down_rule=args.scale_down_rule,
            repeat_while_firing=not args.edge_only,
        )
    except ValueError as e:
        raise SystemExit(f"autoscaler: {e}")
    if args.spawn_initial and args.min_fleet > 0:
        backend.spawn(args.min_fleet)
    daemon.start()
    print(f"autoscaler serving on {daemon.url} (/statusz /decisionz)")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        daemon.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
