"""Cross-session window packing: broker-side pack state (ISSUE 19).

The converged tail of a search emits 1–4-individual generations, and each
one pays the full program-switch + dispatch + RPC floor PERF.md measures
at ~1.9 s — a cost a full mesh-bucket window pays once and amortizes over
the whole population.  A multi-tenant broker multiplies that regime: many
concurrent sessions, each emitting tiny batches, each paying the floor
alone.  The fix is to let queued jobs from DIFFERENT sessions share one
device window whenever that is provably safe.

Safety is the purity protocol note (PERF.md, ``TestBatchCompositionPurity``):
under content-hash PRNG keys, fitness is a pure function of
(architecture, config, seed) — invariant to batch composition, slot, and
padding.  Two jobs may therefore share a window iff they would compile to
the same program, which is exactly equality of:

- the serialized ``additional_parameters`` bytes (static config
  fingerprint — the ``jobs2`` envelope-grouping rule),
- the serialized ``fidelity`` bytes (fidelity fingerprint — rung epochs
  feed the compiled step count), and
- the genome size class (``job_size_class`` — small genomes share the
  data-parallel program; big/micro genomes get singleton windows).

:class:`WindowPacker` is pure pack STATE: compile-compatibility groups,
each a FIFO of ``(session, job_id)`` with arrival stamps, plus bounded
fill/linger observations for ``pack_stats()``.  All policy — when to fill
(fair-share ``pop_next``, so DRR deficit charging is preserved job by
job), when to flush (window full at the worker's mesh-aligned capacity,
or the oldest job's ``max_linger_ms`` deadline), and where (placement
class, credit) — lives in ``JobBroker._dispatch_packed``.  Like
``FairShareScheduler``, every method here runs on the broker's event
loop thread only; no locks.

Crash safety needs no packed-window journal record: the journal is
per-job, a packed in-flight window replays as its constituent
per-session jobs, and the packer itself is rebuilt empty on restart
(held jobs were never dispatched, so replay returns them to the
scheduler and they simply re-pack).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Deque, Dict, Iterable, List, Optional, Tuple

__all__ = ["PackGroup", "WindowPacker"]


class PackGroup:
    """One compile-compatibility class's held jobs, FIFO with arrivals.

    ``key`` is the broker's pack key — ``(pack_envelope(env),
    size_class)`` — opaque here beyond identity.  ``size_class`` and
    ``prefers_preemptible`` are denormalized out of the key's jobs so
    the flush loop can size/place a window without touching payloads;
    both are constant within a group by construction (size class is in
    the key, and placement preference is rung-0 AND small, where the
    rung comes from the fidelity bytes that are also in the key).
    """

    __slots__ = ("key", "size_class", "prefers_preemptible", "jobs", "arrivals")

    def __init__(self, key: tuple, size_class: str,
                 prefers_preemptible: bool) -> None:
        self.key = key
        self.size_class = size_class
        self.prefers_preemptible = prefers_preemptible
        self.jobs: Deque[Tuple[str, str]] = deque()  # (session_id, job_id)
        self.arrivals: Deque[float] = deque()

    def __len__(self) -> int:
        return len(self.jobs)

    @property
    def oldest(self) -> Optional[float]:
        """Monotonic arrival stamp of the head job, or ``None`` if empty."""
        return self.arrivals[0] if self.arrivals else None


def _dist(values: List[float]) -> Optional[Dict[str, float]]:
    """count/mean/p50/p90/max over a sorted sample; None when empty."""
    if not values:
        return None
    n = len(values)
    return {
        "count": n,
        "mean": round(sum(values) / n, 6),
        "p50": round(values[min(n - 1, int(0.50 * n))], 6),
        "p90": round(values[min(n - 1, int(0.90 * n))], 6),
        "max": round(values[-1], 6),
    }


class WindowPacker:
    """Pack state for ``JobBroker(pack_windows=True)``.

    Jobs enter through :meth:`add` (the broker pops them from the
    fair-share scheduler, so fairness was already charged), sit in their
    compatibility group's FIFO, and leave through :meth:`take` (one
    window) or :meth:`remove` (cancel / session close).  ``held``
    counts jobs currently parked here — they are neither queued (the
    scheduler no longer has them) nor in flight (no worker owns them),
    so the broker's ``outstanding()`` reports them as ``packed_held``
    and chaos quiescence asserts the count drains to zero.
    """

    #: Bounded window for fill/linger observations — enough for stable
    #: percentiles, small enough to never matter for memory.
    STATS_WINDOW = 512

    def __init__(self, linger_s: float) -> None:
        self.linger_s = max(0.0, float(linger_s))
        self._groups: Dict[tuple, PackGroup] = {}
        self._job_group: Dict[str, tuple] = {}
        self._held = 0
        self.windows_total = 0
        self.jobs_total = 0
        self.cross_session_windows = 0
        self.fill_ratios: Deque[float] = deque(maxlen=self.STATS_WINDOW)
        self.lingers: Deque[float] = deque(maxlen=self.STATS_WINDOW)

    # -- holding ----------------------------------------------------------

    @property
    def held(self) -> int:
        return self._held

    def held_by_session(self) -> Dict[str, int]:
        """Held-job count per session — the broker folds this into its
        in-flight view so ``max_in_flight`` quotas see parked jobs."""
        counts: Dict[str, int] = {}
        for g in self._groups.values():
            for sid, _ in g.jobs:
                counts[sid] = counts.get(sid, 0) + 1
        return counts

    def add(self, sid: str, job_id: str, key: tuple, size_class: str,
            prefers_preemptible: bool, now: Optional[float] = None) -> None:
        """Park one job in its compatibility group (FIFO tail)."""
        g = self._groups.get(key)
        if g is None:
            g = self._groups[key] = PackGroup(key, size_class,
                                             prefers_preemptible)
        g.jobs.append((sid, job_id))
        g.arrivals.append(time.monotonic() if now is None else now)
        self._job_group[job_id] = key
        self._held += 1

    def groups(self) -> List[PackGroup]:
        return list(self._groups.values())

    def next_deadline(self) -> Optional[float]:
        """Earliest monotonic instant a held window becomes linger-due,
        or ``None`` when nothing is held (nothing to time out)."""
        oldest = [g.arrivals[0] for g in self._groups.values() if g.arrivals]
        if not oldest:
            return None
        return min(oldest) + self.linger_s

    # -- leaving ----------------------------------------------------------

    def take(self, group: PackGroup, n: int, step: int,
             now: Optional[float] = None) -> List[Tuple[str, str]]:
        """Pop up to ``n`` jobs FIFO from ``group`` as ONE window.

        ``step`` is the window's target size (the worker's mesh-aligned
        capacity) — it only feeds the fill-ratio observation.  Records
        one windows_total / fill / linger sample, drops the group when
        emptied, and returns the ``(session, job_id)`` window in pack
        order (which IS dispatch order — the DRR interleave the fill
        phase charged).
        """
        if n <= 0 or not group.jobs:
            return []
        now = time.monotonic() if now is None else now
        linger = now - group.arrivals[0]
        out: List[Tuple[str, str]] = []
        for _ in range(min(n, len(group.jobs))):
            pair = group.jobs.popleft()
            group.arrivals.popleft()
            self._job_group.pop(pair[1], None)
            out.append(pair)
        self._held -= len(out)
        if not group.jobs:
            self._groups.pop(group.key, None)
        self.windows_total += 1
        self.jobs_total += len(out)
        if len({sid for sid, _ in out}) > 1:
            self.cross_session_windows += 1
        self.fill_ratios.append(len(out) / max(1, step))
        self.lingers.append(max(0.0, linger))
        return out

    def remove(self, ids: Iterable[str]) -> int:
        """Purge held jobs by id (cancel, session close, terminal fail).
        Returns how many were actually held here."""
        ids = set(ids)
        affected = set()
        for jid in ids:
            key = self._job_group.pop(jid, None)
            if key is not None:
                affected.add(key)
        removed = 0
        for key in affected:
            g = self._groups.get(key)
            if g is None:
                continue
            kept = [(pair, at) for pair, at in zip(g.jobs, g.arrivals)
                    if pair[1] not in ids]
            removed += len(g.jobs) - len(kept)
            g.jobs = deque(pair for pair, _ in kept)
            g.arrivals = deque(at for _, at in kept)
            if not g.jobs:
                del self._groups[key]
        self._held -= removed
        return removed

    # -- observability -----------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """Pack stats for ``/statusz`` and ``JobBroker.pack_stats()``."""
        return {
            "linger_ms": round(self.linger_s * 1000.0, 3),
            "held": self._held,
            "groups": len(self._groups),
            "windows_total": self.windows_total,
            "jobs_total": self.jobs_total,
            "cross_session_windows": self.cross_session_windows,
            "fill_ratio": _dist(sorted(self.fill_ratios)),
            "linger_s": _dist(sorted(self.lingers)),
        }
