"""Wire protocol for the master↔worker control plane.

Reference parity: gentun ships JSON jobs over RabbitMQ (AMQP) with an RPC
reply queue + correlation ids (``gentun/server.py``/``client.py`` [PUB];
SURVEY.md §3.2-3.3).  No broker exists in this environment (SURVEY.md §2.1),
so the rebuild speaks its own minimal protocol: **newline-delimited JSON over
TCP**, carrying exactly what the reference carried — genes, additional
parameters, fitness scalars — and nothing else.  Genes are tiny by design;
wire cost is irrelevant (SURVEY.md §1 "Workers own the training data").

Message types:

====================  =====================================================
worker → broker       ``hello`` {worker_id, token, capacity}
broker → worker       ``welcome`` {} | ``error`` {reason}
worker → broker       ``ready`` {credit}        request up to `credit` jobs
broker → worker       ``jobs`` {jobs: [{job_id, genes, additional_parameters}, ...]}
worker → broker       ``result`` {job_id, fitness}   = the ack (ack-after-work)
worker → broker       ``results`` {results: [{job_id, fitness}, ...]}  coalesced acks
worker → broker       ``fail`` {job_id, reason}      evaluation raised
worker → broker       ``ping`` {}               liveness, from a side thread
====================  =====================================================

``hello`` also carries advisory fields the broker uses for observability:
``n_chips`` (the worker's accelerator count — denominates the master's
per-chip metric) and ``backend`` (fitness-model class name — the broker
warns on a heterogeneous fleet).

Pipelined-dispatch field (new fields are OPTIONAL with conservative
defaults, the same versioning convention as the telemetry fields below —
old workers and old masters interoperate unchanged):

- ``hello`` may carry ``prefetch_depth`` (int ≥ 0): how many jobs BEYOND
  ``capacity`` this worker wants queued locally so the next window is
  already decoded when the current one finishes (double buffering —
  ``client.py``).  A broker that understands it extends the worker's
  credit ceiling to ``capacity + prefetch_depth``
  (``broker._parse_prefetch`` clamps to ``[0, 4 × capacity]``); an old
  broker ignores the field and clamps credit at ``capacity``, which
  degrades the worker to the un-pipelined flow without any protocol
  error.  A worker that never sends it (old worker, or
  ``prefetch_depth=0``) gets exactly the pre-pipelining behavior on
  both ends.

Elastic-membership messages (same OPTIONAL convention — both are NEW
worker→broker types; a broker that doesn't understand them logs-and-drops
the frame, which degrades the worker to the inelastic flow without a
protocol error):

- ``drain`` {requeue: [job_id, ...]}: the worker announces an orderly
  exit — it will finish what it has STARTED, hand back what it merely
  QUEUED (the listed prefetched-but-unstarted job ids), and wants no
  further dispatch.  The broker zeroes the worker's credit, requeues the
  listed ids immediately, and excludes the worker from
  ``fleet_capacity``/``fleet_prefetch`` so elastic masters shrink their
  in-flight target right away.  The requeue list is a promptness
  optimization only: at-least-once disconnect requeue remains the
  correctness net, so a lost or duplicated ``drain`` frame is harmless.
- ``advertise`` {capacity?, prefetch_depth?}: mid-run re-advertisement of
  the ``hello`` sizing fields (a worker gained/lost chips, or an operator
  retuned prefetch).  The broker updates the worker's window in place
  (same clamps as ``hello``), shrinking credit immediately; growth is
  granted by the worker's next ``ready``.  Ignored from a draining
  worker.

Host-mesh field (same OPTIONAL convention — pure observability, never
load-bearing for correctness):

- ``hello`` and ``advertise`` may carry ``mesh`` {pop, data, devices}: a
  host-level mesh worker (``--capacity auto``, DISTRIBUTED.md "Host-level
  mesh workers") advertises the ``(pop, data)`` device-mesh factoring its
  capacity was DERIVED from (compile bucket × pop-axis size) and the
  local device count behind it.  The broker records it per worker
  (``/statusz`` fleet table, the gentun_top mesh column) and exposes the
  fleet's widest pop axis (``fleet_mesh_pop``) so master-side batch
  sizing can align speculative fill to the mesh multiple.  Malformed
  values degrade to "no mesh recorded" (like ``n_chips``); a per-chip
  worker that never sends the field behaves — and is dispatched to —
  exactly as before.

Multi-fidelity field (same OPTIONAL-with-conservative-default convention):

- each ``jobs`` entry may carry ``fidelity`` {v, rung, fingerprint}: the
  rung this job was dispatched at by a ladder-running master
  (``AsyncEvolution(fidelity_ladder=...)``) and the
  ``utils/fitness_store.fidelity_fingerprint`` of the shipped
  ``additional_parameters``.  Workers that understand it cross-check the
  fingerprint against the config they are about to train with and reply
  with a structured ``fail`` frame on mismatch or on an unknown tag
  version (``v != 1``) — a mislabeled fidelity must lose ONE job loudly,
  never poison a rung with a wrong-schedule measurement.  A tagless job
  (old master) evaluates exactly as before, and an old worker ignores
  the field entirely — the fitness-cache keys on the master still keep
  rungs disjoint, the tag only adds fleet-side detection.

Session messages (multi-tenant search sessions, ``sessions.py`` — same
OPTIONAL convention; every pre-session frame stays byte-identical, so old
workers and old single-tenant masters interoperate unchanged):

- ``hello`` may carry ``role: "client"``: the connection is a wire TENANT
  rather than a worker — it submits jobs into a session and receives that
  session's results, but never evaluates.  After ``welcome`` the broker
  accepts from it:

  - ``session_open`` {session?, weight?, max_in_flight?} → ``session_ok``
    {session}: create a search session (or RE-ATTACH to an open one —
    idempotent, and buffered results are flushed on re-attach).  Omitting
    ``session`` lets the broker mint an id.
  - ``session_detach`` {session} → ``session_ok``: stop receiving the
    session's results; they park in a bounded broker-side queue until
    someone re-attaches.  The session stays open.
  - ``session_close`` {session} → ``session_ok``: no further submits; the
    session's queued jobs are withdrawn and its fair-share slot is
    released.  Idempotent.
  - ``submit`` {session, jobs: [{job_id, genes, ...}, ...]}: enqueue jobs
    into the session (client-supplied job ids).  Results come back as
    ``results`` frames carrying ``session``, terminal failures as ``fail``
    frames carrying ``session``.
  - ``cancel`` {jobs: [job_id, ...]}: withdraw still-open jobs.

- a ``submit`` naming an UNKNOWN or CLOSED session is answered with a
  structured ``error`` {code: "session", session, reason} frame — loudly,
  never a silent drop — and bumps the ``session_rejected_total{session}``
  counter.  In-process submitters get the same contract as an
  ``UnknownSessionError`` raised from ``JobBroker.submit``.
- each ``jobs`` entry dispatched from a NON-default session carries
  ``session``: the tenant tag, echoed by session-aware workers in their
  result entries (the broker keys on ``job_id``, so an old worker that
  drops the field loses nothing — the tag exists for worker-side
  telemetry attribution).  Default-session jobs carry no ``session``
  field at all: the single-tenant wire format is byte-identical to
  pre-session brokers.

Telemetry fields (``gentun_tpu/telemetry``, docs/OBSERVABILITY.md) — both
OPTIONAL and only present when tracing is enabled on the sending side;
receivers that don't understand them ignore them, so mixed
enabled/disabled fleets interoperate:

- each ``jobs`` entry may carry ``trace`` {trace_id, span_id}: the
  master-side span context under which the job was submitted.  The worker
  re-attaches it so its spans join the master's trace.
- the FIRST ``result`` frame of a worker's evaluation group may carry
  ``spans`` [span records]: the group's captured worker-side spans
  (eval/train/compile...), which the broker ingests into the active run
  artifact.  It rides a result frame — not a separate message type — so
  span reports inherit result-frame dedup: a duplicated frame cannot
  double-ingest.

Cache services are HTTP side channels, not frames: both the shared
fitness service (``fitness_service.py``, ``--cache-url``) and the
fleet-wide compile-artifact cache (``compile_service.py``,
``--compile-cache-url``) run over their own stdlib-HTTP connections,
never over this socket.  The broker protocol is therefore entirely
unaware of them — a worker prefetches compiled executables and publishes
fresh ones out-of-band, and nothing on this wire changes whether the
services are up, degraded, or absent (that independence is what lets
cache downtime never fail a search).

Pings are deliberately UNANSWERED: the broker's ``last_seen`` update is
the liveness mechanism, and replies the worker only reads between batches
would pile up unread during a long training batch — a worker exiting
right after its final results would then RST away the in-flight result
frames (see ``client._graceful_close``).  Workers detect a dead broker by
EOF/send-failure, never by pong absence.

Delivery semantics (matching AMQP's, SURVEY.md §5 "Failure detection"):
at-least-once.  A job is requeued when its worker disconnects or stops
pinging before sending ``result``; the master deduplicates by ``job_id`` and
keeps the first fitness, so redelivery never double-counts.

Jobs travel in **batches**: a dispatch to a worker is a single ``jobs``
frame holding everything that worker's credit allows.  This is what makes
capacity > 1 deterministic — a capacity-8 worker receives its 8 jobs in one
frame regardless of network latency, so the worker never has to guess (with
a read timeout) whether more jobs are in flight.  One bounded exception: a
batch whose encoded size would approach ``MAX_MESSAGE_BYTES`` is split at a
soft size cap into several consecutive ``jobs`` frames, which the worker
consumes (and trains) one frame at a time — batching degrades gracefully
for pathologically large payloads instead of breaking the protocol.

Results travel the same way: a worker's evaluation group replies with ONE
``results`` frame per capacity window (``coalesce_results``) instead of a
TCP frame per job, so a capacity-8 batch is 1 syscall + 1 broker wake-up
instead of 8 — this shaves the measured small-batch RPC floor of the
converged tail (PERF.md "Tail generations") in both the generational and
the asynchronous mode.  Each entry inside the frame is deduplicated
independently on the broker (at-least-once semantics are unchanged), the
group's span report rides the frame exactly as it used to ride the first
``result`` frame, and the single-job ``result`` frame remains accepted for
back-compat with older workers.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

__all__ = [
    "encode",
    "decode",
    "coalesce_results",
    "MAX_MESSAGE_BYTES",
    "ProtocolError",
    "AuthError",
]

#: Hard cap per message; genes + params are a few KB, so anything huge is a
#: protocol violation (or an attempt to ship training data, which the design
#: forbids — data lives with the worker).
MAX_MESSAGE_BYTES = 4 * 1024 * 1024


class ProtocolError(Exception):
    """Malformed or oversized frame."""


class AuthError(ConnectionError):
    """The broker rejected this worker's credentials (``error: bad token``).

    Unlike a network blip, auth rejection is deterministic — reconnecting
    with the same token can never succeed — so ``GentunClient.work()``
    treats it as TERMINAL instead of retrying forever (the reference's
    RabbitMQ credential failure is equally loud [PUB]).  Subclasses
    ``ConnectionError`` so pre-existing callers that catch broadly keep
    working.
    """


def encode(msg: Dict[str, Any]) -> bytes:
    """Message dict → one newline-terminated JSON frame."""
    data = json.dumps(msg, separators=(",", ":")).encode("utf-8")
    if len(data) > MAX_MESSAGE_BYTES:
        raise ProtocolError(f"message of {len(data)} bytes exceeds {MAX_MESSAGE_BYTES}")
    return data + b"\n"


def decode(line: bytes) -> Dict[str, Any]:
    """One frame (without trailing newline requirement) → message dict."""
    # Strip the framing newline before the size check so a payload of
    # exactly MAX_MESSAGE_BYTES (which encode() allows) round-trips.
    line = line.rstrip(b"\n")
    if len(line) > MAX_MESSAGE_BYTES:
        raise ProtocolError(f"frame of {len(line)} bytes exceeds {MAX_MESSAGE_BYTES}")
    try:
        msg = json.loads(line)
    except json.JSONDecodeError as e:
        raise ProtocolError(f"bad JSON frame: {e}") from e
    if not isinstance(msg, dict) or "type" not in msg:
        raise ProtocolError(f"frame is not a typed message: {msg!r}")
    return msg


def coalesce_results(
    entries: List[Dict[str, Any]],
    spans: Optional[List[Dict[str, Any]]] = None,
    soft_cap: Optional[int] = None,
) -> List[Dict[str, Any]]:
    """Pack per-job result entries into the fewest ``results`` frames.

    The worker-side mirror of the broker's ``jobs`` batching: one frame per
    capacity window, split at a soft size cap (default
    ``MAX_MESSAGE_BYTES // 2``) so a pathological batch degrades into
    several valid frames instead of one oversized one.  ``spans`` (the
    group's captured telemetry report) is attached to the FIRST frame only,
    preserving the ride-the-first-result dedup contract.  Returns message
    dicts, not bytes — the client's send path owns encoding (and fault
    injection sees typed messages).
    """
    cap = int(soft_cap) if soft_cap else MAX_MESSAGE_BYTES // 2
    batches: List[List[Dict[str, Any]]] = []
    batch: List[Dict[str, Any]] = []
    batch_bytes = 0
    for entry in entries:
        entry_bytes = len(json.dumps(entry, separators=(",", ":")).encode("utf-8"))
        if batch and batch_bytes + entry_bytes > cap:
            batches.append(batch)
            batch, batch_bytes = [], 0
        batch.append(entry)
        batch_bytes += entry_bytes
    if batch:
        batches.append(batch)
    frames: List[Dict[str, Any]] = []
    for i, group in enumerate(batches):
        msg: Dict[str, Any] = {"type": "results", "results": group}
        if i == 0 and spans:
            msg["spans"] = spans
        frames.append(msg)
    return frames
