"""Wire protocol for the master↔worker control plane.

Reference parity: gentun ships JSON jobs over RabbitMQ (AMQP) with an RPC
reply queue + correlation ids (``gentun/server.py``/``client.py`` [PUB];
SURVEY.md §3.2-3.3).  No broker exists in this environment (SURVEY.md §2.1),
so the rebuild speaks its own minimal protocol: **newline-delimited JSON over
TCP**, carrying exactly what the reference carried — genes, additional
parameters, fitness scalars — and nothing else.  Genes are tiny by design;
wire cost is irrelevant (SURVEY.md §1 "Workers own the training data").

Message types:

====================  =====================================================
worker → broker       ``hello`` {worker_id, token, capacity}
broker → worker       ``welcome`` {} | ``error`` {reason}
worker → broker       ``ready`` {credit}        request up to `credit` jobs
broker → worker       ``jobs`` {jobs: [{job_id, genes, additional_parameters}, ...]}
worker → broker       ``result`` {job_id, fitness}   = the ack (ack-after-work)
worker → broker       ``results`` {results: [{job_id, fitness}, ...]}  coalesced acks
worker → broker       ``fail`` {job_id, reason}      evaluation raised
worker → broker       ``ping`` {}               liveness, from a side thread
====================  =====================================================

``hello`` also carries advisory fields the broker uses for observability:
``n_chips`` (the worker's accelerator count — denominates the master's
per-chip metric) and ``backend`` (fitness-model class name — the broker
warns on a heterogeneous fleet).

Pipelined-dispatch field (new fields are OPTIONAL with conservative
defaults, the same versioning convention as the telemetry fields below —
old workers and old masters interoperate unchanged):

- ``hello`` may carry ``prefetch_depth`` (int ≥ 0): how many jobs BEYOND
  ``capacity`` this worker wants queued locally so the next window is
  already decoded when the current one finishes (double buffering —
  ``client.py``).  A broker that understands it extends the worker's
  credit ceiling to ``capacity + prefetch_depth``
  (``broker._parse_prefetch`` clamps to ``[0, 4 × capacity]``); an old
  broker ignores the field and clamps credit at ``capacity``, which
  degrades the worker to the un-pipelined flow without any protocol
  error.  A worker that never sends it (old worker, or
  ``prefetch_depth=0``) gets exactly the pre-pipelining behavior on
  both ends.

Elastic-membership messages (same OPTIONAL convention — both are NEW
worker→broker types; a broker that doesn't understand them logs-and-drops
the frame, which degrades the worker to the inelastic flow without a
protocol error):

- ``drain`` {requeue: [job_id, ...]}: the worker announces an orderly
  exit — it will finish what it has STARTED, hand back what it merely
  QUEUED (the listed prefetched-but-unstarted job ids), and wants no
  further dispatch.  The broker zeroes the worker's credit, requeues the
  listed ids immediately, and excludes the worker from
  ``fleet_capacity``/``fleet_prefetch`` so elastic masters shrink their
  in-flight target right away.  The requeue list is a promptness
  optimization only: at-least-once disconnect requeue remains the
  correctness net, so a lost or duplicated ``drain`` frame is harmless.
- ``advertise`` {capacity?, prefetch_depth?}: mid-run re-advertisement of
  the ``hello`` sizing fields (a worker gained/lost chips, or an operator
  retuned prefetch).  The broker updates the worker's window in place
  (same clamps as ``hello``), shrinking credit immediately; growth is
  granted by the worker's next ``ready``.  Ignored from a draining
  worker.

Preemptible-capacity field (same OPTIONAL-with-conservative-default
convention — placement hint, never load-bearing for correctness):

- ``hello`` and ``advertise`` may carry ``preemptible`` (bool): the
  worker runs on capacity that may be reclaimed (``gentun-worker
  --preempt``; a spot/preemptible VM, or an autoscaler-managed member).
  A broker that understands it routes cheap requeue-able work there
  first — rung-0 probes — and pins high-rung promotions and big/micro
  genomes to stable members when both classes exist, falling back to any
  capacity when one class is absent (``broker._dispatch`` placement).
  Anything but the JSON literal ``true`` — absent, old worker, malformed
  — degrades to stable, the conservative default: a stable-only fleet
  dispatches byte-identically to a broker that predates the field.
  ``drain`` may carry ``reason`` ("preempt"): attribution for the
  requeue lineage events so a study can separate preemption churn from
  operator drains; unknown or absent reasons degrade to "drain".

Host-mesh field (same OPTIONAL convention — pure observability, never
load-bearing for correctness):

- ``hello`` and ``advertise`` may carry ``mesh`` {pop, data, devices}: a
  host-level mesh worker (``--capacity auto``, DISTRIBUTED.md "Host-level
  mesh workers") advertises the ``(pop, data)`` device-mesh factoring its
  capacity was DERIVED from (compile bucket × pop-axis size) and the
  local device count behind it.  The broker records it per worker
  (``/statusz`` fleet table, the gentun_top mesh column) and exposes the
  fleet's widest pop axis (``fleet_mesh_pop``) so master-side batch
  sizing can align speculative fill to the mesh multiple.  Malformed
  values degrade to "no mesh recorded" (like ``n_chips``); a per-chip
  worker that never sends the field behaves — and is dispatched to —
  exactly as before.

Multi-home field (same OPTIONAL convention — pure observability, never
load-bearing for correctness):

- ``hello`` may carry ``homes`` (int): how many broker SHARDS this
  worker multi-homed to (horizontal sharding, ISSUE 18 — DISTRIBUTED.md
  "Horizontal broker sharding").  Only sent when > 1, so a single-homed
  worker's hello stays byte-identical.  The broker records it per worker
  (``/statusz`` fleet table, ``worker_homes{worker}`` gauge) so
  operators reading per-shard capacity sums know a 2-homed capacity-8
  worker legitimately shows 8 on BOTH shards.  Credit stays per
  connection exactly as before — each shard grants against the window
  the worker advertised to IT, and the worker replenishes each batch's
  credit at the shard that dispatched it.  Absent or malformed degrades
  to 1, never a dropped connection.

Multi-fidelity field (same OPTIONAL-with-conservative-default convention):

- each ``jobs`` entry may carry ``fidelity`` {v, rung, fingerprint}: the
  rung this job was dispatched at by a ladder-running master
  (``AsyncEvolution(fidelity_ladder=...)``) and the
  ``utils/fitness_store.fidelity_fingerprint`` of the shipped
  ``additional_parameters``.  Workers that understand it cross-check the
  fingerprint against the config they are about to train with and reply
  with a structured ``fail`` frame on mismatch or on an unknown tag
  version (``v != 1``) — a mislabeled fidelity must lose ONE job loudly,
  never poison a rung with a wrong-schedule measurement.  A tagless job
  (old master) evaluates exactly as before, and an old worker ignores
  the field entirely — the fitness-cache keys on the master still keep
  rungs disjoint, the tag only adds fleet-side detection.

Session messages (multi-tenant search sessions, ``sessions.py`` — same
OPTIONAL convention; every pre-session frame stays byte-identical, so old
workers and old single-tenant masters interoperate unchanged):

- ``hello`` may carry ``role: "client"``: the connection is a wire TENANT
  rather than a worker — it submits jobs into a session and receives that
  session's results, but never evaluates.  After ``welcome`` the broker
  accepts from it:

  - ``session_open`` {session?, weight?, max_in_flight?} → ``session_ok``
    {session}: create a search session (or RE-ATTACH to an open one —
    idempotent, and buffered results are flushed on re-attach).  Omitting
    ``session`` lets the broker mint an id.
  - ``session_detach`` {session} → ``session_ok``: stop receiving the
    session's results; they park in a bounded broker-side queue until
    someone re-attaches.  The session stays open.
  - ``session_close`` {session} → ``session_ok``: no further submits; the
    session's queued jobs are withdrawn and its fair-share slot is
    released.  Idempotent.
  - ``submit`` {session, jobs: [{job_id, genes, ...}, ...]}: enqueue jobs
    into the session (client-supplied job ids).  Results come back as
    ``results`` frames carrying ``session``, terminal failures as ``fail``
    frames carrying ``session``.
  - ``cancel`` {jobs: [job_id, ...]}: withdraw still-open jobs.
  - ``session_stats`` {session?, reset_chips?} → ``session_stats``
    {session, capacity, prefetch, mesh_pop, chips}: the session's
    weighted fleet share and the fleet-wide sizing facts
    (``fleet_mesh_pop``, ``chips_seen``) — the wire mirror of the
    in-process sizing reads, added for sharded masters (ISSUE 18) whose
    engines run against remote brokers only.  ``reset_chips: true``
    starts a fresh chips-seen observation window first.  Old clients
    never send it; old brokers log-and-ignore it.

- a wire ``submit`` whose ``job_id`` is ALREADY OPEN on this broker is
  skipped silently (ISSUE 18): a sharded master whose submit ack died
  with the link retries the same ids after reconnect, and re-enqueueing
  them would double-run the jobs.  Ids already terminal DO re-run
  (at-least-once); the client-side results table dedups by id.

- a ``submit`` naming an UNKNOWN or CLOSED session is answered with a
  structured ``error`` {code: "session", session, reason} frame — loudly,
  never a silent drop — and bumps the ``session_rejected_total{session}``
  counter.  In-process submitters get the same contract as an
  ``UnknownSessionError`` raised from ``JobBroker.submit``.
- each ``jobs`` entry dispatched from a NON-default session carries
  ``session``: the tenant tag, echoed by session-aware workers in their
  result entries (the broker keys on ``job_id``, so an old worker that
  drops the field loses nothing — the tag exists for worker-side
  telemetry attribution).  Default-session jobs carry no ``session``
  field at all: the single-tenant wire format is byte-identical to
  pre-session brokers.

Crash-safety fields (ISSUE 16, ``journal.py`` — same OPTIONAL convention;
a broker running WITHOUT a dispatch journal emits none of them, keeping
its wire format byte-identical to pre-journal brokers):

- ``welcome`` (worker AND client role) may carry ``boot_id``: the
  journaled broker's boot epoch, a fresh opaque token per process start.
  Clients/workers that understand it echo it as ``boot`` on their
  ``results``/``fail`` frames; old peers ignore it and echo nothing.
- a restarted broker uses the echo to vet results minted under a PREVIOUS
  epoch: a ``boot``-mismatched result is accepted iff its ``job_id`` is
  still open in the replayed journal state (the work is real and wanted),
  else dropped with ``epoch_stale_results_total`` — never double-counted.
- ``session_open``/``submit`` over the wire may be refused under
  admission control with a structured ``error`` {code: "admission",
  session, reason: "saturated"|"rate_limited", retry_after_s} frame — the
  429 contract: nothing was enqueued; back off ``retry_after_s`` seconds
  and retry the same request.  ``SessionClient`` raises
  :class:`~.sessions.AdmissionRejected` carrying both fields.

Telemetry fields (``gentun_tpu/telemetry``, docs/OBSERVABILITY.md) — both
OPTIONAL and only present when tracing is enabled on the sending side;
receivers that don't understand them ignore them, so mixed
enabled/disabled fleets interoperate:

- each ``jobs`` entry may carry ``trace`` {trace_id, span_id}: the
  master-side span context under which the job was submitted.  The worker
  re-attaches it so its spans join the master's trace.
- the FIRST ``result`` frame of a worker's evaluation group may carry
  ``spans`` [span records]: the group's captured worker-side spans
  (eval/train/compile...), which the broker ingests into the active run
  artifact.  It rides a result frame — not a separate message type — so
  span reports inherit result-frame dedup: a duplicated frame cannot
  double-ingest.

Cache services are HTTP side channels, not frames: both the shared
fitness service (``fitness_service.py``, ``--cache-url``) and the
fleet-wide compile-artifact cache (``compile_service.py``,
``--compile-cache-url``) run over their own stdlib-HTTP connections,
never over this socket.  The broker protocol is therefore entirely
unaware of them — a worker prefetches compiled executables and publishes
fresh ones out-of-band, and nothing on this wire changes whether the
services are up, degraded, or absent (that independence is what lets
cache downtime never fail a search).

Pings are deliberately UNANSWERED: the broker's ``last_seen`` update is
the liveness mechanism, and replies the worker only reads between batches
would pile up unread during a long training batch — a worker exiting
right after its final results would then RST away the in-flight result
frames (see ``client._graceful_close``).  Workers detect a dead broker by
EOF/send-failure, never by pong absence.

Delivery semantics (matching AMQP's, SURVEY.md §5 "Failure detection"):
at-least-once.  A job is requeued when its worker disconnects or stops
pinging before sending ``result``; the master deduplicates by ``job_id`` and
keeps the first fitness, so redelivery never double-counts.

Jobs travel in **batches**: a dispatch to a worker is a single ``jobs``
frame holding everything that worker's credit allows.  This is what makes
capacity > 1 deterministic — a capacity-8 worker receives its 8 jobs in one
frame regardless of network latency, so the worker never has to guess (with
a read timeout) whether more jobs are in flight.  One bounded exception: a
batch whose encoded size would approach ``MAX_MESSAGE_BYTES`` is split at a
soft size cap into several consecutive ``jobs`` frames, which the worker
consumes (and trains) one frame at a time — batching degrades gracefully
for pathologically large payloads instead of breaking the protocol.

Results travel the same way: a worker's evaluation group replies with ONE
``results`` frame per capacity window (``coalesce_results``) instead of a
TCP frame per job, so a capacity-8 batch is 1 syscall + 1 broker wake-up
instead of 8 — this shaves the measured small-batch RPC floor of the
converged tail (PERF.md "Tail generations") in both the generational and
the asynchronous mode.  Each entry inside the frame is deduplicated
independently on the broker (at-least-once semantics are unchanged), the
group's span report rides the frame exactly as it used to ride the first
``result`` frame, and the single-job ``result`` frame remains accepted for
back-compat with older workers.

Wire fast path (same OPTIONAL-with-conservative-defaults convention —
DISTRIBUTED.md "Wire fast path"):

- ``hello`` may carry ``caps`` [str]: wire capabilities the worker can
  decode beyond the v1 frame set.  The broker intersects them with its
  own (``JobBroker(wire_caps=...)``) and echoes the GRANTED set back on
  ``welcome`` — a capability is live only when both ends named it.  An
  old broker ignores ``caps`` and sends a bare ``welcome``; an old
  worker never sends ``caps`` and its ``welcome`` stays byte-identical
  to pre-caps brokers, so mixed fleets interoperate on the v1 path with
  zero configuration.
- ``jobs2`` {shared: {...}, jobs: [{job_id, gk, genes, ...}, ...]}
  (capability ``"jobs2"``): a dispatch frame that hoists the envelope
  fields every job of the window shares — ``additional_parameters``,
  ``fidelity``, ``trace``, ``session`` — into ONE per-frame ``shared``
  block instead of duplicating them into every entry.  The worker
  expands each entry as ``dict(shared)`` + per-entry overrides
  (``expand_jobs2``), so the shared params VALUE is decoded once and
  one object is reused across the window (evaluators treat it
  read-only).  Each entry also carries ``gk``, the broker's
  already-computed ``genome_key``, so the worker never re-hashes genes
  for forensics attribution.  The broker groups a dispatch batch by
  envelope; a heterogeneous batch degrades to one ``jobs2`` frame per
  distinct envelope, never to an incorrect merge.
- encode-once fragments: the master keeps a bounded
  ``GenomeFragmentCache`` mapping ``genome_key`` → the genes' serialized
  JSON bytes, so a genome is dumped exactly once per master lifetime and
  every dispatch — first send, disconnect requeue, straggler speculative
  requeue, promotion re-dispatch — reassembles its frame by joining
  cached byte fragments (``build_job_wire``).  Assembly is byte-for-byte
  identical to ``encode({"job_id": ..., **payload})``, which the
  back-compat tests pin, so fault injectors and v1 workers observe
  exactly the frames a pre-fast-path broker produced.

Cross-session window packing (same OPTIONAL convention — DISTRIBUTED.md
"Cross-session window packing"):

- a ``jobs``/``jobs2`` frame may carry top-level ``packed: true``: the
  broker sized this window as ONE evaluation batch (already
  mesh-aligned to the receiving worker's capacity), coalescing jobs
  from different sessions that share a compile-compatible envelope.  A
  packing-aware worker asserts the frame never re-splits in
  ``_chunk_jobs`` (``packed_window_resplit_total`` counts violations —
  degrade loudly, never drop); an old worker ignores the unknown key
  and chunks as always, which is safe because a packed window is never
  larger than the worker's advertised capacity.  The marker is emitted
  ONLY by a ``JobBroker(pack_windows=True)`` — a pack-off broker's
  frames stay byte-identical to this build's predecessors.
- a packed ``jobs2`` frame hoists only :data:`PACK_ENVELOPE_FIELDS`
  (``additional_parameters``, ``fidelity`` — the compile-compatibility
  envelope) into ``shared``; the per-job tenant fields (``session``,
  ``trace``) ride each entry instead (``packed_entry2``).
  ``expand_jobs2`` already lets per-entry keys override the envelope,
  so expansion is lossless and per-job session attribution survives
  the shared hoist.
"""

from __future__ import annotations

import json
import threading
from collections import OrderedDict
from typing import Any, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "encode",
    "decode",
    "coalesce_results",
    "MAX_MESSAGE_BYTES",
    "ProtocolError",
    "AuthError",
    "WIRE_CAPS",
    "SHARED_ENVELOPE_FIELDS",
    "parse_caps",
    "GenomeFragmentCache",
    "JobWire",
    "build_job_wire",
    "jobs_frame",
    "jobs2_frame",
    "expand_jobs2",
    "PACK_ENVELOPE_FIELDS",
    "pack_envelope",
    "packed_entry2",
    "PreencodedMessage",
]

#: Hard cap per message; genes + params are a few KB, so anything huge is a
#: protocol violation (or an attempt to ship training data, which the design
#: forbids — data lives with the worker).
MAX_MESSAGE_BYTES = 4 * 1024 * 1024


class ProtocolError(Exception):
    """Malformed or oversized frame."""


class AuthError(ConnectionError):
    """The broker rejected this worker's credentials (``error: bad token``).

    Unlike a network blip, auth rejection is deterministic — reconnecting
    with the same token can never succeed — so ``GentunClient.work()``
    treats it as TERMINAL instead of retrying forever (the reference's
    RabbitMQ credential failure is equally loud [PUB]).  Subclasses
    ``ConnectionError`` so pre-existing callers that catch broadly keep
    working.
    """


class PreencodedMessage(dict):
    """A message dict that carries its own wire frame, assembled from cached
    fragments.  ``encode()`` sends ``wire`` verbatim when set, so assemblers
    pay serialization once while fault injectors and tests still see a typed
    dict.  The assembler owns the invariant that ``wire`` matches the dict —
    mutate the dict after assembly and the bytes go stale.
    """

    __slots__ = ("wire",)

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)
        self.wire: Optional[bytes] = None


def encode(msg: Dict[str, Any]) -> bytes:
    """Message dict → one newline-terminated JSON frame.

    A :class:`PreencodedMessage` whose frame was already assembled (wire
    fast path, ``coalesce_results``) returns its bytes without re-dumping;
    plain dicts pay one attribute probe (~ns) and serialize as before.
    """
    wire = getattr(msg, "wire", None)
    if wire is not None:
        return wire
    data = json.dumps(msg, separators=(",", ":")).encode("utf-8")
    if len(data) > MAX_MESSAGE_BYTES:
        raise ProtocolError(f"message of {len(data)} bytes exceeds {MAX_MESSAGE_BYTES}")
    return data + b"\n"


def decode(line: bytes) -> Dict[str, Any]:
    """One frame (without trailing newline requirement) → message dict."""
    # Strip the framing newline before the size check so a payload of
    # exactly MAX_MESSAGE_BYTES (which encode() allows) round-trips.
    line = line.rstrip(b"\n")
    if len(line) > MAX_MESSAGE_BYTES:
        raise ProtocolError(f"frame of {len(line)} bytes exceeds {MAX_MESSAGE_BYTES}")
    try:
        msg = json.loads(line)
    except json.JSONDecodeError as e:
        raise ProtocolError(f"bad JSON frame: {e}") from e
    if not isinstance(msg, dict) or "type" not in msg:
        raise ProtocolError(f"frame is not a typed message: {msg!r}")
    return msg


# --------------------------------------------------------------------------
# Wire fast path: encode-once fragments, v1/v2 frame assembly, capability
# negotiation.  See the module docstring ("Wire fast path") and
# DISTRIBUTED.md for the design; tests/test_protocol.py pins the
# byte-identity invariants.
# --------------------------------------------------------------------------

#: Capabilities this build can speak beyond the v1 frame set.  Both ends
#: default to advertising all of them; pass ``wire_caps=()`` to
#: ``JobBroker``/``GentunClient`` to emulate a v1 peer (ops kill switch,
#: mixed-fleet tests).
WIRE_CAPS: Tuple[str, ...] = ("jobs2",)

#: Envelope fields a ``jobs2`` frame hoists into its ``shared`` block.  The
#: tuple order is the hoisting order; grouping is by exact serialized value,
#: so hoisting is always lossless.
SHARED_ENVELOPE_FIELDS: Tuple[str, ...] = (
    "additional_parameters", "fidelity", "trace", "session")

_SHARED_SET = frozenset(SHARED_ENVELOPE_FIELDS)

#: The compile-compatibility slice of the envelope — the fields whose
#: serialized bytes must match for two jobs to share one packed device
#: window (static config fingerprint + fidelity fingerprint; the genome
#: size class rides alongside in the broker's pack key).  ``trace`` and
#: ``session`` are deliberately absent: they are per-tenant attribution,
#: not compile inputs, and stay per-entry in a packed frame.
PACK_ENVELOPE_FIELDS: Tuple[str, ...] = ("additional_parameters", "fidelity")

_PACK_SET = frozenset(PACK_ENVELOPE_FIELDS)

#: Fixed framing bytes around a single-entry ``jobs`` frame — used to give
#: submit-time oversize validation the exact byte count ``encode()`` saw.
_JOBS_FRAME_OVERHEAD = len(b'{"type":"jobs","jobs":[]}')


def parse_caps(msg: Dict[str, Any]) -> frozenset:
    """The ``caps`` field of a ``hello``/``welcome`` as a frozenset of
    strings; anything malformed degrades to "no capabilities" (the v1
    path), never to an error — same conservative-defaults posture as
    ``n_chips``/``mesh``."""
    caps = msg.get("caps")
    if not isinstance(caps, (list, tuple)):
        return frozenset()
    return frozenset(c for c in caps if isinstance(c, str))


# Per-field assembly calls the serializer once per VALUE, so the fixed cost
# of each call matters here in a way it never did for whole-frame encode():
# a shared encoder instance skips the per-call JSONEncoder construction that
# custom separators force on json.dumps, and plain strings (job ids, genome
# keys, session ids) go straight to the C escaper.  Output stays
# byte-identical to ``json.dumps(obj, separators=(",", ":"))``.
_json_encode = json.JSONEncoder(separators=(",", ":")).encode
_escape_str = json.encoder.encode_basestring_ascii


def _dumps(obj: Any) -> bytes:
    if type(obj) is str:
        return _escape_str(obj).encode("utf-8")
    return _json_encode(obj).encode("utf-8")


# Payload keys come from a tiny fixed vocabulary (genes, additional_parameters,
# fidelity, trace, session, ...), so their serialized forms are memoized —
# per-field assembly then pays dumps() only for VALUES.
_key_bytes_cache: Dict[str, bytes] = {}


def _key_bytes(key: str) -> bytes:
    b = _key_bytes_cache.get(key)
    if b is None:
        if len(_key_bytes_cache) > 256:  # wire vocabularies don't grow; bound anyway
            _key_bytes_cache.clear()
        b = _key_bytes_cache[key] = _dumps(key)
    return b


class GenomeFragmentCache:
    """Bounded LRU of ``genome_key`` → the genes' serialized JSON bytes.

    A genome's wire fragment is dumped exactly once per master lifetime
    (first dispatch) and reused by every later frame assembly — requeues,
    speculative refills, promotion re-dispatch.  Thread-safe: ``submit()``
    builds fragments in the caller thread while the broker loop assembles
    frames from them.  ``hits``/``misses`` are advisory totals for gates
    and panels, not synchronization.
    """

    def __init__(self, max_entries: int = 8192) -> None:
        self._max = max(1, int(max_entries))
        self._lock = threading.Lock()
        self._frags: "OrderedDict[str, bytes]" = OrderedDict()
        self.hits = 0
        self.misses = 0

    def fragment(self, key: str, genes: Any) -> bytes:
        with self._lock:
            frag = self._frags.get(key)
            if frag is not None:
                self._frags.move_to_end(key)
                self.hits += 1
                return frag
        frag = _dumps(genes)  # dump outside the lock; losing a race is harmless
        with self._lock:
            self.misses += 1
            self._frags[key] = frag
            while len(self._frags) > self._max:
                self._frags.popitem(last=False)
        return frag

    def __len__(self) -> int:
        with self._lock:
            return len(self._frags)

    @property
    def max_entries(self) -> int:
        return self._max


class JobWire:
    """A job's cached wire forms, built once at enqueue and reused for every
    (re-)dispatch:

    - ``v1``: the complete v1 ``jobs`` entry bytes — byte-identical to
      ``json.dumps({"job_id": job_id, **payload}, separators=(",", ":"))``.
    - ``entry2``: the ``jobs2`` entry bytes (job_id + gk + non-envelope
      fields; the envelope lives in the frame's ``shared`` block).
    - ``env``: the envelope as a hashable ``((field, value_bytes), ...)``
      tuple — the grouping key AND the ``shared``-block fragments.
    - ``gk``: the genome key, carried so enqueue bookkeeping (quarantine,
      lineage, dedup) reuses the hash computed at build time.
    """

    __slots__ = ("gk", "v1", "entry2", "env")

    def __init__(self, gk: str, v1: bytes, entry2: bytes,
                 env: Tuple[Tuple[str, bytes], ...]) -> None:
        self.gk = gk
        self.v1 = v1
        self.entry2 = entry2
        self.env = env

    def with_session(self, session: str) -> "JobWire":
        """This wire record with the tenant tag appended — mirrors the
        broker adding ``payload["session"]`` as the LAST payload key, so
        ``v1`` stays byte-identical to the tagged dict's encoding.  The tag
        joins the envelope, keeping ``jobs2`` grouping session-disjoint."""
        sid_bytes = _dumps(session)
        v1 = b"".join((self.v1[:-1], b',"session":', sid_bytes, b"}"))
        return JobWire(self.gk, v1, self.entry2,
                       self.env + (("session", sid_bytes),))


def build_job_wire(job_id: str, payload: Dict[str, Any], gk: str,
                   cache: GenomeFragmentCache,
                   memo: Optional[Dict[int, Tuple[Any, bytes]]] = None) -> JobWire:
    """Assemble a job's cached wire forms from fragments (one dumps() per
    non-genes field; genes come from ``cache``).  Raises
    :class:`ProtocolError` for a payload no single-entry frame could carry,
    with the same byte accounting ``encode()`` would have reported — this
    doubles as the submit-time validation pass.

    ``memo`` (optional) dedups value serialization WITHIN one submit batch:
    the master ships one shared params/fidelity object across a population's
    payloads, so the batch pays one dumps() for it, not one per job.  Keyed
    by ``id()`` with an identity check, and the memo holds a reference to
    each value, so entries can't alias a recycled id.  Pass a dict scoped to
    the batch loop — never a long-lived one (values may mutate between
    submits).
    """
    fields: List[Tuple[str, bytes]] = []
    for k, v in payload.items():
        if k == "job_id":
            continue  # entry position 0 below; {"job_id": ..., **payload} keeps one copy
        if k == "genes":
            b = cache.fragment(gk, v)
        elif memo is not None:
            hit = memo.get(id(v))
            if hit is not None and hit[0] is v:
                b = hit[1]
            else:
                b = _dumps(v)
                memo[id(v)] = (v, b)
        else:
            b = _dumps(v)
        fields.append((k, b))
    jid_bytes = _dumps(payload.get("job_id", job_id))

    parts = [b'{"job_id":', jid_bytes]
    for k, b in fields:
        parts += (b",", _key_bytes(k), b":", b)
    parts.append(b"}")
    v1 = b"".join(parts)
    total = _JOBS_FRAME_OVERHEAD + len(v1)
    if total > MAX_MESSAGE_BYTES:
        raise ProtocolError(f"message of {total} bytes exceeds {MAX_MESSAGE_BYTES}")

    parts2 = [b'{"job_id":', jid_bytes, b',"gk":', _dumps(gk)]
    env: List[Tuple[str, bytes]] = []
    for k, b in fields:
        if k in _SHARED_SET:
            env.append((k, b))
        else:
            parts2 += (b",", _key_bytes(k), b":", b)
    parts2.append(b"}")
    return JobWire(gk, v1, b"".join(parts2), tuple(env))


def _finish_frame(body: bytes) -> bytes:
    if len(body) > MAX_MESSAGE_BYTES:
        raise ProtocolError(f"message of {len(body)} bytes exceeds {MAX_MESSAGE_BYTES}")
    return body + b"\n"


def jobs_frame(entries: Iterable[bytes], packed: bool = False) -> bytes:
    """Join v1 entry bytes into one ``jobs`` frame — byte-identical to
    ``encode({"type": "jobs", "jobs": [...]})`` over the decoded entries.
    ``packed=True`` adds the ``"packed":true`` marker (cross-session
    window packing); the default path's bytes are untouched, which is
    what makes a pack-off broker wire-byte-identical by construction."""
    head = (b'{"type":"jobs","packed":true,"jobs":[' if packed
            else b'{"type":"jobs","jobs":[')
    return _finish_frame(head + b",".join(entries) + b"]}")


def jobs2_frame(env: Iterable[Tuple[str, bytes]],
                entries: Iterable[bytes], packed: bool = False) -> bytes:
    """Join a shared envelope + ``jobs2`` entry bytes into one frame.
    ``packed=True`` marks a broker-sized cross-session window (see
    :func:`jobs_frame`); the envelope should then be the
    :func:`pack_envelope` slice with per-job fields in the entries."""
    shared = b",".join(_key_bytes(k) + b":" + v for k, v in env)
    head = (b'{"type":"jobs2","packed":true,"shared":{' if packed
            else b'{"type":"jobs2","shared":{')
    return _finish_frame(head + shared +
                         b'},"jobs":[' + b",".join(entries) + b"]}")


def pack_envelope(env: Iterable[Tuple[str, bytes]]) -> Tuple[Tuple[str, bytes], ...]:
    """The compile-compatibility slice of a :class:`JobWire` envelope:
    only :data:`PACK_ENVELOPE_FIELDS`, in envelope order.  Equality of
    this tuple (serialized bytes, not parsed values) is the broker's
    pack-compatibility test — the same exact-value grouping rule
    ``jobs2`` hoisting already relies on."""
    return tuple((k, v) for k, v in env if k in _PACK_SET)


def packed_entry2(jw: "JobWire") -> bytes:
    """A ``jobs2`` entry for a PACKED (cross-session) window: the cached
    ``entry2`` plus the per-tenant envelope fields (``session``,
    ``trace``) a packed frame cannot hoist into ``shared``.
    ``expand_jobs2`` lets per-entry keys override the envelope, so the
    worker reconstructs exactly the per-job dicts an unpacked dispatch
    would have produced — session attribution survives the hoist."""
    extra = b"".join(b"," + _key_bytes(k) + b":" + v
                     for k, v in jw.env if k not in _PACK_SET)
    if not extra:
        return jw.entry2
    return jw.entry2[:-1] + extra + b"}"


def expand_jobs2(msg: Dict[str, Any]) -> List[Dict[str, Any]]:
    """``jobs2`` frame → the v1-shaped job dicts a ``jobs`` frame would have
    carried (plus ``gk``).  The shared envelope is decoded once by the JSON
    layer; every expanded job references the SAME shared value objects
    (params dict, fidelity, trace), so a capacity window holds one params
    object, not N copies.  Per-entry keys override the envelope."""
    shared = msg.get("shared") or {}
    jobs: List[Dict[str, Any]] = []
    for entry in msg.get("jobs") or ():
        job = dict(shared)
        job.update(entry)
        jobs.append(job)
    return jobs


def coalesce_results(
    entries: List[Dict[str, Any]],
    spans: Optional[List[Dict[str, Any]]] = None,
    soft_cap: Optional[int] = None,
) -> List[Dict[str, Any]]:
    """Pack per-job result entries into the fewest ``results`` frames.

    The worker-side mirror of the broker's ``jobs`` batching: one frame per
    capacity window, split at a soft size cap (default
    ``MAX_MESSAGE_BYTES // 2``) so a pathological batch degrades into
    several valid frames instead of one oversized one.  ``spans`` (the
    group's captured telemetry report) is attached to the FIRST frame only,
    preserving the ride-the-first-result dedup contract.  Returns message
    dicts, not bytes — the client's send path owns encoding (and fault
    injection sees typed messages).  Each entry is JSON-dumped exactly once:
    the bytes that size the split also assemble the frame, which the
    returned :class:`PreencodedMessage` carries for ``encode()`` to reuse.
    """
    cap = int(soft_cap) if soft_cap else MAX_MESSAGE_BYTES // 2
    batches: List[Tuple[List[Dict[str, Any]], List[bytes]]] = []
    batch: List[Dict[str, Any]] = []
    batch_encs: List[bytes] = []
    batch_bytes = 0
    for entry in entries:
        enc = _dumps(entry)
        if batch and batch_bytes + len(enc) > cap:
            batches.append((batch, batch_encs))
            batch, batch_encs, batch_bytes = [], [], 0
        batch.append(entry)
        batch_encs.append(enc)
        batch_bytes += len(enc)
    if batch:
        batches.append((batch, batch_encs))
    frames: List[Dict[str, Any]] = []
    for i, (group, encs) in enumerate(batches):
        msg = PreencodedMessage({"type": "results", "results": group})
        body = b'{"type":"results","results":[' + b",".join(encs) + b"]"
        if i == 0 and spans:
            msg["spans"] = spans
            body += b',"spans":' + _dumps(spans)
        body += b"}"
        if len(body) <= MAX_MESSAGE_BYTES:
            msg.wire = body + b"\n"
        # else: wire stays None and encode() raises its usual oversize
        # ProtocolError when the frame is actually sent — unchanged contract.
        frames.append(msg)
    return frames
