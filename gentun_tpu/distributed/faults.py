"""Deterministic fault injection for the distributed plane.

The broker reimplements the AMQP semantics the reference got for free from
RabbitMQ (SURVEY.md §3.2, §5: competing consumers, ack-after-work,
at-least-once redelivery), but every hardware artifact in DISTRIBUTED.md
records **0 retries, 0 requeues, 0 penalized individuals** — the failure
machinery (reaper, redelivery, ``JobFailed``/``GatherTimeout``,
duplicate-result drop, checkpoint resume) had only ever been unit-poked.
This module drives the whole stack through its failure paths
*deterministically*: a :class:`FaultPlan` is a seeded, serializable
schedule of faults, and a :class:`FaultInjector` fires them at named hook
points threaded through the production code.

Hook points and the fault kinds each supports:

====================  ==================================================
``broker_send``       drop_connection, delay, corrupt   (per jobs-frame)
``broker_recv``       drop_connection, delay, corrupt   (per worker frame)
``client_send``       drop_connection, delay, corrupt, duplicate_result
``client_recv``       drop_connection, delay, corrupt
``client_connect``    drop_connection (refuse), delay
``worker_pre_eval``   fail_eval, hang, delay, fitness_corrupt (per job)
``master_boundary``   kill_master                       (per generation)
``journal_write``     journal_io_error, broker_crash    (per journal drain)
====================  ==================================================

Fault kinds (the recoverable failure modes the plane is DESIGNED for —
there is deliberately no "silently lose one frame" kind, because TCP never
does that; a lost frame in the real world is a broken connection):

- ``drop_connection`` — close the socket mid-protocol (worker crash /
  partition).  Broker side: requeue-on-disconnect.  Client side:
  reconnect with capped exponential backoff.
- ``delay``           — stall a frame/connect by ``delay`` seconds
  (network latency, GC pause).  Must be invisible to the search outcome.
- ``corrupt``         — replace a frame with truncated garbage.  The
  receiver's ``ProtocolError`` path must tear the connection down and
  recover exactly like a disconnect.
- ``hang``            — stop heartbeating while holding jobs for
  ``duration`` seconds (hung process).  The broker's reaper must declare
  the worker dead and redeliver.
- ``fail_eval``       — raise inside the fitness evaluation (OOM, bad
  genes).  The ``fail`` reply must requeue up to ``max_attempts``.
- ``duplicate_result``— send a ``result`` frame twice (redelivery race /
  retransmit).  The broker must count the first only.
- ``kill_master``     — raise :class:`MasterKilled` at a generation
  boundary.  A checkpointed search must resume bit-identically.
- ``journal_io_error``— torn/short write on the dispatch journal: a
  ``fraction`` prefix of the pending batch reaches the disk, then the
  journal wedges (ISSUE 16).  Replay of the truncated tail must discard
  the torn record loudly, never poison the fold.
- ``broker_crash``    — the broker dies at a journal drain point WITHOUT
  flushing (the in-process SIGKILL analog): the buffer is dropped and
  ``DispatchJournal.crash_requested`` trips, which the broker's journal
  task turns into an abrupt :meth:`JobBroker.kill`.  Restart-with-replay
  must re-adopt every open job through the at-least-once path.
- ``fitness_corrupt`` — the evaluation SUCCEEDS but the worker reports a
  deterministically perturbed fitness (stale cache entry, packed-window
  demux bug, silent numeric corruption — the failure class NO transport
  machinery can catch, because the frame is well-formed).  Only the
  canary plane's golden-genome bit-equality check
  (``gentun_tpu/telemetry/canary.py``) detects it.

Zero-cost when disabled: every production hook site is a single
``if self._injector is not None`` attribute check — no allocation, no
call — and the default injector is ``None`` everywhere.
"""

from __future__ import annotations

import dataclasses
import json
import math
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from ..telemetry import spans as _tele
from ..telemetry.registry import get_registry as _get_registry
from .protocol import ProtocolError, encode

__all__ = [
    "HOOKS", "KINDS", "FaultSpec", "FaultPlan", "FaultInjector", "MasterKilled",
]

HOOKS = (
    "broker_send", "broker_recv", "client_send", "client_recv",
    "client_connect", "worker_pre_eval", "master_boundary",
    "journal_write",
)

KINDS = (
    "drop_connection", "delay", "corrupt", "hang", "fail_eval",
    "duplicate_result", "kill_master", "journal_io_error", "broker_crash",
    "fitness_corrupt",
)

#: Which kinds make sense at which hook — validated at FaultSpec build so a
#: typo'd plan fails loudly at construction, not silently never-fires.
_HOOK_KINDS: Dict[str, tuple] = {
    "broker_send": ("drop_connection", "delay", "corrupt"),
    "broker_recv": ("drop_connection", "delay", "corrupt"),
    "client_send": ("drop_connection", "delay", "corrupt", "duplicate_result"),
    "client_recv": ("drop_connection", "delay", "corrupt"),
    "client_connect": ("drop_connection", "delay"),
    "worker_pre_eval": ("fail_eval", "hang", "delay", "fitness_corrupt"),
    "master_boundary": ("kill_master",),
    "journal_write": ("journal_io_error", "broker_crash"),
}

#: A deliberately-invalid frame: ASCII so json sees JSONDecodeError (not
#: UnicodeDecodeError, which would bypass the ProtocolError path).
_CORRUPT_FRAME = b'{"truncated by fault inject' + b"\n"


class MasterKilled(RuntimeError):
    """Injected master death at a generation boundary (``kill_master``).

    Raised AFTER the boundary checkpoint was written, so the defined
    recovery is exactly a real crash's: rebuild the population (same
    port), re-run with the same checkpointer, and the search resumes
    bit-identically (``GeneticAlgorithm.run(..., checkpointer=...)``).
    """

    def __init__(self, generation: int):
        super().__init__(f"injected master kill at generation boundary {generation}")
        self.generation = int(generation)


@dataclasses.dataclass
class FaultSpec:
    """One scheduled fault: fire ``kind`` at hook ``hook`` on the ``at``-th
    matching event (0-based), for ``times`` consecutive matching events.

    ``match_type`` restricts counting to frames of one message type (e.g.
    only ``result`` frames); ``worker`` restricts broker-side hooks to one
    worker id; ``generation`` pins ``kill_master`` to a boundary.
    ``delay`` (seconds) parameterizes the ``delay`` kind, ``duration``
    the ``hang`` kind.
    """

    hook: str
    kind: str
    at: int = 0
    times: int = 1
    match_type: Optional[str] = None
    worker: Optional[str] = None
    generation: Optional[int] = None
    delay: float = 0.05
    duration: float = 1.0
    #: ``journal_io_error`` only: fraction of the pending batch that
    #: reaches the disk before the torn write wedges the journal.
    fraction: float = 0.5

    def __post_init__(self) -> None:
        if self.hook not in HOOKS:
            raise ValueError(f"unknown hook {self.hook!r}; choose from {HOOKS}")
        if self.kind not in KINDS:
            raise ValueError(f"unknown kind {self.kind!r}; choose from {KINDS}")
        if self.kind not in _HOOK_KINDS[self.hook]:
            raise ValueError(
                f"kind {self.kind!r} is not injectable at hook {self.hook!r} "
                f"(supported: {_HOOK_KINDS[self.hook]})"
            )
        if self.at < 0 or self.times < 1:
            raise ValueError(f"need at >= 0 and times >= 1, got at={self.at} times={self.times}")

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "FaultSpec":
        return cls(**d)


class FaultPlan:
    """A seeded, serializable schedule of faults.

    Either build explicitly from :class:`FaultSpec` entries, or draw a
    random-but-reproducible plan with :meth:`sample` — two processes given
    the same seed construct the identical schedule, which is what lets a
    chaos run be replayed exactly (``scripts/chaos_run.py`` commits the
    plan JSON next to its artifact).
    """

    def __init__(self, specs: Sequence[FaultSpec] = (), seed: Optional[int] = None):
        self.specs: List[FaultSpec] = list(specs)
        self.seed = seed

    def __len__(self) -> int:
        return len(self.specs)

    def __repr__(self) -> str:
        return f"FaultPlan(seed={self.seed}, specs={[s.to_dict() for s in self.specs]})"

    # -- (de)serialization -------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        return {"seed": self.seed, "specs": [s.to_dict() for s in self.specs]}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "FaultPlan":
        return cls(specs=[FaultSpec.from_dict(s) for s in d.get("specs", [])],
                   seed=d.get("seed"))

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), separators=(",", ":"))

    @classmethod
    def from_json(cls, payload: str) -> "FaultPlan":
        return cls.from_dict(json.loads(payload))

    @classmethod
    def sample(cls, seed: int, n_faults: int = 4,
               hooks: Optional[Sequence[str]] = None) -> "FaultPlan":
        """A reproducible random plan: ``n_faults`` draws over ``hooks``
        (default: every hook except ``master_boundary``, which needs a
        resume harness around the search loop to be survivable)."""
        import numpy as np

        rng = np.random.default_rng(seed)
        pool = tuple(hooks) if hooks is not None else tuple(
            h for h in HOOKS if h != "master_boundary")
        specs = []
        for _ in range(int(n_faults)):
            hook = pool[int(rng.integers(len(pool)))]
            kinds = _HOOK_KINDS[hook]
            kind = kinds[int(rng.integers(len(kinds)))]
            specs.append(FaultSpec(
                hook=hook, kind=kind,
                at=int(rng.integers(0, 8)),
                delay=float(rng.uniform(0.01, 0.1)),
                duration=float(rng.uniform(0.5, 2.0)),
                generation=int(rng.integers(1, 4)) if kind == "kill_master" else None,
            ))
        return cls(specs, seed=seed)


class FaultInjector:
    """Live fault-firing state for ONE component (a broker, or a client).

    Give each component its OWN injector (even when they share a plan's
    spec values): per-spec event counters are what make the schedule
    deterministic, and two components racing one counter would not be.

    Every hook method is thread-safe (one lock around the counters) and
    records what it fired in :attr:`fired` so tests and the chaos artifact
    can assert the plan actually executed.
    """

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self._lock = threading.Lock()
        self._counts = [0] * len(plan.specs)
        self.fired: List[Dict[str, Any]] = []
        self._hang_until = 0.0
        self._corrupt_jobs: set = set()

    # -- matching ----------------------------------------------------------

    def _match(self, hook: str, mtype: Optional[str] = None,
               worker: Optional[str] = None,
               generation: Optional[int] = None) -> Optional[FaultSpec]:
        """The first armed spec this event trips, advancing every matching
        spec's event counter (deterministic: counters only ever see events
        that satisfy the spec's own filters)."""
        with self._lock:
            hit = None
            for i, s in enumerate(self.plan.specs):
                if s.hook != hook:
                    continue
                if s.match_type is not None and mtype != s.match_type:
                    continue
                if s.worker is not None and worker != s.worker:
                    continue
                if s.generation is not None and generation != s.generation:
                    continue
                n = self._counts[i]
                self._counts[i] = n + 1
                if hit is None and s.at <= n < s.at + s.times:
                    hit = s
            if hit is not None:
                record = {
                    "hook": hook, "kind": hit.kind, "type": mtype,
                    "worker": worker, "generation": generation,
                }
                self.fired.append(record)
                if _tele.enabled():
                    # Structured trail of every injected fault: a counter per
                    # (hook, kind) in the registry plus an event record in the
                    # run artifact (docs/OBSERVABILITY.md; the chaos artifact
                    # asserts these — scripts/chaos_run.py).
                    _get_registry().counter(
                        "faults_injected_total", hook=hook, kind=hit.kind,
                    ).inc()
                    _tele.record_event("fault_injected", record)
            return hit

    # -- broker-side hooks (run on the broker loop thread) -----------------

    def broker_send(self, worker, msg: Dict[str, Any]) -> bool:
        """True ⇒ the broker must suppress the real send."""
        s = self._match("broker_send", msg.get("type"), worker=worker.worker_id)
        if s is None:
            return False
        if s.kind == "delay":
            time.sleep(s.delay)  # stalls the loop thread: an honest GC-pause
            return False
        if s.kind == "corrupt":
            try:
                worker.writer.write(_CORRUPT_FRAME)
            except Exception:
                pass
            return True
        # drop_connection: the reader's EOF path requeues this worker's jobs
        worker.writer.close()
        return True

    def broker_recv(self, worker, msg: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """The (possibly delayed) frame, or None ⇒ the handler must treat
        the connection as torn down (corrupt raises instead)."""
        s = self._match("broker_recv", msg.get("type"), worker=worker.worker_id)
        if s is None:
            return msg
        if s.kind == "delay":
            time.sleep(s.delay)
            return msg
        if s.kind == "corrupt":
            raise ProtocolError("injected corrupt frame")
        worker.writer.close()
        return None

    # -- client-side hooks (run on the worker's consume thread) ------------

    def client_send(self, client, msg: Dict[str, Any]) -> bool:
        """True ⇒ the client must suppress the real send (the injector has
        already written whatever the fault calls for)."""
        s = self._match("client_send", msg.get("type"))
        if s is None:
            return False
        if s.kind == "delay":
            time.sleep(s.delay)
            return False
        if s.kind == "duplicate_result":
            data = encode(msg)
            client._raw_send(data)
            client._raw_send(data)  # the replayed twin the broker must drop
            return True
        if s.kind == "corrupt":
            client._raw_send(_CORRUPT_FRAME)
            return True
        # drop_connection: die mid-batch; the consume loop's reconnect path
        # (and the broker's requeue-on-disconnect) must pick up the pieces.
        client._close()
        raise OSError("injected connection drop")

    def client_recv(self, client, msg: Dict[str, Any]) -> Dict[str, Any]:
        s = self._match("client_recv", msg.get("type"))
        if s is None:
            return msg
        if s.kind == "delay":
            time.sleep(s.delay)
            return msg
        if s.kind == "corrupt":
            raise ProtocolError("injected corrupt frame")
        client._close()
        raise ConnectionError("injected connection drop")

    def client_connect(self, client) -> None:
        s = self._match("client_connect")
        if s is None:
            return
        if s.kind == "delay":
            time.sleep(s.delay)
            return
        raise ConnectionError("injected connect refusal")

    def worker_pre_eval(self, client, job: Dict[str, Any]) -> None:
        s = self._match("worker_pre_eval", worker=None)
        if s is None:
            return
        if s.kind == "delay":
            time.sleep(s.delay)
            return
        if s.kind == "fail_eval":
            raise RuntimeError(f"injected eval failure (job {job.get('job_id')})")
        if s.kind == "fitness_corrupt":
            # The eval proceeds normally; the worker's result path consumes
            # this mark (take_fitness_corrupt) and perturbs the reported
            # fitness AFTER evaluation — a well-formed frame with a wrong
            # number, invisible to every transport check.
            with self._lock:
                self._corrupt_jobs.add(job.get("job_id"))
            return
        # hang: hold the jobs, stop heartbeating (the heartbeat loop checks
        # heartbeats_suppressed), and let the broker's reaper declare us dead.
        self._hang_until = time.monotonic() + s.duration
        time.sleep(s.duration)

    def take_fitness_corrupt(self, job_id: Any) -> bool:
        """Consume (once) a ``fitness_corrupt`` mark left by
        :meth:`worker_pre_eval` for this job."""
        with self._lock:
            if job_id in self._corrupt_jobs:
                self._corrupt_jobs.discard(job_id)
                return True
            return False

    @staticmethod
    def corrupt_fitness(value: Any) -> float:
        """The deterministic perturbation a ``fitness_corrupt`` fault
        applies: finite fitnesses shift by +1.0, anything else becomes
        1.0 — always a well-formed float, never bit-equal to the truth."""
        try:
            v = float(value)
        except (TypeError, ValueError):
            return 1.0
        if v != v or v in (float("inf"), float("-inf")):
            return 1.0
        out = v + 1.0
        if out == v:  # |v| swamps the +1.0 — nudge one ulp toward zero
            out = math.nextafter(v, 0.0)
        return out

    def heartbeats_suppressed(self) -> bool:
        """True while a ``hang`` fault is in force (checked by the client's
        heartbeat loop — once per interval, never per frame)."""
        return time.monotonic() < self._hang_until

    # -- journal hook (runs on the broker loop thread) ---------------------

    def journal_write(self, journal) -> Optional[FaultSpec]:
        """Fires once per journal drain (the batched write point, NOT per
        record).  Returns the matched spec — ``DispatchJournal._drain``
        executes the torn write / crash itself, because only it knows the
        pending bytes."""
        return self._match("journal_write")

    # -- master-side hook --------------------------------------------------

    def master_boundary(self, generation: int) -> None:
        """Fires at each generation boundary AFTER the checkpoint save;
        a matching ``kill_master`` spec raises :class:`MasterKilled`."""
        s = self._match("master_boundary", generation=generation)
        if s is not None:
            raise MasterKilled(generation)
