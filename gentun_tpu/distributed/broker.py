"""The job broker: competing consumers, ack-after-work, redelivery.

This is the rebuild's replacement for the RabbitMQ broker + ``pika`` RPC
pattern (``gentun/server.py`` [PUB][BASELINE]; SURVEY.md §3.2, §5
"Distributed communication backend").  It reproduces the exact semantics the
reference got for free from AMQP:

- **competing consumers** — whichever worker has spare credit gets the next
  job; no ordering guarantees;
- **ack-after-work** — a worker's ``result`` message is the ack; jobs held
  by a worker that disconnects or stops heartbeating are requeued and
  redelivered to another worker (at-least-once);
- **redelivery without double-count** — the first ``result`` per job wins;
  late duplicates from a worker that "died" but finished anyway are dropped;
- **per-generation barrier** — :meth:`gather` blocks until every submitted
  job has a result (stragglers gate the generation, SURVEY.md §3.2);
- **completion-driven consumption** — :meth:`wait_any` blocks only until
  *some* submitted job reaches a terminal state, which is what the
  asynchronous steady-state engine (``algorithms_async.AsyncEvolution``)
  uses instead of the barrier: a returning result immediately breeds and
  dispatches a replacement, keeping the fleet busy through the tail.

Architecture: a single asyncio event loop in a daemon thread owns ALL broker
state (no locks on the hot path); the master thread talks to it through
``call_soon_threadsafe`` and a ``threading.Condition`` around the results
dict.  This control plane rides DCN between TPU-VM hosts; the data plane
(collectives inside a worker's slice) is jax's, over ICI — the two never mix
(SURVEY.md §5).

One deliberate extension beyond the reference: **worker capacity**.  A
worker may announce capacity N > 1 and receive N jobs at once, which lets a
TPU worker train the whole batch as one vmapped program (``models/cnn.py``)
instead of one individual at a time — the reference's one-job-per-worker
model wastes the MXU on small populations.
"""

from __future__ import annotations

import asyncio
import hmac
import itertools
import logging
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Set

from ..parallel.mesh import SIZE_SMALL, job_size_class
from ..telemetry import health as _health
from ..telemetry import lineage as _lineage
from ..telemetry import spans as _tele
from ..telemetry.registry import get_registry as _get_registry
from .journal import DispatchJournal, replay_file
from .packing import WindowPacker
from .protocol import (
    MAX_MESSAGE_BYTES,
    WIRE_CAPS,
    GenomeFragmentCache,
    JobWire,
    ProtocolError,
    build_job_wire,
    decode,
    encode,
    jobs2_frame,
    jobs_frame,
    pack_envelope,
    packed_entry2,
    parse_caps,
)
from .sessions import (
    DEFAULT_SESSION,
    FairShareScheduler,
    SearchSession,
    SessionRegistry,
    UnknownSessionError,
    genome_key,
)

__all__ = ["JobBroker", "JobFailed", "GatherTimeout"]

logger = logging.getLogger("gentun_tpu.distributed")


class JobFailed(RuntimeError):
    """Some jobs exhausted their delivery attempts (every try raised worker-side).

    Raised by :meth:`JobBroker.gather` only after EVERY submitted job reached
    a terminal state, so it carries the full picture of the barrier:

    - :attr:`failures` — ``{job_id: reason}`` for the jobs that failed;
    - :attr:`partial` — ``{job_id: fitness}`` for the jobs that succeeded.

    The broker prunes all state for the gathered jobs before raising, so the
    defined retry is simply: apply ``partial``, then submit fresh jobs for
    the failed work (``DistributedPopulation.evaluate`` does exactly this —
    calling it again after a ``JobFailed`` reships only the failed
    individuals, with reset attempt counts).
    """

    def __init__(self, message: str, failures: Optional[Dict[str, str]] = None,
                 partial: Optional[Dict[str, float]] = None):
        super().__init__(message)
        self.failures = dict(failures or {})
        self.partial = dict(partial or {})


class GatherTimeout(TimeoutError):
    """The barrier timed out with jobs still unfinished (and none failed —
    a deadline with permanent failures raises :class:`JobFailed` instead).

    :attr:`partial` carries the fitnesses that DID arrive before the
    deadline, so a straggler-timeout generation keeps its finished work.
    The broker cancels the unfinished jobs and prunes all gathered state
    before raising, so a resubmit starts clean.
    """

    def __init__(self, message: str, partial: Optional[Dict[str, float]] = None):
        super().__init__(message)
        self.partial = dict(partial or {})


class _Worker:
    """Per-connection state, touched only from the broker loop thread."""

    __slots__ = ("worker_id", "writer", "capacity", "prefetch_depth", "credit",
                 "in_flight", "last_seen", "n_chips", "backend", "draining",
                 "mesh", "caps", "preemptible", "homes")

    def __init__(self, worker_id: str, writer: asyncio.StreamWriter, capacity: int,
                 n_chips: int = 1, backend: Optional[str] = None,
                 prefetch_depth: int = 0, mesh: Optional[Dict[str, int]] = None,
                 caps: frozenset = frozenset(), preemptible: bool = False,
                 homes: int = 1):
        self.worker_id = worker_id
        self.writer = writer
        self.capacity = capacity
        #: jobs the worker wants queued locally BEYOND its evaluation
        #: capacity (pipelined dispatch, protocol.py "Pipelined-dispatch
        #: field"); 0 for workers that never advertised one.
        self.prefetch_depth = prefetch_depth
        self.credit = 0
        self.in_flight: Set[str] = set()
        self.last_seen = time.monotonic()
        self.n_chips = n_chips
        self.backend = backend
        #: host-mesh advertisement (protocol.py "Host-mesh field"):
        #: {"pop": P, "data": D, "devices": N} for a host-level mesh
        #: worker whose capacity derives from its device mesh; None for
        #: per-chip workers (the entire pre-mesh fleet).
        self.mesh = mesh
        #: GRANTED wire capabilities (protocol.py "Wire fast path"): the
        #: intersection of what the worker advertised on ``hello`` and what
        #: this broker speaks.  Empty ⇔ the v1 frame set — every old worker.
        self.caps = caps
        #: Preemptible-capacity advertisement (protocol.py "Preemptible-
        #: capacity field"): True routes cheap rung-0 probes here when the
        #: fleet is mixed; absent/malformed on the wire degrades to False
        #: (stable), the conservative default.
        self.preemptible = preemptible
        #: Multi-home advertisement (protocol.py "Multi-home field"): how
        #: many broker shards this worker connected to.  Informational —
        #: this broker already advertised the worker's FULL window through
        #: the normal credit path (the worker meters per-broker credit
        #: itself) — but operators need it to read per-shard /statusz
        #: capacity sums correctly: a 2-homed capacity-8 worker shows 8 on
        #: BOTH shards.  1 for every single-homed (old) worker.
        self.homes = homes
        #: True once the worker announced an orderly exit (elastic
        #: membership): no new dispatches, excluded from the fleet sums —
        #: but still a live connection until its in-flight results land.
        self.draining = False

    @property
    def window(self) -> int:
        """Credit ceiling: evaluation slots plus the local prefetch queue."""
        return self.capacity + self.prefetch_depth


class JobBroker:
    """Embedded TCP job broker (master side).

    Parameters
    ----------
    host, port:
        Bind address; ``port=0`` picks an ephemeral port (read it back from
        :attr:`address` after :meth:`start`).
    token:
        Shared secret workers must present in ``hello`` — the counterpart of
        the reference's RabbitMQ user/password kwargs [PUB].  ``None``
        disables the check.
    heartbeat_timeout:
        Seconds of silence after which a worker *holding jobs* is declared
        dead and its jobs requeued.  Workers ping from a side thread even
        while training, so only a crashed/hung process trips this.
    max_attempts:
        Explicit worker-side ``fail`` replies per job before :meth:`gather`
        raises :class:`JobFailed`.  Worker *disconnects* never count (AMQP
        redelivers those indefinitely).
    fault_injector:
        Optional :class:`distributed.faults.FaultInjector` for deterministic
        chaos testing.  ``None`` (the default) costs one attribute check per
        frame and nothing else.
    straggler_floor_s, straggler_k:
        Stall-watchdog tuning (``telemetry/health.py``): a dispatched job is
        flagged as a straggler after ``max(floor, k × rolling-p95(RTT))``
        seconds in flight.  Only consulted while the ops plane is enabled
        (``telemetry.start_ops_server``); otherwise the watchdog sees no
        traffic at all.
    straggler_requeue:
        Opt-in: a flagged straggler is pulled from its worker and requeued
        for redelivery (the membership dedup drops the stalled worker's
        late result, exactly like disconnect redelivery).  Off by default —
        flagging alone never changes the dispatch schedule.
    quarantine_after:
        Poison-genome isolation (sessions.py): terminal failures of the
        SAME genome within one session before that session refuses to
        dispatch it again.  Per-session by design — a genome that crashes
        tenant A's species may be fine for tenant B's.
    quarantine_crash_requeues:
        Opt-in crash isolation: after this many disconnect-redeliveries of
        one job, the job fails terminally and its genome is quarantined in
        its session, instead of crash-looping through the whole fleet.
        ``None`` (default) preserves unbounded AMQP-style disconnect
        redelivery — required by the chaos suite's kill/redeliver tests.
    aggregator_url:
        Optional fleet metrics aggregator (``telemetry/aggregator.py``):
        while the broker runs, this process pushes metric snapshots there
        under role ``broker`` (shared per-process pusher — a master that
        also wired the URL merges roles instead of double-counting).
        Fail-open: aggregator downtime never touches dispatch.
    journal_path:
        Crash safety (ISSUE 16; ``distributed/journal.py``): path of the
        append-only dispatch journal.  :meth:`start` REPLAYS whatever is
        there first — a restarted broker re-adopts its pre-crash sessions,
        parked results, and open jobs (all requeued as suspect through the
        at-least-once path) — then appends this boot's records under a
        fresh ``boot_id``/epoch.  ``None`` (default) disables journaling
        entirely: byte-identical wire behavior and zero hot-path cost.
    journal_fsync_interval:
        Batched-fsync cadence of the journal task, seconds.  Records
        buffer in memory between fsyncs (a crash loses at most one
        interval — safe: a lost ``c`` record only means one redundant,
        deduplicated re-evaluation).
    admission_rate, admission_burst:
        Per-tenant token-bucket admission control on the WIRE tenant paths
        (``session_open``/``submit``): sustained frames/s and burst size.
        ``None`` (default) disables rate limiting.  In-process submits are
        never rate-limited — a master throttling itself deadlocks.
    admission_queue_factor:
        Back-pressure heuristic: reject wire submits/opens with a
        structured ``error {code:"admission", retry_after_s}`` while the
        undispatched backlog exceeds ``factor × live fleet capacity``.
        ``None`` (default) disables the check.
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        token: Optional[str] = None,
        heartbeat_timeout: float = 15.0,
        max_attempts: int = 3,
        fault_injector=None,
        straggler_floor_s: float = 30.0,
        straggler_k: float = 4.0,
        straggler_requeue: bool = False,
        quarantine_after: int = 3,
        quarantine_crash_requeues: Optional[int] = None,
        aggregator_url: Optional[str] = None,
        wire_caps: Optional[tuple] = None,
        journal_path: Optional[str] = None,
        journal_fsync_interval: float = 0.05,
        admission_rate: Optional[float] = None,
        admission_burst: Optional[float] = None,
        admission_queue_factor: Optional[float] = None,
        pack_windows: bool = False,
        pack_linger_ms: float = 50.0,
    ):
        self._host = host
        self._port = port
        # Fleet observability (telemetry/aggregator.py): pushing starts
        # with the broker and stops with it.  acquire_pusher dedups per
        # URL, so a master that also wired aggregator_url shares this
        # process's pusher (roles merge) instead of double-counting.
        self._aggregator_url = aggregator_url
        self._pusher = None
        self._token = token
        self._heartbeat_timeout = float(heartbeat_timeout)
        self._max_attempts = int(max_attempts)
        self._injector = fault_injector
        # Ops plane (telemetry/health.py): the watchdog is fed from the
        # loop thread behind `_health.enabled()` gates, checked by
        # _watchdog_loop.  Check cadence adapts to the floor so a test
        # with a sub-second floor is flagged promptly, without busy-spin.
        self._watchdog_interval = max(0.05, min(1.0, float(straggler_floor_s) / 4.0))
        self._straggler_requeue = bool(straggler_requeue)
        self._watchdog = _health.StallWatchdog(
            floor_s=straggler_floor_s,
            k=straggler_k,
            on_straggler=self._on_straggler if straggler_requeue else None,
        )

        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._server: Optional[asyncio.base_events.Server] = None
        self._reaper_task: Optional[asyncio.Task] = None
        self._watchdog_task: Optional[asyncio.Task] = None
        self._started = threading.Event()
        self._stopping = False

        # Crash safety (ISSUE 16): the dispatch journal and this boot's
        # identity.  _boot_id is None ⇔ journaling is off — the welcome
        # frame then carries no boot_id and the epoch check never fires,
        # byte-identical to the pre-journal broker.
        self._journal_path = journal_path
        self._journal_fsync_interval = max(0.005, float(journal_fsync_interval))
        self._journal: Optional[DispatchJournal] = None
        self._journal_task: Optional[asyncio.Task] = None
        self._journal_counts_synced: Dict[str, int] = {}
        self._boot_id: Optional[str] = None
        self._epoch = 0
        self._replay_seconds = 0.0
        self._restarts = 0
        # Admission control (wire tenants only): per-session token buckets
        # (sid -> (tokens, last_refill)) plus saturation back-pressure.
        # Loop-thread state, like the scheduler.
        self._admission_rate = None if admission_rate is None else float(admission_rate)
        self._admission_burst = None if admission_burst is None else float(admission_burst)
        self._admission_queue_factor = (
            None if admission_queue_factor is None else float(admission_queue_factor))
        self._admission_buckets: Dict[str, tuple] = {}
        self._admission_rejections: Dict[str, int] = {}
        # Cross-session window packing (ISSUE 19, packing.py): OFF by
        # default — _packer is None ⇔ _dispatch takes the original path
        # and every frame stays byte-identical to a pack-off build.
        # Loop-thread state, like the scheduler.
        self._pack_windows = bool(pack_windows)
        self._pack_linger_s = max(0.0, float(pack_linger_ms) / 1000.0)
        self._packer: Optional[WindowPacker] = (
            WindowPacker(self._pack_linger_s) if self._pack_windows else None)
        self._pack_timer: Optional[asyncio.TimerHandle] = None

        # Loop-thread state.  A job is "open" iff its id is in _payloads:
        # the first result pops the payload, and every other path (dispatch,
        # requeue, fail) checks membership — that is what makes redelivery
        # duplicates and stale scheduler entries harmless.
        #
        # Multi-tenant sessions (sessions.py): the single pending deque is
        # replaced by a fair-share scheduler over per-session queues.  With
        # one session (the implicit default) it degenerates to the old FIFO.
        self._registry = SessionRegistry(quarantine_after=quarantine_after)
        self._quarantine_crash_requeues = (
            None if quarantine_crash_requeues is None
            else max(1, int(quarantine_crash_requeues)))
        self._sched = FairShareScheduler(self._registry.weight)
        self._payloads: Dict[str, Dict[str, Any]] = {}
        self._fail_counts: Dict[str, int] = {}
        # Session tenancy maps, popped exactly where _payloads is popped.
        self._job_session: Dict[str, str] = {}
        self._job_genome: Dict[str, str] = {}
        self._crash_counts: Dict[str, int] = {}
        # Wire fast path (protocol.py "Wire fast path"): capabilities this
        # broker grants workers, the per-master genome fragment cache, and
        # the per-open-job wire records (popped exactly where _payloads is
        # popped) that make every re-dispatch a byte-join instead of a
        # re-serialization.
        self._wire_caps = frozenset(WIRE_CAPS if wire_caps is None else wire_caps)
        self._frag_cache = GenomeFragmentCache()
        self._job_wire: Dict[str, JobWire] = {}
        # Memoized wire-telemetry handles (memoize-or-die: the registry's
        # get-or-create takes a lock per lookup; the dispatch path bumps
        # per frame, not per job, but still holds its instruments).
        self._wire_counters: Dict[str, tuple] = {}
        self._encode_hist = None
        self._encode_samples = 0
        self._workers: Dict[int, _Worker] = {}
        self._worker_seq = itertools.count()
        # Sticky once any preemptible member has joined: gates the
        # preemptible_members gauge so stable-only fleets emit no new series.
        self._seen_preemptible = False
        # Telemetry (loop-thread only): monotonic (re)enqueue stamp per open
        # job, feeding queue_wait and job spans.  Populated only while
        # telemetry is enabled; pruned wherever _payloads is pruned.
        self._tele_enqueued: Dict[str, float] = {}
        # Monotonic handoff-to-worker stamp per dispatched job, feeding the
        # dispatch_rtt_s histogram (handoff → result: worker queue residence
        # + evaluation + frame transit).  Same lifecycle discipline as
        # _tele_enqueued; a requeue removes the stamp (the job is no longer
        # dispatched).
        self._tele_dispatched: Dict[str, float] = {}
        # TTFD anchors (loop-thread writes, snapshot reads): per-session
        # monotonic stamps of the FIRST submit and FIRST worker handoff,
        # feeding session_ttfd() and the session_stats wire reply's
        # ttfd_s.  Always maintained (one dict-membership check per job,
        # not per frame); cleared on session close.
        self._first_submit_t: Dict[str, float] = {}
        self._first_dispatch_t: Dict[str, float] = {}

        # Cross-thread results channel
        self._cond = threading.Condition()
        self._results: Dict[str, float] = {}
        self._failures: Dict[str, str] = {}
        # Running max of the fleet's advertised chip total, sampled whenever
        # a result arrives (ADVICE r4: a worker that disconnects right after
        # its final result must still count in the per-chip denominator).
        self._chips_seen = 0

    # -- lifecycle ---------------------------------------------------------

    @property
    def address(self) -> tuple[str, int]:
        if not self._started.is_set():
            raise RuntimeError("broker not started")
        return self._bound  # set in _serve

    def start(self) -> "JobBroker":
        if self._thread is not None:
            return self
        self._stopping = False  # allow stop() → start() restart
        if self._journal_path is not None and self._journal is None:
            # Replay BEFORE the loop serves: the rebuilt state is primed
            # single-threaded, and the first reconnecting worker already
            # sees the re-adopted queue.
            self._adopt_journal()
        self._thread = threading.Thread(target=self._run_loop, name="gentun-broker", daemon=True)
        self._thread.start()
        if not self._started.wait(timeout=10.0):
            raise RuntimeError("broker failed to start within 10s")
        # Ops-plane registration: dict writes, harmless while the plane is
        # disabled.  The loop's beat gates /healthz — a wedged broker loop
        # goes stale within a few watchdog intervals.
        _health.register_source(
            "broker_loop", timeout=max(2.0, 10.0 * self._watchdog_interval))
        _health.register_watchdog(self._watchdog)
        _health.register_status_provider("fleet", self._ops_status)
        if self._aggregator_url and self._pusher is None:
            from ..telemetry.aggregator import acquire_pusher
            self._pusher = acquire_pusher(self._aggregator_url, role="broker")
        return self

    def stop(self) -> None:
        if self._loop is None:
            return
        self._stopping = True
        loop = self._loop

        async def _shutdown():
            # loop.stop() sits in the finally: if any close() below raises,
            # run_forever must still return — otherwise the loop thread
            # outlives stop() as an unjoinable zombie holding the port.
            try:
                for w in list(self._workers.values()):
                    w.writer.close()
                if self._server is not None:
                    self._server.close()
                # Cancel every other task — connection handlers, the reaper
                # — and WAIT for their cleanup before stopping the loop:
                # stopping with handlers still parked on readline() destroys
                # pending tasks ("Task was destroyed but it is pending!" at
                # every master exit) and skips their finally-block cleanup.
                tasks = [t for t in asyncio.all_tasks(loop)
                         if t is not asyncio.current_task()]
                for t in tasks:
                    t.cancel()
                await asyncio.gather(*tasks, return_exceptions=True)
            finally:
                loop.stop()

        loop.call_soon_threadsafe(lambda: asyncio.ensure_future(_shutdown()))
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            if self._thread.is_alive():  # pragma: no cover - defensive
                logger.warning(
                    "broker loop thread did not exit within 5s of stop(); "
                    "abandoning it (daemon) — port may stay bound until "
                    "process exit"
                )
        self._thread = None
        self._loop = None
        self._started.clear()
        # The linger timer handle belongs to the dead loop; a restart's
        # first dispatch re-arms on the new one.
        self._pack_timer = None
        if self._journal is not None:
            # Clean shutdown: final batched fsync.  (kill() abandons the
            # buffer FIRST, so a killed broker's journal truly loses its
            # un-fsynced tail, like a real crash's.)  Dropping the handle
            # makes the next start() replay the file afresh.
            self._journal.close()
            self._journal = None
        _health.unregister_watchdog(self._watchdog)
        _health.unregister_status_provider("fleet", self._ops_status)
        _health.unregister_source("broker_loop")
        if self._pusher is not None:
            from ..telemetry.aggregator import release_pusher
            release_pusher(self._pusher)
            self._pusher = None
        self._watchdog.clear()

    def kill(self) -> None:
        """In-process SIGKILL analog (chaos / HA harness): die NOW.

        The journal's un-fsynced buffer is dropped on the floor first —
        exactly what a real ``kill -9`` takes — then every TCP connection
        and ALL loop-thread dispatch state is destroyed.  Workers see a
        disconnect and re-enter their capped-backoff reconnect loops; wire
        tenants likewise.  The ONLY road back is :meth:`start` replaying
        the same ``journal_path``.  The cross-thread results channel
        (``_results``/``_failures``/``_cond``) survives deliberately: it
        is the MASTER's memory, and for an embedded broker the master
        process did not die.
        """
        if self._journal is not None:
            self._journal.abandon()
        self.stop()
        self._registry = SessionRegistry(
            quarantine_after=self._registry.quarantine_after)
        self._sched = FairShareScheduler(self._registry.weight)
        self._payloads.clear()
        self._fail_counts.clear()
        self._job_session.clear()
        self._job_genome.clear()
        self._crash_counts.clear()
        self._job_wire.clear()
        self._frag_cache = GenomeFragmentCache()
        self._tele_enqueued.clear()
        self._tele_dispatched.clear()
        self._workers.clear()
        self._admission_buckets.clear()
        # Held pack windows die with the boot: the journal never saw a
        # dispatch for them, so replay returns them to the scheduler and
        # the fresh packer simply re-packs.
        if self._pack_windows:
            self._packer = WindowPacker(self._pack_linger_s)
        self._pack_timer = None
        self._journal = None
        self._boot_id = None

    def _adopt_journal(self) -> None:
        """Replay ``journal_path`` and rebuild the pre-crash dispatch
        state (caller thread, BEFORE the loop starts — single-threaded by
        construction).  Every replayed open job is suspect: requeued
        through the exact at-least-once path a worker disconnect uses,
        with its wire bytes rebuilt through the fragment cache so a
        re-send is byte-identical to the pre-crash dispatch."""
        t0 = time.perf_counter()
        state = replay_file(self._journal_path)
        restart = state.epoch > 0
        journal = DispatchJournal(self._journal_path,
                                  fsync_interval=self._journal_fsync_interval,
                                  fault_injector=self._injector)
        journal.open(state)  # compacts to the adopted snapshot, bumps epoch
        for sid, s in state.sessions.items():
            sess = self._registry.open(sid, weight=s["w"],
                                       max_in_flight=s["q"], remote=s["r"])
            if s["closed"]:
                # Keep the id burned: re-opening a closed session must
                # still raise, exactly as before the crash.
                sess.closed = True
                continue
            sess.quarantine |= s["quarantine"]
            for frame in s["parked"]:
                sess.undelivered.append(frame)
        memo: dict = {}
        for job_id, job in state.jobs.items():
            payload, sid = job["p"], job["sid"]
            gk = job["gk"] or genome_key(payload.get("genes"))
            jw = build_job_wire(job_id, payload, gk, self._frag_cache, memo)
            if sid != DEFAULT_SESSION:
                payload = dict(payload)
                payload["session"] = sid
                jw = jw.with_session(sid)
            self._payloads[job_id] = payload
            self._job_wire[job_id] = jw
            self._job_session[job_id] = sid
            self._job_genome[job_id] = gk
            self._sched.push(sid, job_id)
            sess = self._registry.peek(sid)
            if sess is not None and job["d"]:
                sess.requeued += 1  # was in flight when the broker died
        self._journal = journal
        self._boot_id = journal.boot_id
        self._epoch = journal.epoch
        self._journal_counts_synced = {}
        elapsed = time.perf_counter() - t0
        self._replay_seconds = journal.replay_seconds = round(elapsed, 6)
        reg = _get_registry()
        reg.gauge("journal_replay_seconds").set(elapsed)
        reg.gauge("broker_epoch").set(self._epoch)
        if restart:
            self._restarts += 1
            reg.counter("broker_restarts_total").inc()
            logger.warning(
                "broker restarted into epoch %d from journal %s: re-adopted "
                "%d session(s), requeued %d suspect open job(s) in %.3fs%s",
                self._epoch, self._journal_path, len(state.sessions),
                len(state.jobs), elapsed,
                " (torn tail discarded)" if state.torn_tail else "")
            _tele.record_event("broker_restarted", {
                "epoch": self._epoch, "sessions": len(state.sessions),
                "suspect_jobs": len(state.jobs),
                "replay_seconds": round(elapsed, 6),
                "torn_tail": state.torn_tail,
            })

    async def _journal_loop(self) -> None:
        """Batched-fsync driver: ONE ``writelines+flush+fsync`` per
        interval, whatever the dispatch rate — the hot path only appends
        pre-formatted strings (``run_journal_gate`` holds that cost to
        ≤ 2% of a dispatch).  Also threshold-compacts, mirrors the
        journal's record counts into ``journal_records_total{type}``, and
        turns an injected ``broker_crash`` into an abrupt :meth:`kill`."""
        journal = self._journal
        if journal is None:
            return
        while not self._stopping:
            await asyncio.sleep(self._journal_fsync_interval)
            journal.flush()
            journal.maybe_compact()
            if _tele.enabled():
                reg = _get_registry()
                for rtype, n in journal.status()["records_total"].items():
                    seen = self._journal_counts_synced.get(rtype, 0)
                    if n > seen:
                        reg.counter("journal_records_total", type=rtype).inc(n - seen)
                        self._journal_counts_synced[rtype] = n
            if journal.crash_requested:
                # kill() joins the loop thread — it must run elsewhere.
                threading.Thread(target=self.kill, name="gentun-broker-crash",
                                 daemon=True).start()
                return

    def _run_loop(self) -> None:
        loop = asyncio.new_event_loop()
        self._loop = loop
        asyncio.set_event_loop(loop)
        loop.run_until_complete(self._serve())
        try:
            loop.run_forever()
        finally:
            loop.close()

    async def _serve(self) -> None:
        # Reader limit must cover a full protocol frame: the default 64 KiB
        # StreamReader limit would kill legitimate (if large) worker frames
        # with a LimitOverrunError instead of the clean ProtocolError path.
        self._server = await asyncio.start_server(
            self._handle_worker, self._host, self._port, limit=MAX_MESSAGE_BYTES + 2
        )
        sock = self._server.sockets[0]
        self._bound = sock.getsockname()[:2]
        self._reaper_task = asyncio.ensure_future(self._reaper())
        self._watchdog_task = asyncio.ensure_future(self._watchdog_loop())
        if self._journal is not None:
            self._journal_task = asyncio.ensure_future(self._journal_loop())
        self._started.set()
        logger.info("broker listening on %s:%d", *self._bound)

    # -- master-side API (called from any thread) --------------------------

    def submit(self, payloads: Dict[str, Dict[str, Any]],
               session: Optional[str] = None) -> None:
        """Enqueue jobs: {job_id: payload}.  Non-blocking.

        ``session`` tags the jobs with a tenant opened via
        :meth:`open_session`; ``None`` rides the implicit default session
        (the pre-session single-tenant behavior, byte-identical on the
        wire).  Naming an unknown or closed session raises
        :class:`~.sessions.UnknownSessionError` HERE, in the caller's
        thread — loud, never a silent drop — and bumps
        ``session_rejected_total{session}``.
        """
        if not self._started.is_set():
            raise RuntimeError("broker not started")
        sid = str(session) if session else DEFAULT_SESSION
        if session is not None:
            sess = self._registry.peek(sid)
            if sess is None or sess.closed:
                if sess is not None:
                    sess.rejected += len(payloads)
                _get_registry().counter("session_rejected_total", session=sid).inc(len(payloads))
                raise UnknownSessionError(
                    f"session {sid!r} is {'closed' if sess is not None else 'unknown'}; "
                    f"open_session() it before submitting")

        # Assemble each job's wire record in the CALLER's thread: the
        # byte-for-byte validation pass (an oversized payload raises where
        # the submitter can see it, instead of being swallowed by the loop
        # thread's best-effort writer) now doubles as the ONLY serialization
        # this job ever pays — dispatch and every requeue re-join these
        # cached fragments (protocol.py "Wire fast path").  The genome hash
        # moves off the loop thread with it.
        wires: Dict[str, JobWire] = {}
        memo: dict = {}  # batch-scoped: dedups the shared params object's dumps
        for job_id, payload in payloads.items():
            wires[job_id] = build_job_wire(
                job_id, payload, genome_key(payload.get("genes")),
                self._frag_cache, memo)

        self._loop.call_soon_threadsafe(
            self._enqueue_jobs, dict(payloads), sid, wires)

    def _enqueue_jobs(self, payloads: Dict[str, Dict[str, Any]], sid: str,
                      wires: Optional[Dict[str, JobWire]] = None) -> None:
        """Loop-thread enqueue: session books, quarantine gate, scheduler.

        Also the wire-client submit path (``_handle_client`` runs in the
        loop thread and calls this directly).  A session that closed
        between the caller-side check and this callback records loud
        terminal failures instead of silently dropping the jobs.
        """
        if sid == DEFAULT_SESSION:
            sess: Optional[SearchSession] = self._registry.ensure_default()
        else:
            sess = self._registry.peek(sid)
        if sess is None or sess.closed:
            _get_registry().counter("session_rejected_total", session=sid).inc(len(payloads))
            reason = f"session {sid!r} is {'closed' if sess is not None else 'unknown'}"
            if sess is not None:
                sess.rejected += len(payloads)
            if sess is not None and sess.remote:
                for job_id in payloads:
                    self._deliver_remote(sess, {"type": "fail", "session": sid,
                                                "job_id": job_id, "reason": reason})
            else:
                with self._cond:
                    for job_id in payloads:
                        self._failures[job_id] = reason
                    self._cond.notify_all()
            return
        tele = _tele.enabled()
        jrn = self._journal
        now = time.monotonic()
        quarantined: Dict[str, str] = {}
        for job_id, payload in payloads.items():
            jw = wires.get(job_id) if wires is not None else None
            if jw is None:
                # Wire-client submits arrive without records (arbitrary
                # dicts off the socket): build them here, loop thread.
                jw = build_job_wire(job_id, payload,
                                    genome_key(payload.get("genes")),
                                    self._frag_cache)
            gk = jw.gk
            if gk in sess.quarantine:
                # Poison isolation: this genome already burned its failure
                # budget in THIS session — fail instantly, never dispatch.
                sess.rejected += 1
                quarantined[job_id] = (
                    f"genome {gk} quarantined in session {sid!r} "
                    f"after repeated failures")
                continue
            if jrn is not None:
                # Journal the UNTAGGED payload: replay re-runs this very
                # tagging path, so the rebuilt wire bytes match exactly.
                jrn.record_submit(job_id, sid, gk, payload)
            if sid != DEFAULT_SESSION:
                # Tag a COPY: default-session payloads stay byte-identical
                # to the pre-session wire format, and callers keep their
                # dicts untouched either way.
                payload = dict(payload)
                payload["session"] = sid
                jw = jw.with_session(sid)
            self._payloads[job_id] = payload
            self._job_wire[job_id] = jw
            self._job_session[job_id] = sid
            self._job_genome[job_id] = gk
            self._sched.push(sid, job_id)
            sess.submitted += 1
            if sid not in self._first_submit_t:
                # TTFD anchor (telemetry/canary.py): the session's FIRST
                # submit.  One dict-membership check per job; cleared on
                # session close so a reopened id re-anchors.
                self._first_submit_t[sid] = now
            if tele:
                self._tele_enqueued[job_id] = now
        if quarantined:
            if sess.remote:
                for job_id, reason in quarantined.items():
                    self._deliver_remote(sess, {"type": "fail", "session": sid,
                                                "job_id": job_id, "reason": reason})
            else:
                with self._cond:
                    self._failures.update(quarantined)
                    self._cond.notify_all()
        if tele:
            self._update_flow_gauges()
        self._dispatch()

    def wait_any(
        self, job_ids: List[str], timeout: Optional[float] = None
    ) -> tuple[Dict[str, float], Dict[str, str]]:
        """Block until at least ONE of ``job_ids`` is terminal; no barrier.

        Returns ``(results, failures)`` — every fitness and permanent
        failure available at wake-up (so a burst of completions drains in
        one call), pruned from broker state exactly like :meth:`gather`'s.
        Both dicts empty ⇔ the timeout expired with nothing terminal.
        The caller owns retry/penalty policy; unlike :meth:`gather` this
        never raises, because the steady-state engine treats a failure as
        one completed (dead) evaluation, not a reason to stop the world.
        """
        deadline = None if timeout is None else time.monotonic() + timeout
        want = set(job_ids)
        with self._cond:
            while True:
                done_r = {j: self._results[j] for j in want if j in self._results}
                done_f = {j: self._failures[j] for j in want if j in self._failures}
                if done_r or done_f:
                    self._prune_gathered(set(done_r) | set(done_f))
                    return done_r, done_f
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return {}, {}
                self._cond.wait(timeout=min(remaining, 1.0) if remaining is not None else 1.0)

    def cancel(self, job_ids) -> None:
        """Withdraw still-open jobs (the public face of :meth:`_cancel_jobs`).

        The steady-state engine calls this for children still in flight
        when its evaluation budget is reached: their results are no longer
        wanted, and a late arrival is dropped as stale.
        """
        self._cancel_jobs(set(job_ids))

    def gather(self, job_ids: List[str], timeout: Optional[float] = None) -> Dict[str, float]:
        """Block until every job in ``job_ids`` has a fitness (the barrier).

        Raises :class:`JobFailed` if any job exhausted its attempts, and
        ``TimeoutError`` on timeout.
        """
        deadline = None if timeout is None else time.monotonic() + timeout
        want = set(job_ids)
        no_workers_since: Optional[float] = None
        with self._cond:
            while True:
                done_r = {j for j in want if j in self._results}
                done_f = {j for j in want if j in self._failures}
                open_jobs = want - done_r - done_f
                # The barrier waits for every job to reach a TERMINAL state
                # (result or permanent failure) before deciding the outcome:
                # one poisoned individual must not discard the rest of the
                # generation's finished work.
                if not open_jobs:
                    out = {j: self._results[j] for j in done_r}
                    failed = {j: self._failures[j] for j in done_f}
                    self._prune_gathered(want)
                    if failed:
                        job_id = sorted(failed)[0]
                        raise JobFailed(
                            f"{len(failed)} of {len(want)} job(s) failed permanently "
                            f"(first: {job_id}: {failed[job_id]})",
                            failures=failed,
                            partial=out,
                        )
                    return out
                # Fail fast when waiting cannot help: a permanent failure is
                # already recorded and NO worker is connected, so the open
                # jobs sit in the queue with nobody to run them.  (A busy
                # connected worker always eventually produces a result, a
                # fail, or a disconnect — all of which wake this loop.)
                # The no-workers condition must HOLD for a full heartbeat
                # window before we act on it: a worker in its reconnect
                # backoff makes self._workers transiently empty, and
                # aborting then would cancel still-runnable jobs.
                if done_f and not self._workers:
                    now = time.monotonic()
                    if no_workers_since is None:
                        no_workers_since = now
                    if now - no_workers_since >= self._heartbeat_timeout:
                        out = {j: self._results[j] for j in done_r}
                        failed = {j: self._failures[j] for j in done_f}
                        self._prune_gathered(want)
                        self._cancel_jobs(open_jobs)
                        raise JobFailed(
                            f"{len(done_f)} job(s) failed permanently with no workers "
                            f"connected for {self._heartbeat_timeout:.0f}s; cancelled "
                            f"{len(open_jobs)} undispatchable job(s)",
                            failures=failed,
                            partial=out,
                        )
                else:
                    no_workers_since = None
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    out = {j: self._results[j] for j in done_r}
                    failed = {j: self._failures[j] for j in done_f}
                    # Cancel + prune so timed-out generations leave no state
                    # behind (late results are then dropped as stale) and a
                    # resubmit starts with fresh attempt counts.
                    self._prune_gathered(want)
                    self._cancel_jobs(open_jobs)
                    missing = sorted(open_jobs)
                    if failed:
                        raise JobFailed(
                            f"barrier timed out with {len(failed)} permanent failure(s) "
                            f"and {len(missing)} unfinished job(s)",
                            failures=failed,
                            partial=out,
                        )
                    raise GatherTimeout(
                        f"{len(missing)} job(s) unfinished: {missing[:5]}...",
                        partial=out,
                    )
                # Poll at ≥1 Hz even under a long finite deadline: the
                # no-workers fail-fast above re-evaluates on wake-ups only,
                # and with zero workers connected nothing else notifies.
                self._cond.wait(timeout=min(remaining, 1.0) if remaining is not None else 1.0)

    def _prune_gathered(self, want: Set[str]) -> None:
        """Drop all master-side state for a gathered job set (holds _cond).

        Keeps the master O(one generation), not O(whole search), and gives a
        post-failure resubmit fresh attempt counts.  Late duplicates are
        dropped by the _payloads membership check, so pruning cannot
        resurrect a job.
        """
        for j in want:
            self._results.pop(j, None)
            self._failures.pop(j, None)
            self._fail_counts.pop(j, None)

    def _cancel_jobs(self, job_ids: Set[str]) -> None:
        """Withdraw still-open jobs (loop-thread async; safe from any thread).

        Removing the payload is the single source of truth: dispatch skips
        pending ids without payloads, and any result that still arrives is
        dropped as stale."""
        ids = set(job_ids)
        if not ids or self._loop is None:
            return
        self._loop.call_soon_threadsafe(self._cancel_ids, ids)

    def _cancel_ids(self, ids: Set[str]) -> None:
        """Loop-thread cancel body (also the close_session sweep)."""
        ops = _health.enabled()
        if self._journal is not None:
            withdrawn = sorted(j for j in ids if j in self._payloads)
            if withdrawn:
                self._journal.record_cancel(withdrawn)
        for j in ids:
            self._payloads.pop(j, None)
            self._job_wire.pop(j, None)
            self._job_session.pop(j, None)
            self._job_genome.pop(j, None)
            self._crash_counts.pop(j, None)
            self._tele_enqueued.pop(j, None)
            self._tele_dispatched.pop(j, None)
            if ops:
                self._watchdog.job_removed(j)
        # Drain cancelled ids from the scheduler now: with no worker
        # connected nothing else pops the queues, and a retry loop would
        # grow them by one generation per attempt.
        self._sched.remove(ids)
        if self._packer is not None:
            self._packer.remove(ids)
        for w in self._workers.values():
            # Restore the credit _dispatch deducted for cancelled jobs,
            # so the worker's next batch isn't shrunk for one cycle.
            cancelled_here = len(w.in_flight & ids)
            w.in_flight -= ids
            w.credit = min(w.window, w.credit + cancelled_here)
        # Late sweep: a result that was mid-delivery when gather pruned
        # (past the payload check, blocked on _cond) lands in _results
        # BEFORE this callback runs — handler and callbacks share the
        # loop thread, and call_soon callbacks queue behind the handler.
        # Sweeping here therefore removes any such orphan for good.
        with self._cond:
            for j in ids:
                self._results.pop(j, None)
                self._failures.pop(j, None)
                self._fail_counts.pop(j, None)
        if _tele.enabled():
            self._update_flow_gauges()

    def evaluate(self, payloads: Dict[str, Dict[str, Any]], timeout: Optional[float] = None) -> Dict[str, float]:
        """submit + gather in one call."""
        self.submit(payloads)
        return self.gather(list(payloads), timeout=timeout)

    # -- session API (multi-tenant; sessions.py) ---------------------------

    def open_session(self, session_id: Optional[str] = None, weight: float = 1.0,
                     max_in_flight: Optional[int] = None,
                     tag: Optional[str] = None) -> str:
        """Open (or re-attach to) a search session and return its id.

        ``weight`` sets the tenant's fair-share priority (a weight-2
        session gets 2× the dispatch share of a weight-1 neighbor while
        both are backlogged); ``max_in_flight`` caps how many of its jobs
        may be dispatched at once regardless of share.  ``tag="canary"``
        marks a probe session the broker keeps out of tenant-facing SLI
        series (tags are not journaled — probe sessions reopen fresh after
        a restart).  Safe from any thread; idempotent for an open id.
        """
        sess = self._registry.open(session_id, weight=weight,
                                   max_in_flight=max_in_flight, tag=tag)
        if self._journal is not None:
            jrn, loop = self._journal, self._loop

            def _rec(s=sess):
                jrn.record_session_open(s.session_id, s.weight,
                                        s.max_in_flight, s.remote)

            # Journal appends belong to the loop thread; before the loop
            # exists (pre-start adoption) the caller IS the only thread.
            if loop is not None and self._started.is_set():
                loop.call_soon_threadsafe(_rec)
            else:
                _rec()
        return sess.session_id

    def close_session(self, session_id: str) -> None:
        """Close a session: no new submits, its queued jobs are withdrawn
        and its capacity share flows back to the remaining tenants.
        Idempotent; unknown ids are a no-op (close-after-close races are
        normal during teardown)."""
        sid = str(session_id)
        sess = self._registry.close(sid)
        if sess is None or self._loop is None or not self._started.is_set():
            return

        def _do():
            if self._journal is not None:
                self._journal.record_session_close(sid)
            self._first_submit_t.pop(sid, None)
            self._first_dispatch_t.pop(sid, None)
            ids = {j for j, s in self._job_session.items() if s == sid}
            if ids:
                self._cancel_ids(ids)

        self._loop.call_soon_threadsafe(_do)

    def session_stats(self) -> Dict[str, Dict[str, Any]]:
        """Per-session book snapshot (submitted/completed/failed/rejected/
        requeued/quarantined, queue depth, in-flight).  Snapshot read —
        safe from any thread."""
        inflight = self._inflight_by_session()
        return {
            s.session_id: s.snapshot(
                in_flight=inflight.get(s.session_id, 0),
                queued=self._sched.session_depth(s.session_id))
            for s in self._registry.list()
        }

    def session_ttfd(self, session_id: Optional[str] = None) -> Optional[float]:
        """Time-to-first-dispatch for this session: seconds between its
        FIRST submit and the FIRST of its jobs handed to a worker, or
        None until both have happened.  The canary plane's
        ``canary_ttfd_seconds`` SLI — the user-visible "how long before
        the fleet started my work" signal that queue depth alone can't
        give.  Snapshot read; monotonic stamps share one clock domain
        (this process), so the difference is exact."""
        sid = str(session_id) if session_id else DEFAULT_SESSION
        t0 = self._first_submit_t.get(sid)
        t1 = self._first_dispatch_t.get(sid)
        if t0 is None or t1 is None:
            return None
        return max(0.0, t1 - t0)

    def session_capacity(self, session_id: Optional[str] = None) -> int:
        """This session's share of :meth:`fleet_capacity`.

        With ≤1 open session (or an unknown id — old single-tenant
        callers) this IS the full fleet capacity.  With concurrent
        tenants it is the weighted share ``total × w/W`` (min 1 while the
        fleet is non-empty, so a light tenant always makes progress),
        clamped by the session's ``max_in_flight`` quota.  The engines'
        in-flight targets read this instead of the raw fleet sum, so N
        searches sharing a fleet size themselves to their shares.
        """
        total = self.fleet_capacity()
        sid = str(session_id) if session_id else DEFAULT_SESSION
        open_s = self._registry.open_sessions()
        mine = next((s for s in open_s if s.session_id == sid), None)
        if mine is None or len(open_s) <= 1:
            cap = total
        elif total <= 0:
            cap = 0
        else:
            weight_sum = sum(s.weight for s in open_s)
            cap = max(1, round(total * mine.weight / weight_sum))
        if mine is not None and mine.max_in_flight is not None:
            cap = min(cap, mine.max_in_flight)
        return cap

    def session_prefetch(self, session_id: Optional[str] = None) -> int:
        """This session's share of :meth:`fleet_prefetch`, proportional
        like :meth:`session_capacity` and clamped so share + prefetch
        never exceeds the session's ``max_in_flight`` quota."""
        total = self.fleet_prefetch()
        sid = str(session_id) if session_id else DEFAULT_SESSION
        open_s = self._registry.open_sessions()
        mine = next((s for s in open_s if s.session_id == sid), None)
        if mine is None or len(open_s) <= 1:
            pre = total
        else:
            weight_sum = sum(s.weight for s in open_s)
            pre = int(total * mine.weight / weight_sum)
        if mine is not None and mine.max_in_flight is not None:
            pre = max(0, min(pre, mine.max_in_flight - self.session_capacity(sid)))
        return pre

    def _admission_check(self, sid: str,
                         cost: float = 1.0) -> Optional[tuple]:
        """Admission control for the WIRE tenant paths (loop thread).

        Returns None to admit, else ``(reason, retry_after_s)`` — the
        429-style verdict ``_handle_client`` turns into a structured
        ``error {code:"admission"}`` frame.  Two independent gates:

        - **saturation** (``admission_queue_factor``): while the
          undispatched backlog exceeds ``factor × live capacity``, taking
          more work only grows queue wait — ``retry_after_s`` estimates
          the excess backlog's drain time at current capacity.
        - **token bucket** (``admission_rate``/``admission_burst``): a
          per-tenant refill-on-read bucket; ``retry_after_s`` is the exact
          time until the needed tokens exist.

        In-process submits bypass this entirely: a master throttling
        itself would deadlock its own gather."""
        f = self._admission_queue_factor
        if f is not None:
            cap = max(1, self.fleet_capacity())
            depth = self._sched.depth()
            if depth + cost > f * cap:
                excess = depth + cost - f * cap
                return "saturated", max(0.1, round(excess / cap, 3))
        rate = self._admission_rate
        if rate is not None and rate > 0:
            burst = (self._admission_burst if self._admission_burst is not None
                     else max(1.0, rate))
            now = time.monotonic()
            tokens, last = self._admission_buckets.get(sid, (burst, now))
            tokens = min(burst, tokens + (now - last) * rate)
            # Debt-based bucket: a batch costing more than the burst is
            # admitted once the bucket is FULL and drives it negative, so
            # later requests wait out the repayment — never a retry_after_s
            # after which the same request would still be rejected.
            need = min(cost, burst)
            if tokens < need:
                self._admission_buckets[sid] = (tokens, now)
                return "rate_limited", max(0.05, round((need - tokens) / rate, 3))
            self._admission_buckets[sid] = (tokens - cost, now)
        return None

    def _inflight_by_session(self) -> Dict[str, int]:
        """Dispatched-unacked job count per session, recomputed from the
        worker table (no drift-prone counters).  Loop-thread exact; from
        other threads a snapshot read with one retry against a mid-copy
        resize, like every other fleet snapshot."""
        counts: Dict[str, int] = {}
        for w in list(self._workers.values()):
            try:
                held = list(w.in_flight)
            except RuntimeError:  # pragma: no cover - resized mid-copy
                held = list(w.in_flight)
            for job_id in held:
                sid = self._job_session.get(job_id, DEFAULT_SESSION)
                counts[sid] = counts.get(sid, 0) + 1
        return counts

    def _deliver_remote(self, sess: SearchSession, frame: Dict[str, Any]) -> bool:
        """Forward a result/fail frame to a wire tenant (loop thread).

        Detached (or broken) owners get the frame parked in the session's
        bounded ``undelivered`` queue, flushed on re-attach.  Returns True
        iff the frame was written to a live owner (False ⇔ parked — the
        journal's ``pk`` flag, so replay re-parks undelivered results)."""
        owner = sess.owner
        if owner is not None:
            try:
                data = encode(frame)
                owner.write(data)
            except Exception:  # connection died; reader cleanup will detach
                sess.owner = None
            else:
                self._note_wire(str(frame.get("type")), len(data))
                return True
        sess.undelivered.append(frame)
        return False

    def fleet_capacity(self) -> int:
        """Total job slots advertised by the LIVE fleet (0 when none).

        The asynchronous engine's default in-flight target: capacity-C
        fleet ⇒ keep C evaluations in flight.  Computed from current
        membership on every call — a worker that disconnects or drains
        leaves the sum immediately, and a late joiner enters it the moment
        its hello is accepted, so elastic fleets resize the engine's
        target without restarts.  Snapshot read — safe from any thread.
        """
        return sum(w.capacity for w in list(self._workers.values())
                   if not w.draining)

    def fleet_prefetch(self) -> int:
        """Total prefetch slots advertised by the LIVE fleet (0 when
        none, and 0 for a fleet of pre-pipelining workers).

        The asynchronous engine adds this to :meth:`fleet_capacity` for its
        default in-flight target: breeding ahead to ``capacity + prefetch``
        is what keeps every worker's local ready-queue non-empty, so a
        finished window starts the next one without waiting out a
        results→breed→dispatch round trip.  Draining workers are excluded
        like disconnected ones.  Snapshot read — safe from any thread.
        """
        return sum(w.prefetch_depth for w in list(self._workers.values())
                   if not w.draining)

    def fleet_members(self) -> int:
        """Number of connected workers, draining included (they still hold
        a live connection until their in-flight results land).  Snapshot
        read — safe from any thread."""
        return len(self._workers)

    def fleet_preemptible(self) -> int:
        """Number of LIVE (non-draining) workers advertising preemptible
        capacity.  The autoscaler's churn gauge and the placement plane's
        existence check share this read.  Snapshot read — safe from any
        thread."""
        return sum(1 for w in list(self._workers.values())
                   if w.preemptible and not w.draining)

    def fleet_mesh_pop(self) -> int:
        """Largest pop-axis size advertised by the LIVE fleet (1 when no
        worker advertised a mesh).

        The master-side half of mesh-aware dispatch: a host-level mesh
        worker pads every evaluation window up to its pop-axis multiple,
        so batch sizing that rounds to this multiple (speculative fill,
        ``DistributedPopulation._fill_target``) turns would-be padding
        waste into paid-for work.  Max — not LCM — across a heterogeneous
        fleet: aligning to the widest mesh keeps the biggest worker
        waste-free and costs the narrow ones nothing (their multiple
        divides the bucket shapes anyway on power-of-two hosts).
        Snapshot read — safe from any thread.
        """
        pops = [int((w.mesh or {}).get("pop", 1))
                for w in list(self._workers.values()) if not w.draining]
        return max([1] + [p for p in pops if p > 0])

    def fleet_chips(self) -> int:
        """Total accelerator chips advertised by the connected workers (≥1).

        Each worker's ``hello`` carries its ``n_chips`` (global device count
        for a multi-host worker, 1 for non-jax species), so the master can
        log the TRUE individuals/hour/chip for exactly the deployment the
        metric was designed for.  Snapshot read — safe from any thread.
        """
        return max(1, sum(w.n_chips for w in list(self._workers.values())))

    def reset_chips_seen(self) -> None:
        """Start a fresh per-sweep chip-count observation window."""
        with self._cond:
            self._chips_seen = 0

    def chips_seen(self) -> int:
        """The sweep's per-chip denominator (≥1): max of the CURRENT fleet
        chip total and any total observed at a result arrival since the last
        :meth:`reset_chips_seen`.  Counts both a worker that delivered its
        last result and disconnected before the end-of-sweep snapshot, and a
        late-joining worker that hasn't delivered yet."""
        with self._cond:
            return max(self._chips_seen, self.fleet_chips())

    def outstanding(self) -> Dict[str, int]:
        """Sizes of every master-side job-state structure; all zero ⇔ the
        broker is quiescent (no open jobs, no undelivered results, no
        attempt counts).  The chaos suite asserts this after every final
        gather: at-least-once redelivery + dedup must leave ZERO state
        behind whatever faults fired mid-search.  Snapshot read (len only),
        safe from any thread.
        """
        with self._cond:
            results, failures = len(self._results), len(self._failures)
        return {
            "payloads": len(self._payloads),
            "pending": self._sched.depth(),
            "fail_counts": len(self._fail_counts),
            "results": results,
            "failures": failures,
            # Session tenancy maps share the _payloads lifecycle: nonzero
            # after a final gather means a pop site was missed.
            "job_sessions": len(self._job_session),
            "crash_counts": len(self._crash_counts),
            # Wire records share it too (encode-once fast path): a leak
            # here would pin payload bytes past job completion.
            "job_wires": len(self._job_wire),
            # Pack-held jobs are neither queued nor in flight; the linger
            # deadline bounds how long one may sit here, so at quiescence
            # this too must be zero.
            "packed_held": self._packer.held if self._packer is not None else 0,
        }

    @staticmethod
    def new_job_id() -> str:
        return uuid.uuid4().hex

    @staticmethod
    def _parse_prefetch(hello: Dict[str, Any], capacity: int) -> int:
        """The worker's advertised ``prefetch_depth``, validated and capped.

        Missing (old worker) or malformed values degrade to 0 — the
        pre-pipelining credit flow — never to a dropped connection.  The
        cap (4 × capacity) bounds how much of the queue one worker can
        hoard: prefetch hides one results→breed→dispatch round trip, so
        depth beyond a few windows only starves the rest of the fleet.
        """
        try:
            depth = int(hello.get("prefetch_depth", 0))
        except (TypeError, ValueError):
            return 0
        return max(0, min(depth, 4 * capacity))

    @staticmethod
    def _parse_homes(hello: Dict[str, Any]) -> int:
        """The worker's OPTIONAL multi-home advertisement (protocol.py
        "Multi-home field"): how many broker shards it joined.  Missing
        (every single-homed worker — the field is only sent when >1) or
        malformed degrades to 1, never a dropped connection."""
        try:
            homes = int(hello.get("homes", 1))
        except (TypeError, ValueError):
            return 1
        return max(1, homes)

    @staticmethod
    def _parse_mesh(msg: Dict[str, Any]) -> Optional[Dict[str, int]]:
        """The worker's OPTIONAL host-mesh advertisement, validated.

        Expects ``{"pop": P, "data": D, "devices": N}`` with positive
        ints (``devices`` may be 0 = unknown).  Advisory observability
        data — malformed values degrade to None (no mesh recorded), never
        drop the worker, same convention as ``n_chips``.
        """
        mesh = msg.get("mesh")
        if not isinstance(mesh, dict):
            return None
        try:
            pop = int(mesh.get("pop", 1))
            data = int(mesh.get("data", 1))
            devices = int(mesh.get("devices", 0))
        except (TypeError, ValueError):
            return None
        if pop < 1 or data < 1 or devices < 0:
            return None
        return {"pop": pop, "data": data, "devices": devices}

    # -- loop-thread internals --------------------------------------------

    def _update_flow_gauges(self) -> None:
        """Refresh the tail-regime flow gauges (loop thread, telemetry on).

        ``jobs_in_flight`` (jobs handed to workers, unacked) is the gauge
        the async-mode acceptance test samples: a capacity-C fleet under
        the steady-state engine must sustain it at ≥ C.  ``queue_depth``
        is the undispatched backlog; ``broker_queue_depth`` is kept as an
        alias for pre-existing dashboards.
        """
        reg = _get_registry()
        reg.gauge("jobs_in_flight").set(
            sum(len(w.in_flight) for w in self._workers.values()))
        depth = self._sched.depth()
        reg.gauge("queue_depth").set(depth)
        reg.gauge("broker_queue_depth").set(depth)
        # Per-tenant twins (labels): only emitted once a session table
        # exists, so single-tenant dashboards see no new series.
        sessions = self._registry.list()
        if sessions:
            inflight = self._inflight_by_session()
            for s in sessions:
                if s.tag == "canary":
                    # Probe sessions are invisible to tenant-facing SLI
                    # series: no per-session flow gauges (the canary plane
                    # publishes its own canary_* instruments instead).
                    continue
                sid = s.session_id
                reg.gauge("session_in_flight", session=sid).set(inflight.get(sid, 0))
                reg.gauge("session_queue_depth", session=sid).set(
                    self._sched.session_depth(sid))
        # Dispatched jobs beyond the workers' evaluation capacity are (from
        # the broker's vantage) sitting in worker-local ready-queues — the
        # double-buffering inventory.  Persistently 0 with prefetching
        # workers connected means the ENGINE is the bottleneck (not breeding
        # ahead fast enough); pinned at fleet_prefetch() means workers never
        # drain their queues (compute-bound — prefetch is pure win).
        reg.gauge("prefetch_queue_depth").set(
            sum(max(0, len(w.in_flight) - w.capacity)
                for w in self._workers.values()))

    def job_prefers_preemptible(self, job_id: str) -> bool:
        """Placement class of one open job: True ⇔ preemptible-preferred.

        Exactly the ASHA economics (DISTRIBUTED.md "Autoscaling &
        preemptible capacity"): a rung-0 small-class probe is cheap and
        fully requeue-able, so losing its worker mid-train costs one cheap
        retrain — route it to capacity that may vanish.  A high-rung
        promotion (rung ≥ 1) or a big/micro-class genome embodies real
        chip-seconds (or an axis-split program that must not thrash), so
        it pins to stable members.  Size class is judged worker-
        independently (``n_devices=1``) — a placement class must not
        change with whichever worker happens to be asking.  Pure dict
        reads plus the memoized :func:`job_size_class`; the per-decision
        cost is gated ≤ 2% of a dispatch by scripts/broker_throughput.py
        ``run_placement_gate``.
        """
        pl = self._payloads.get(job_id)
        if pl is None:  # defensive: racing a cancel — class is moot
            return False
        if (pl.get("fidelity") or {}).get("rung", 0):
            return False
        return job_size_class(pl.get("additional_parameters")) == SIZE_SMALL

    def _placeable_for(self, worker_preemptible: bool):
        """The ``pop_next`` placement filter for one worker's class."""
        if worker_preemptible:
            return self.job_prefers_preemptible
        return lambda job_id: not self.job_prefers_preemptible(job_id)

    def _dispatch(self) -> None:
        """Hand pending jobs to workers with spare credit (competing consumers).

        Everything a worker's credit allows goes out as ONE ``jobs`` frame —
        credit-based prefetch.  The worker never guesses (with a read
        timeout) whether more of its batch is still in flight: a capacity-8
        worker gets its 8 jobs in a single frame whatever the DCN latency.

        Job ORDER comes from the fair-share scheduler: weighted deficit
        round-robin across sessions, with per-session ``max_in_flight``
        quotas enforced here (a quota-full session's jobs stay queued and
        its turn passes to the others — work conservation).

        In a mixed stable+preemptible fleet the pass is also placement-
        aware: each worker only takes jobs of its class (rung-0 small
        probes → preemptible, everything else → stable), and the pass
        repeats while it makes progress so a head-of-queue job unblocked
        mid-pass still reaches a worker visited earlier.

        With cross-session window packing on (``pack_windows=True``) the
        whole pass is delegated to :meth:`_dispatch_packed` — the branch
        sits BEFORE the empty-queue fast return because the packer may
        hold linger-due jobs even when the scheduler is drained.
        """
        if self._packer is not None:
            self._dispatch_packed()
            return
        if self._sched.depth() == 0:
            return
        tele = _tele.enabled()
        ops = _health.enabled()
        jrn = self._journal
        # Quota eligibility is computed once and tracked incrementally
        # through this pass; the next _dispatch recomputes from the worker
        # table, so the count can never drift.
        inflight = self._inflight_by_session()
        sessions = self._registry.list()
        quotas = {s.session_id: s.max_in_flight
                  for s in sessions if s.max_in_flight is not None}
        # Canary probe sessions stay out of tenant-facing SLI series
        # (per-session queue_wait_s below, flow gauges in
        # _update_flow_gauges); built once per pass from the same registry
        # snapshot the quota table already walks.
        canary_sids = {s.session_id for s in sessions if s.tag == "canary"}

        def eligible(sid: str) -> bool:
            quota = quotas.get(sid)
            return quota is None or inflight.get(sid, 0) < quota

        exhausted = False  # no session has a dispatchable job left
        workers = list(self._workers.values())
        # Placement-aware dispatch (protocol.py "Preemptible-capacity
        # field") activates only for a MIXED live fleet: with both classes
        # present, rung-0 small-class probes route to preemptible members
        # and everything else pins to stable.  A homogeneous fleet takes
        # every job wherever there is credit — the "fallback to any
        # capacity when a class has none" rule, and what keeps the
        # stable-only path byte-identical to the pre-placement broker.
        placement_on = (
            any(w.preemptible for w in workers if not w.draining)
            and any(not w.preemptible for w in workers if not w.draining))
        while True:
            progress = False
            for w in workers:
                if exhausted:
                    break
                if w.draining:  # orderly exit in progress: never hand it work
                    continue
                placeable = (self._placeable_for(w.preemptible)
                             if placement_on else None)
                batch: List[tuple] = []  # (job_id, JobWire)
                batch_bytes = 0
                use_jobs2 = "jobs2" in w.caps
                # Keep each frame well under the protocol cap: submit() bounds
                # single jobs, but a large-capacity worker's combined batch could
                # exceed it — flush into multiple `jobs` frames when needed (the
                # client reads frames one per consume-loop iteration).
                soft_cap = MAX_MESSAGE_BYTES // 2
                while w.credit > 0:
                    nxt = self._sched.pop_next(
                        eligible, lambda j: j in self._payloads, placeable)
                    if nxt is None:
                        # Nothing queued / every session quota-full — or,
                        # with placement on, every queue head pinned to the
                        # OTHER class.  Only the class-blind read proves the
                        # whole pass is done.
                        if placeable is None:
                            exhausted = True
                        break
                    progress = True
                    sid, job_id = nxt
                    w.credit -= 1
                    w.in_flight.add(job_id)
                    inflight[sid] = inflight.get(sid, 0) + 1
                    if sid not in self._first_dispatch_t:
                        # TTFD landing stamp: this session's first handoff.
                        self._first_dispatch_t[sid] = time.monotonic()
                    if jrn is not None:
                        # THE hot-path journal record: a pre-formatted string
                        # append; fsync is the journal task's, never ours.
                        jrn.record_dispatch(job_id)
                    # Size-class dispatch accounting (big-genome regime,
                    # docs/OBSERVABILITY.md): one labeled counter bump per
                    # handoff.  job_size_class is jax-free integer math on the
                    # payload config — its cost share of a dispatch is gated
                    # at <= 2% by scripts/broker_throughput.py.
                    _get_registry().counter(
                        "jobs_dispatched_total",
                        genome_size_class=job_size_class(
                            self._payloads[job_id].get("additional_parameters"),
                            int((w.mesh or {}).get("devices") or 1)),
                    ).inc()
                    if tele:
                        # queue_wait: time from (re)enqueue to handoff.  The
                        # stamp stays in place — _on_result uses it for the
                        # end-to-end job span.
                        attrs = {"worker": w.worker_id}
                        if sid != DEFAULT_SESSION:
                            attrs["session"] = sid
                        t_enq = self._tele_enqueued.get(job_id)
                        if t_enq is not None:
                            wait = time.monotonic() - t_enq
                            _tele.record_span(
                                "queue_wait", t_enq, wait,
                                trace=self._payloads[job_id].get("trace"),
                                attrs=attrs,
                            )
                            # The registry twin of the span: a per-job wait
                            # histogram dashboards can read without span
                            # post-processing (tail-regime pressure signal).
                            # Session-labeled only for tenant jobs, so the
                            # single-tenant series name never changes; canary
                            # probes are excluded entirely (their waits are
                            # the canary plane's own SLIs, never a tenant's).
                            if sid in canary_sids:
                                pass
                            elif sid != DEFAULT_SESSION:
                                _get_registry().histogram(
                                    "queue_wait_s", session=sid).observe(wait)
                            else:
                                _get_registry().histogram("queue_wait_s").observe(wait)
                        # dispatch_rtt_s starts here: handoff to the worker.
                        self._tele_dispatched[job_id] = time.monotonic()
                    if _lineage.enabled():
                        pl = self._payloads[job_id]
                        _lineage.record(
                            "dispatched", self._job_genome.get(job_id),
                            job=job_id, worker=w.worker_id,
                            rung=(pl.get("fidelity") or {}).get("rung", 0),
                            session=sid if sid != DEFAULT_SESSION else None)
                    if ops:
                        # Same clock start as dispatch_rtt_s: the watchdog
                        # measures handoff → now against its rolling threshold.
                        self._watchdog.job_started(
                            job_id, w.worker_id,
                            session=sid if sid != DEFAULT_SESSION else None)
                    # Encode-once fast path: the entry bytes were assembled at
                    # enqueue (or on a previous dispatch of this very job) and
                    # size the split AND join the frame — a requeued job costs
                    # zero serialization here.
                    jw = self._job_wire.get(job_id)
                    if jw is None:  # defensive: open job without a record
                        jw = build_job_wire(job_id, self._payloads[job_id],
                                            self._job_genome.get(job_id)
                                            or genome_key(self._payloads[job_id].get("genes")),
                                            self._frag_cache)
                        self._job_wire[job_id] = jw
                    entry_bytes = len(jw.v1)
                    if batch and batch_bytes + entry_bytes > soft_cap:
                        self._flush_batch(w, batch, use_jobs2)
                        batch, batch_bytes = [], 0
                    batch.append((job_id, jw))
                    batch_bytes += entry_bytes
                if batch:
                    self._flush_batch(w, batch, use_jobs2)
            # One pass is the whole story for a class-blind fleet.  A mixed
            # fleet repeats while the pass made progress: a preemptible pop
            # can expose a stable-pinned job mid-pass (and vice versa) for a
            # worker the iteration already visited.
            if not placement_on or exhausted or not progress:
                break
        if tele:
            self._update_flow_gauges()

    # -- cross-session window packing (ISSUE 19, packing.py) ---------------

    def _pack_key(self, job_id: str) -> tuple:
        """The compile-compatibility key for one open job:
        ``(pack_envelope(env), job_size_class)`` — serialized static
        config + fidelity bytes, plus the genome size class.  Equal keys
        ⇒ the jobs compile to the same program and may share a window
        (purity argument: DISTRIBUTED.md "Cross-session window packing").
        """
        jw = self._job_wire.get(job_id)
        if jw is None:  # defensive: open job without a wire record
            jw = build_job_wire(job_id, self._payloads[job_id],
                                self._job_genome.get(job_id)
                                or genome_key(self._payloads[job_id].get("genes")),
                                self._frag_cache)
            self._job_wire[job_id] = jw
        sclass = job_size_class(
            self._payloads[job_id].get("additional_parameters"))
        return (pack_envelope(jw.env), sclass)

    def _pack_step(self, w: _Worker, size_class: str) -> int:
        """The packed-window target size for (worker, size class): the
        worker's capacity, mesh-aligned EXACTLY like the client's
        ``_chunk_jobs`` (round down to a multiple of the pop axis, floor
        at one row) so a packed frame is one evaluation chunk — never
        re-split worker-side.  Big/micro genomes never pack: the chunker
        makes them singleton windows, so the broker does too."""
        if size_class != SIZE_SMALL:
            return 1
        step = max(1, int(w.capacity))
        pop = int((w.mesh or {}).get("pop") or 1)
        if pop > 1 and step % pop:
            step = max(pop, step - step % pop)
        return step

    def _dispatch_packed(self) -> None:
        """The pack-mode dispatch pass: FILL then FLUSH then re-arm.

        FILL drains the fair-share scheduler into the packer's
        compatibility groups — through ``pop_next``, so the weighted DRR
        deficit is charged job-by-job in exactly the order an unpacked
        dispatch would have charged it, and session quotas count
        packer-held jobs as in flight.  Fill is bounded by the fleet's
        spare credit: with no worker able to take a window there is no
        reason to pull work out of the (observable, fair) queue.

        FLUSH hands each worker whole windows: a group ships when it can
        fill the worker's mesh-aligned capacity (``_pack_step``) or when
        its oldest job has lingered past the deadline — a lone
        latency-sensitive job never waits for fill beyond
        ``pack_linger_ms``.  In a mixed stable+preemptible fleet a group
        only lands on its placement class (rung-0 small probes →
        preemptible), same rule as the unpacked pass.

        Whatever still waits on its linger deadline re-arms the loop
        timer (:meth:`_arm_pack_timer`); a due-but-creditless group
        flushes on the next ready-triggered dispatch instead.
        """
        packer = self._packer
        now = time.monotonic()
        workers = [w for w in self._workers.values() if not w.draining]
        # -- fill ----------------------------------------------------------
        if self._sched.depth():
            spare = sum(w.credit for w in workers)
            inflight = self._inflight_by_session()
            for sid, n in packer.held_by_session().items():
                inflight[sid] = inflight.get(sid, 0) + n
            quotas = {s.session_id: s.max_in_flight
                      for s in self._registry.list()
                      if s.max_in_flight is not None}

            def eligible(sid: str) -> bool:
                quota = quotas.get(sid)
                return quota is None or inflight.get(sid, 0) < quota

            while packer.held < spare:
                nxt = self._sched.pop_next(
                    eligible, lambda j: j in self._payloads, None)
                if nxt is None:
                    break
                sid, job_id = nxt
                inflight[sid] = inflight.get(sid, 0) + 1
                key = self._pack_key(job_id)
                packer.add(sid, job_id, key, key[1],
                           self.job_prefers_preemptible(job_id), now)
        # -- flush ---------------------------------------------------------
        placement_on = (any(w.preemptible for w in workers)
                        and any(not w.preemptible for w in workers))
        while True:
            progress = False
            for w in workers:
                if w.credit <= 0:
                    continue
                for g in packer.groups():
                    if w.credit <= 0:
                        break
                    if not g.jobs:
                        continue
                    if placement_on and g.prefers_preemptible != w.preemptible:
                        continue
                    step = self._pack_step(w, g.size_class)
                    due = (now - g.arrivals[0]) >= packer.linger_s
                    if len(g.jobs) < step and not due:
                        continue
                    window = packer.take(g, min(len(g.jobs), step, w.credit),
                                         step, now)
                    if window:
                        self._send_packed_window(w, window, g.key[0])
                        progress = True
            if not progress:
                break
        self._arm_pack_timer(now)
        if _tele.enabled():
            self._update_flow_gauges()

    def _send_packed_window(self, w: _Worker, window: List[tuple],
                            pack_env: tuple) -> None:
        """Per-job dispatch bookkeeping + ONE packed frame.

        The per-job half mirrors the unpacked ``_dispatch`` body line for
        line — journal dispatch record, size-class counter, queue-wait
        span + histogram, dispatch-RTT stamp, lineage, watchdog — so every
        demux path downstream (result, requeue, quarantine, replay) keeps
        its session attribution untouched.  The frame half ships the whole
        window as one ``packed: true`` frame: ``jobs2`` workers get the
        compile envelope hoisted with per-job session/trace in the entries
        (``packed_entry2``), v1 workers get the session-tagged v1 entries.
        """
        tele = _tele.enabled()
        ops = _health.enabled()
        jrn = self._journal
        packer = self._packer
        reg = _get_registry()
        canary_sids = {s.session_id for s in self._registry.list()
                       if s.tag == "canary"}
        batch: List[JobWire] = []
        for sid, job_id in window:
            w.credit -= 1
            w.in_flight.add(job_id)
            if sid not in self._first_dispatch_t:
                self._first_dispatch_t[sid] = time.monotonic()
            if jrn is not None:
                jrn.record_dispatch(job_id)
            reg.counter(
                "jobs_dispatched_total",
                genome_size_class=job_size_class(
                    self._payloads[job_id].get("additional_parameters"),
                    int((w.mesh or {}).get("devices") or 1)),
            ).inc()
            if sid not in canary_sids:
                reg.counter("packed_jobs_total", session=sid).inc()
            if tele:
                attrs = {"worker": w.worker_id}
                if sid != DEFAULT_SESSION:
                    attrs["session"] = sid
                t_enq = self._tele_enqueued.get(job_id)
                if t_enq is not None:
                    wait = time.monotonic() - t_enq
                    _tele.record_span(
                        "queue_wait", t_enq, wait,
                        trace=self._payloads[job_id].get("trace"),
                        attrs=attrs,
                    )
                    if sid in canary_sids:
                        pass  # canary probes never feed tenant SLI series
                    elif sid != DEFAULT_SESSION:
                        reg.histogram("queue_wait_s", session=sid).observe(wait)
                    else:
                        reg.histogram("queue_wait_s").observe(wait)
                self._tele_dispatched[job_id] = time.monotonic()
            if _lineage.enabled():
                pl = self._payloads[job_id]
                _lineage.record(
                    "dispatched", self._job_genome.get(job_id),
                    job=job_id, worker=w.worker_id,
                    rung=(pl.get("fidelity") or {}).get("rung", 0),
                    session=sid if sid != DEFAULT_SESSION else None)
            if ops:
                self._watchdog.job_started(
                    job_id, w.worker_id,
                    session=sid if sid != DEFAULT_SESSION else None)
            jw = self._job_wire.get(job_id)
            if jw is None:  # defensive: open job without a record
                jw = build_job_wire(job_id, self._payloads[job_id],
                                    self._job_genome.get(job_id)
                                    or genome_key(self._payloads[job_id].get("genes")),
                                    self._frag_cache)
                self._job_wire[job_id] = jw
            batch.append(jw)
        # Defensive oversize split at the same soft cap as _dispatch; a
        # window is at most one capacity of few-KB genomes, so in practice
        # this is always a single frame (and every part stays <= the
        # window, so the worker-side no-resplit assertion holds per frame).
        soft_cap = MAX_MESSAGE_BYTES // 2
        parts: List[List[JobWire]] = []
        cur: List[JobWire] = []
        cur_bytes = 0
        for jw in batch:
            if cur and cur_bytes + len(jw.v1) > soft_cap:
                parts.append(cur)
                cur, cur_bytes = [], 0
            cur.append(jw)
            cur_bytes += len(jw.v1)
        parts.append(cur)
        self._encode_samples += 1
        sample = (self._encode_samples & 63) == 0
        t0 = time.perf_counter() if sample else 0.0
        if "jobs2" in w.caps:
            frames = [("jobs2", jobs2_frame(
                pack_env, [packed_entry2(jw) for jw in part], packed=True))
                for part in parts]
        else:
            frames = [("jobs", jobs_frame([jw.v1 for jw in part], packed=True))
                      for part in parts]
        if sample:
            self._note_encode(time.perf_counter() - t0)
        for mtype, data in frames:
            try:
                if self._injector is not None and \
                        self._injector.broker_send(w, decode(data)):
                    continue
                w.writer.write(data)
            except Exception:  # connection already broken; reader cleans up
                logger.debug("write to worker %s failed", w.worker_id,
                             exc_info=True)
                continue
            self._note_wire(mtype, len(data))
        reg.counter("packed_windows_total").inc()
        reg.histogram("pack_fill_ratio").observe(packer.fill_ratios[-1])
        reg.histogram("pack_linger_seconds").observe(packer.lingers[-1])

    def _arm_pack_timer(self, now: float) -> None:
        """(Re)arm the loop timer for the earliest linger deadline.

        Only future deadlines get a precise timer.  A deadline already in
        the past here means the flush pass just declined the window (no
        credit / wrong placement class); the next worker `ready` triggers
        a dispatch anyway, and a linger-cadence backstop poll guarantees
        a lone held job never waits on worker timing alone.
        """
        if self._pack_timer is not None:
            self._pack_timer.cancel()
            self._pack_timer = None
        deadline = self._packer.next_deadline()
        if deadline is None or self._loop is None:
            return
        delay = deadline - now
        if delay <= 0:
            delay = max(self._packer.linger_s, 0.01)
        self._pack_timer = self._loop.call_later(delay, self._pack_timer_fire)

    def _pack_timer_fire(self) -> None:
        self._pack_timer = None
        if not self._stopping:
            self._dispatch()

    def pack_stats(self) -> Optional[Dict[str, Any]]:
        """Pack-plane snapshot (``None`` when ``pack_windows=False``):
        windows/jobs/cross-session totals, currently-held count, and
        fill-ratio + linger percentile distributions.  Also surfaced in
        ``/statusz`` under ``fleet.packing`` for gentun_top."""
        if self._packer is None:
            return None
        return self._packer.snapshot()

    def _send(self, w: _Worker, msg: Dict[str, Any]) -> None:
        try:
            if self._injector is not None and self._injector.broker_send(w, msg):
                return
            data = encode(msg)
            w.writer.write(data)
        except Exception:  # connection already broken; reader will clean up
            logger.debug("write to worker %s failed", w.worker_id, exc_info=True)
            return
        self._note_wire(str(msg.get("type")), len(data))

    def _flush_batch(self, w: _Worker, batch: List[tuple],
                     use_jobs2: bool) -> None:
        """Send one dispatch batch as pre-assembled frame bytes.

        v1 workers get a single ``jobs`` frame, byte-identical to the
        pre-fast-path ``encode({"type": "jobs", "jobs": [...]})``.  A
        ``jobs2`` worker gets one frame per distinct shared envelope — one
        frame in the common case of a homogeneous window, and never a merge
        of jobs that don't share their envelope.  Frame assembly is sampled
        1-in-64 into ``frame_encode_seconds``; with a fault injector
        installed, the typed dict the injector contracts on is recovered by
        decoding the frame (cold path only — injectors are a test harness).
        """
        # 1-in-N histogram sampling: a perf_counter pair per sampled frame,
        # a single int test otherwise (memoize-or-die, run_wire_gate).
        self._encode_samples += 1
        sample = (self._encode_samples & 63) == 0
        t0 = time.perf_counter() if sample else 0.0
        if not use_jobs2:
            frames = [("jobs", jobs_frame([jw.v1 for _, jw in batch]))]
        else:
            groups: Dict[tuple, list] = {}
            order: List[tuple] = []
            for _, jw in batch:
                g = groups.get(jw.env)
                if g is None:
                    groups[jw.env] = g = []
                    order.append(jw.env)
                g.append(jw.entry2)
            frames = [("jobs2", jobs2_frame(env, groups[env])) for env in order]
        if sample:
            self._note_encode(time.perf_counter() - t0)
        for mtype, data in frames:
            try:
                if self._injector is not None and \
                        self._injector.broker_send(w, decode(data)):
                    continue
                w.writer.write(data)
            except Exception:  # connection already broken; reader cleans up
                logger.debug("write to worker %s failed", w.worker_id,
                             exc_info=True)
                continue
            self._note_wire(mtype, len(data))

    def _note_wire(self, mtype: str, nbytes: int) -> None:
        """Bump the per-frame-type wire counters through memoized handles."""
        handles = self._wire_counters.get(mtype)
        if handles is None:
            reg = _get_registry()
            handles = (reg.counter("wire_bytes_sent_total", type=mtype),
                       reg.counter("wire_frames_sent_total", type=mtype))
            self._wire_counters[mtype] = handles
        handles[0].inc(nbytes)
        handles[1].inc()

    def _note_encode(self, seconds: float) -> None:
        if self._encode_hist is None:
            self._encode_hist = _get_registry().histogram(
                "frame_encode_seconds", side="broker")
        self._encode_hist.observe(seconds)

    def _requeue_worker_jobs(self, w: _Worker, reason: str) -> None:
        tele = _tele.enabled()
        ops = _health.enabled()
        crash_cap = self._quarantine_crash_requeues
        for job_id in sorted(w.in_flight):
            if ops:
                self._watchdog.job_removed(job_id)
            if job_id in self._payloads:
                sid = self._job_session.get(job_id, DEFAULT_SESSION)
                if crash_cap is not None and reason == "disconnect":
                    # Crash isolation (opt-in): a job whose worker keeps
                    # dying mid-evaluation is most likely KILLING them.
                    # After crash_cap redeliveries it fails terminally and
                    # its genome is quarantined in its session, so one
                    # poison genome cannot crash-loop the fleet for every
                    # tenant.  Default None = unbounded AMQP redelivery.
                    n = self._crash_counts.get(job_id, 0) + 1
                    self._crash_counts[job_id] = n
                    if n >= crash_cap:
                        logger.error(
                            "job %s crashed its worker %d time(s); failing "
                            "terminally and quarantining its genome", job_id, n)
                        self._fail_terminal(
                            job_id,
                            f"worker crashed {n} time(s) while evaluating",
                            force_quarantine=True)
                        continue
                logger.warning("requeue job %s (%s, worker %s)", job_id, reason, w.worker_id)
                if self._journal is not None:
                    self._journal.record_requeue(job_id)
                # Disconnect redelivery is unbounded, like AMQP's.  This
                # covers the worker's whole in-flight set — the jobs it was
                # evaluating AND the ones still queued-but-unstarted in its
                # local prefetch queue (the broker cannot tell them apart,
                # and at-least-once makes the distinction irrelevant).
                self._sched.push(sid, job_id)
                sess = self._registry.peek(sid)
                if sess is not None:
                    sess.requeued += 1
                if _lineage.enabled():
                    _lineage.record(
                        "requeued", self._job_genome.get(job_id),
                        job=job_id, worker=w.worker_id, reason=reason,
                        session=sid if sid != DEFAULT_SESSION else None)
                if tele:
                    # Restart the clock: queue_wait/job measure time since
                    # the LAST enqueue, not since first submission.
                    self._tele_enqueued[job_id] = time.monotonic()
                self._tele_dispatched.pop(job_id, None)
        w.in_flight.clear()
        if tele:
            self._update_flow_gauges()

    def _fail_terminal(self, job_id: str, reason: str,
                       force_quarantine: bool = False) -> None:
        """Terminal failure: close the job's state, count its genome toward
        (or force) per-session quarantine, surface the failure to the
        session's owner.  Loop thread only."""
        if self._payloads.pop(job_id, None) is None:
            return
        self._job_wire.pop(job_id, None)
        sid = self._job_session.pop(job_id, DEFAULT_SESSION)
        gk = self._job_genome.pop(job_id, None)
        self._crash_counts.pop(job_id, None)
        self._fail_counts.pop(job_id, None)
        self._tele_enqueued.pop(job_id, None)
        self._tele_dispatched.pop(job_id, None)
        if self._journal is not None:
            self._journal.record_fail(job_id, reason)
        sess = self._registry.peek(sid)
        if sess is not None:
            # Quarantine bookkeeping (poison counts, counter, telemetry
            # event, lineage entry) lives with the session's books.
            newly_quarantined = sess.record_terminal_failure(
                gk, self._registry.quarantine_after,
                force_quarantine=force_quarantine)
            if newly_quarantined and self._journal is not None and gk:
                self._journal.record_quarantine(sid, gk)
        if _tele.enabled():
            self._update_flow_gauges()
        if sess is not None and sess.remote:
            self._deliver_remote(sess, {"type": "fail", "session": sid,
                                        "job_id": job_id, "reason": reason})
        else:
            with self._cond:
                self._failures[job_id] = reason
                self._cond.notify_all()

    async def _reaper(self) -> None:
        """Declare silent workers holding jobs dead; requeue their jobs."""
        while not self._stopping:
            await asyncio.sleep(self._heartbeat_timeout / 3.0)
            now = time.monotonic()
            for w in list(self._workers.values()):
                if w.in_flight and now - w.last_seen > self._heartbeat_timeout:
                    logger.warning("worker %s missed heartbeats; dropping", w.worker_id)
                    w.writer.close()  # triggers cleanup in _handle_worker

    async def _watchdog_loop(self) -> None:
        """Beat the broker's liveness source and sweep for stragglers.

        Separate from :meth:`_reaper` because the cadences differ by an
        order of magnitude: the reaper runs at heartbeat scale (seconds to
        tens of seconds), the watchdog must flag within a fraction of its
        floor.  While the ops plane is off each pass is one bool read and
        a sleep.
        """
        while not self._stopping:
            await asyncio.sleep(self._watchdog_interval)
            if _health.enabled():
                _health.beat("broker_loop")
                self._watchdog.check()

    def _on_straggler(self, info: Dict[str, Any]) -> None:
        """Watchdog requeue hook (``straggler_requeue=True``).  May fire
        from the loop thread (watchdog sweep) or an HTTP handler thread
        (healthz-triggered check); the mutation hops to the loop thread
        either way — broker state stays single-threaded."""
        loop = self._loop
        if loop is not None:
            loop.call_soon_threadsafe(self._requeue_straggler, info)

    def _requeue_straggler(self, info: Dict[str, Any]) -> None:
        job_id = str(info.get("job_id"))
        if job_id not in self._payloads or self._sched.queued(job_id):
            return  # finished/cancelled/already requeued since flagging
        holder = next((w for w in self._workers.values() if job_id in w.in_flight), None)
        if holder is None:
            return  # the worker vanished; disconnect cleanup already requeued
        logger.warning(
            "requeue straggler job %s (worker %s, in flight %.1fs > %.1fs threshold)",
            job_id, holder.worker_id, info.get("age_s", -1.0),
            info.get("threshold_s", -1.0))
        # The stalled worker's credit stays consumed: it is not accepting
        # new work anyway, and its late result is dropped by the payload
        # membership check like any redelivery duplicate.
        holder.in_flight.discard(job_id)
        sid = self._job_session.get(job_id, DEFAULT_SESSION)
        if self._journal is not None:
            self._journal.record_requeue(job_id)
        self._sched.push(sid, job_id)
        sess = self._registry.peek(sid)
        if sess is not None:
            sess.requeued += 1
        self._watchdog.job_removed(job_id)
        self._tele_dispatched.pop(job_id, None)
        if _tele.enabled():
            self._tele_enqueued[job_id] = time.monotonic()
        labels = {"worker": holder.worker_id}
        if sid != DEFAULT_SESSION:
            labels["session"] = sid
        _get_registry().counter("stragglers_requeued_total", **labels).inc()
        _tele.record_event("straggler_requeued", {
            "job_id": job_id, "worker_id": holder.worker_id, "session": sid,
            "age_s": info.get("age_s"), "threshold_s": info.get("threshold_s"),
        })
        if _lineage.enabled():
            _lineage.record(
                "requeued", self._job_genome.get(job_id),
                job=job_id, worker=holder.worker_id, reason="straggler",
                session=sid if sid != DEFAULT_SESSION else None)
        self._dispatch()

    def _ops_status(self) -> Dict[str, Any]:
        """The ``/statusz`` "fleet" block (registered as a status
        provider).  Snapshot reads from an HTTP thread, same discipline as
        :meth:`fleet_capacity`: list() the worker table, read scalars —
        never mutate."""
        now = time.monotonic()
        workers = [{
            "worker_id": w.worker_id,
            "capacity": w.capacity,
            "prefetch_depth": w.prefetch_depth,
            "credit": w.credit,
            "jobs_in_flight": len(w.in_flight),
            "last_seen_age_s": round(now - w.last_seen, 3),
            "n_chips": w.n_chips,
            "backend": w.backend,
            "draining": w.draining,
            "preemptible": w.preemptible,
            "mesh": w.mesh,
            "wire_caps": sorted(w.caps),
            "homes": w.homes,
        } for w in list(self._workers.values())]
        return {
            "address": list(self._bound) if self._started.is_set() else None,
            "workers": workers,
            # Encode-once fragment cache (protocol.py "Wire fast path"):
            # size + hit counters for the gentun_top wire panel.
            "fragment_cache": {
                "entries": len(self._frag_cache),
                "hits": self._frag_cache.hits,
                "misses": self._frag_cache.misses,
            },
            "members": len(workers),
            "draining": sum(1 for x in workers if x["draining"]),
            "preemptible_members": self.fleet_preemptible(),
            "live_capacity": self.fleet_capacity(),
            "live_prefetch": self.fleet_prefetch(),
            "queue_depth": self._sched.depth(),
            "open_jobs": len(self._payloads),
            "jobs_in_flight": sum(x["jobs_in_flight"] for x in workers),
            "straggler_threshold_s": round(self._watchdog.threshold(), 3),
            "stragglers": self._watchdog.stragglers(),
            "straggler_requeue": self._straggler_requeue,
            # Widest advertised pop axis (1 = no mesh workers): the
            # multiple mesh-aware batch sizing aligns to.
            "mesh_pop_multiple": self.fleet_mesh_pop(),
            # Tenant table (empty until the first submit/open_session):
            # per-session books for the /statusz sessions panel.
            "sessions": self.session_stats(),
            # Crash-safety plane (ISSUE 16): journal health for the
            # gentun_top broker panel; None ⇔ journaling off.
            "journal": (self._journal.status()
                        if self._journal is not None else None),
            "epoch": self._epoch,
            "restarts": self._restarts,
            "admission": {
                "rate": self._admission_rate,
                "burst": self._admission_burst,
                "queue_factor": self._admission_queue_factor,
                "rejected_by_session": dict(self._admission_rejections),
            },
            # Cross-session window packing (ISSUE 19): None ⇔ packing off
            # (no new statusz noise for the default build).
            "packing": self.pack_stats(),
        }

    async def _handle_worker(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        wid = next(self._worker_seq)
        worker: Optional[_Worker] = None
        try:
            hello = decode(await reader.readline())
            if hello.get("type") != "hello":
                writer.write(encode({"type": "error", "reason": "expected hello"}))
                return
            # Constant-time compare: the token is a shared secret and the
            # broker may listen on a routable DCN address.  Compare as UTF-8
            # bytes — compare_digest raises TypeError on non-ASCII str.
            if self._token is not None and not hmac.compare_digest(
                str(hello.get("token") or "").encode("utf-8"),
                self._token.encode("utf-8"),
            ):
                # code=auth lets the client distinguish a deterministic
                # credential rejection (terminal) from transient errors.
                writer.write(encode({"type": "error", "code": "auth", "reason": "bad token"}))
                logger.warning("worker rejected: bad token")
                return
            if str(hello.get("role") or "") == "client":
                # Session tenant over the wire (protocol.py "Session
                # messages") — not a worker: no credit, no capacity, no
                # entry in the fleet table.
                await self._handle_client(reader, writer)
                return
            try:
                n_chips = max(1, int(hello.get("n_chips", 1)))
            except (TypeError, ValueError):
                n_chips = 1  # malformed advertisement: degrade, don't drop
            backend = hello.get("backend") or None
            capacity = max(1, int(hello.get("capacity", 1)))
            worker = _Worker(
                worker_id=str(hello.get("worker_id", f"worker-{wid}")),
                writer=writer,
                capacity=capacity,
                n_chips=n_chips,
                backend=str(backend) if backend is not None else None,
                prefetch_depth=self._parse_prefetch(hello, capacity),
                mesh=self._parse_mesh(hello),
                # Grant only capabilities BOTH ends speak; an old worker
                # advertises nothing and lands on the v1 frame set.
                caps=parse_caps(hello) & self._wire_caps,
                # Strict literal check — absent/malformed degrades to
                # stable, the conservative placement default.
                preemptible=hello.get("preemptible") is True,
                homes=self._parse_homes(hello),
            )
            # Heterogeneous-fleet check (ADVICE r3): two workers scoring one
            # generation with different estimators (e.g. xgb.cv on one host,
            # sklearn HistGradientBoosting on another) produce incomparable
            # fitnesses — warn the operator the moment the second one joins.
            others = {w.backend for w in self._workers.values() if w.backend}
            if worker.backend and others and others != {worker.backend}:
                logger.warning(
                    "heterogeneous fitness backends in the fleet: worker %s "
                    "uses %s but connected workers use %s — fitnesses from "
                    "different backends are not comparable within a generation",
                    worker.worker_id, worker.backend, sorted(others),
                )
            self._workers[wid] = worker
            if _tele.enabled():
                reg = _get_registry()
                reg.gauge("broker_workers_connected").set(len(self._workers))
                reg.gauge("fleet_members").set(len(self._workers))
                # Gauge appears only once a preemptible member has EVER
                # joined — a stable-only fleet's metric snapshot gains no
                # new series (PR-2 off-path contract).
                if worker.preemptible or self._seen_preemptible:
                    self._seen_preemptible = True
                    reg.gauge("preemptible_members").set(self.fleet_preemptible())
                # Series appears only for multi-homed workers (ISSUE 18) —
                # a single-broker fleet's metric snapshot gains nothing.
                if worker.homes > 1:
                    reg.gauge("worker_homes",
                              worker=worker.worker_id).set(worker.homes)
            _tele.record_event("worker_joined", {
                "worker_id": worker.worker_id, "capacity": worker.capacity,
                "prefetch_depth": worker.prefetch_depth,
                "members": len(self._workers),
            })
            # Echo the GRANTED capability set so the worker knows which
            # frames may arrive.  A caps-less worker gets the bare welcome —
            # byte-identical to every pre-caps broker.
            welcome: Dict[str, Any] = {"type": "welcome"}
            if worker.caps:
                welcome["caps"] = sorted(worker.caps)
            if self._boot_id is not None:
                # Boot identity (ISSUE 16): lets the worker stamp results
                # with the epoch that dispatched them, so a broker restart
                # can tell re-adopted work from truly stale echoes.  A
                # journal-off broker stays byte-identical on the wire.
                welcome["boot_id"] = self._boot_id
            writer.write(encode(welcome))
            logger.info(
                "worker %s connected (capacity %d, prefetch %d, %d chip(s)%s)",
                worker.worker_id, worker.capacity, worker.prefetch_depth,
                worker.n_chips,
                ", mesh pop=%(pop)d x data=%(data)d" % worker.mesh
                if worker.mesh else "",
            )

            while True:
                line = await reader.readline()
                if not line:
                    break  # EOF: worker gone
                msg = decode(line)
                if self._injector is not None:
                    # May delay, raise ProtocolError (corrupt), or close the
                    # connection and return None (drop_connection) — in which
                    # case the reader's EOF path runs the normal cleanup.
                    msg = self._injector.broker_recv(worker, msg)
                    if msg is None:
                        continue
                worker.last_seen = time.monotonic()
                mtype = msg["type"]
                if mtype == "ping":
                    # No pong reply, deliberately: the `last_seen` update
                    # above IS the liveness mechanism, and replies the
                    # client only reads between batches pile up unread in
                    # its receive buffer during a long training batch — a
                    # worker exiting right after its final results would
                    # then close a socket with unread data, turning the
                    # close into an RST that destroys the in-flight result
                    # frames at this end (measured: 3 of 4 results lost).
                    pass
                elif mtype == "ready":
                    try:
                        add = int(msg.get("credit", 1))
                    except (TypeError, ValueError):
                        add = 1  # malformed credit: degrade, don't drop the worker
                    # Credit ceiling is the worker's WINDOW (capacity +
                    # prefetch_depth): over-subscription keeps the worker's
                    # local ready-queue stocked so the device never waits
                    # for a results→breed→dispatch round trip.  With
                    # prefetch_depth 0 (or an old worker that never sent
                    # one) this is exactly the pre-pipelining clamp.
                    # A draining worker's late ready frame (in flight when
                    # its drain was processed) grants nothing.
                    if not worker.draining:
                        worker.credit = min(worker.window, worker.credit + add)
                        self._dispatch()
                elif mtype == "result":
                    self._on_result(worker, msg)
                elif mtype == "results":
                    # Coalesced form: one frame per worker evaluation group
                    # instead of one per job (protocol.py).  Each entry is
                    # deduplicated independently; the group's span report
                    # rides the frame and is ingested with the FIRST entry
                    # that survives dedup, so a duplicated frame still
                    # cannot double-ingest.
                    spans = msg.get("spans")
                    boot = msg.get("boot")
                    for entry in msg.get("results", ()):
                        e = dict(entry)
                        if spans is not None:
                            e["spans"] = spans
                        if boot is not None:
                            e["boot"] = boot
                        if self._on_result(worker, e):
                            spans = None
                elif mtype == "fail":
                    self._on_fail(worker, msg)
                elif mtype == "drain":
                    self._on_drain(worker, msg)
                elif mtype == "advertise":
                    self._on_advertise(worker, msg)
                else:
                    logger.warning("unknown message type %r from %s", mtype, worker.worker_id)
        except (ProtocolError, ConnectionError, asyncio.IncompleteReadError, ValueError) as e:
            # ValueError covers StreamReader limit overruns (frame > limit),
            # which should tear the connection down via the same cleanup path.
            logger.info("worker connection %d dropped: %s", wid, e)
        finally:
            if worker is not None:
                self._workers.pop(wid, None)
                if _tele.enabled():
                    reg = _get_registry()
                    reg.gauge("broker_workers_connected").set(len(self._workers))
                    reg.gauge("fleet_members").set(len(self._workers))
                    if self._seen_preemptible:
                        reg.gauge("preemptible_members").set(
                            self.fleet_preemptible())
                _tele.record_event("worker_left", {
                    "worker_id": worker.worker_id,
                    "drained": worker.draining,
                    "members": len(self._workers),
                })
                self._requeue_worker_jobs(worker, "disconnect")
                self._dispatch()
            writer.close()

    async def _handle_client(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter) -> None:
        """Wire-tenant connection (``hello`` with ``role="client"``).

        Runs in the broker loop, so session/scheduler mutations go through
        the same single-threaded paths as worker frames.  A dropped
        connection DETACHES the client's sessions (results park in their
        ``undelivered`` queues for re-attach); it does not close them.
        """
        welcome: Dict[str, Any] = {"type": "welcome"}
        if self._boot_id is not None:
            welcome["boot_id"] = self._boot_id
        writer.write(encode(welcome))
        attached: Set[str] = set()

        def _reject(sid: Any, reason: str) -> None:
            # The loud error frame (never a silent drop) + its counter.
            sid = str(sid)
            _get_registry().counter("session_rejected_total", session=sid).inc()
            writer.write(encode({"type": "error", "code": "session",
                                 "session": sid, "reason": reason}))

        def _admission_reject(sid: Any, verdict: tuple) -> None:
            # The 429 of the wire protocol: a structured, retryable
            # rejection carrying how long to back off.  Loud counters by
            # (session, reason) + the per-session ops tally for gentun_top.
            sid = str(sid)
            reason, retry_after = verdict
            self._admission_rejections[sid] = (
                self._admission_rejections.get(sid, 0) + 1)
            _get_registry().counter("admission_rejected_total",
                                    session=sid, reason=reason).inc()
            writer.write(encode({"type": "error", "code": "admission",
                                 "session": sid, "reason": reason,
                                 "retry_after_s": retry_after}))

        try:
            while True:
                line = await reader.readline()
                if not line:
                    break  # EOF: client gone
                msg = decode(line)
                mtype = msg.get("type")
                if mtype == "session_open":
                    verdict = self._admission_check(
                        str(msg.get("session") or "new"))
                    if verdict is not None:
                        _admission_reject(msg.get("session") or "new", verdict)
                        continue
                    try:
                        weight = float(msg.get("weight", 1.0))
                    except (TypeError, ValueError):
                        weight = 1.0
                    quota = msg.get("max_in_flight")
                    try:
                        quota = None if quota is None else int(quota)
                    except (TypeError, ValueError):
                        quota = None
                    # OPTIONAL tag ("canary"): classification only — never
                    # journaled, bounded so a hostile frame can't balloon
                    # the registry.
                    tag = msg.get("tag")
                    tag = str(tag)[:64] if tag else None
                    try:
                        sess = self._registry.open(
                            msg.get("session"), weight=weight,
                            max_in_flight=quota, remote=True, tag=tag)
                    except UnknownSessionError as e:  # reopening a closed id
                        _reject(msg.get("session"), str(e))
                        continue
                    sess.owner = writer
                    attached.add(sess.session_id)
                    # Re-attach: flush results that arrived while detached.
                    flushed = False
                    while sess.undelivered:
                        writer.write(encode(sess.undelivered.popleft()))
                        flushed = True
                    if self._journal is not None:
                        self._journal.record_session_open(
                            sess.session_id, sess.weight,
                            sess.max_in_flight, True)
                        if flushed:
                            # The parked results left the broker: replay
                            # must not re-park them for a second delivery.
                            self._journal.record_flush(sess.session_id)
                    writer.write(encode({"type": "session_ok",
                                         "session": sess.session_id}))
                elif mtype == "session_detach":
                    sid = str(msg.get("session"))
                    sess = self._registry.peek(sid)
                    if sess is not None and sess.owner is writer:
                        sess.owner = None
                    attached.discard(sid)
                    writer.write(encode({"type": "session_ok", "session": sid}))
                elif mtype == "session_close":
                    sid = str(msg.get("session"))
                    self.close_session(sid)
                    attached.discard(sid)
                    writer.write(encode({"type": "session_ok", "session": sid}))
                elif mtype == "submit":
                    sid = str(msg.get("session") or DEFAULT_SESSION)
                    sess = self._registry.peek(sid)
                    if sess is None or sess.closed:
                        state = "closed" if sess is not None else "unknown"
                        if sess is not None:
                            sess.rejected += len(msg.get("jobs") or ())
                        _reject(sid, f"session {sid!r} is {state}")
                        continue
                    verdict = self._admission_check(
                        sid, cost=max(1, len(msg.get("jobs") or ())))
                    if verdict is not None:
                        _admission_reject(sid, verdict)
                        continue
                    payloads = {}
                    for job in msg.get("jobs") or ():
                        job = dict(job)
                        job_id = str(job.pop("job_id", "") or self.new_job_id())
                        # Resubmit dedup (ISSUE 18): a sharded master whose
                        # submit ack died with the link retries the SAME ids
                        # after reconnect — ids still open here were already
                        # enqueued, so scheduling them again would double-run
                        # the job.  (Ids already TERMINAL re-run instead; the
                        # client results table dedups by id, so at-least-once
                        # still converges.)
                        if job_id in self._payloads:
                            continue
                        payloads[job_id] = job
                    if payloads:
                        self._enqueue_jobs(payloads, sid)
                elif mtype == "cancel":
                    self._cancel_ids({str(j) for j in msg.get("jobs") or ()})
                elif mtype == "session_stats":
                    # Sizing snapshot for WIRE tenants (ISSUE 18): sharded
                    # masters read their session's capacity/prefetch share
                    # and the fleet's mesh/chip facts over the wire instead
                    # of an embedded broker reference.  OPTIONAL message —
                    # old clients never send it, old brokers never see it.
                    sid = str(msg.get("session") or DEFAULT_SESSION)
                    if msg.get("reset_chips") is True:
                        self.reset_chips_seen()
                    stats_reply = {
                        "type": "session_stats",
                        "session": sid,
                        "capacity": self.session_capacity(sid),
                        "prefetch": self.session_prefetch(sid),
                        "mesh_pop": self.fleet_mesh_pop(),
                        "chips": self.chips_seen(),
                    }
                    ttfd = self.session_ttfd(sid)
                    if ttfd is not None:
                        # OPTIONAL field (absent until the session's first
                        # dispatch, so pre-dispatch replies keep the old
                        # byte layout): the canary's canary_ttfd_seconds.
                        stats_reply["ttfd_s"] = round(ttfd, 6)
                    writer.write(encode(stats_reply))
                elif mtype == "ping":
                    pass
                else:
                    logger.warning("unknown client message type %r", mtype)
        finally:
            for sid in attached:
                sess = self._registry.peek(sid)
                if sess is not None and sess.owner is writer:
                    sess.owner = None
            writer.close()

    def _on_result(self, w: _Worker, msg: Dict[str, Any]) -> bool:
        """Record one result; True iff it was fresh (not a stale duplicate)."""
        job_id = str(msg["job_id"])
        # Parse BEFORE touching broker state: a malformed fitness must count
        # as a worker-side failure (redeliverable), not delete the payload
        # and lose the job for good.
        try:
            fitness = float(msg["fitness"])
        except (KeyError, TypeError, ValueError):
            self._on_fail(w, {"job_id": job_id, "reason": f"malformed fitness: {msg.get('fitness')!r}"})
            return False
        w.in_flight.discard(job_id)
        # Epoch check (ISSUE 16): a worker that survived a broker crash may
        # deliver results for jobs dispatched by a PREVIOUS boot.  They are
        # accepted iff the job key matches the journal-rebuilt open set
        # (at-least-once re-adoption: exactly the result we were about to
        # redundantly recompute) and otherwise dropped with their own
        # counter — e.g. a job the journal shows already completed.
        boot = msg.get("boot")
        if (boot is not None and self._boot_id is not None
                and boot != self._boot_id and job_id not in self._payloads):
            logger.info("stale result for %s from broker epoch %r dropped "
                        "(current boot %s)", job_id, boot, self._boot_id)
            _get_registry().counter("epoch_stale_results_total").inc()
            return False
        if job_id not in self._payloads:
            logger.info("duplicate/stale result for %s dropped (redelivery race)", job_id)
            return False
        payload = self._payloads[job_id]
        del self._payloads[job_id]
        self._job_wire.pop(job_id, None)
        sid = self._job_session.pop(job_id, DEFAULT_SESSION)
        self._job_genome.pop(job_id, None)
        self._crash_counts.pop(job_id, None)
        sess = self._registry.peek(sid)
        if sess is not None:
            sess.completed += 1
        if _health.enabled():
            # Fresh results only (behind the dedup check): a duplicate's
            # RTT would double-sample the watchdog's rolling window.
            self._watchdog.job_finished(job_id)
        if _tele.enabled():
            # Behind the membership check on purpose: a duplicated result
            # frame (chaos: duplicate_result) must not double-ingest the
            # worker's span report either.
            attrs = {"worker": w.worker_id}
            if sid != DEFAULT_SESSION:
                attrs["session"] = sid
            t_enq = self._tele_enqueued.pop(job_id, None)
            if t_enq is not None:
                dur = time.monotonic() - t_enq
                _tele.record_span("job", t_enq, dur,
                                  trace=payload.get("trace"),
                                  attrs=attrs)
                _get_registry().histogram("broker_job_latency_seconds").observe(dur)
            t_disp = self._tele_dispatched.pop(job_id, None)
            if t_disp is not None:
                # The pipelining acceptance signal: handoff → result.  With
                # prefetch, a job's RTT INCLUDES its residence in the
                # worker's local ready-queue, so per-job RTT grows while
                # fleet throughput does too — read it with queue depth
                # (docs/OBSERVABILITY.md "interpretation rules of thumb").
                rtt = time.monotonic() - t_disp
                _tele.record_span("dispatch_rtt", t_disp, rtt,
                                  trace=payload.get("trace"),
                                  attrs=attrs)
                _get_registry().histogram("dispatch_rtt_s").observe(rtt)
            reported = msg.get("spans")
            if reported:
                _tele.ingest(reported)
                # Chip-hour attribution: the worker's per-genome `device`
                # spans land in the cost ledger here, behind the same
                # dedup check, so a duplicated frame never double-bills.
                _lineage.observe_records(reported, worker=w.worker_id)
            self._update_flow_gauges()
        with self._cond:
            # Under _cond: reset_chips_seen()/chips_seen() run on the master
            # thread, and an unsynchronized read-modify-write here could
            # resurrect a pre-reset total into the next sweep.
            self._chips_seen = max(self._chips_seen, self.fleet_chips())
            if sess is None or not sess.remote:
                self._results[job_id] = fitness
                self._cond.notify_all()
        delivered = True
        if sess is not None and sess.remote:
            # Wire tenant: the result belongs to the attached client, not
            # the in-process results table — forward (or park) the frame.
            delivered = self._deliver_remote(sess, {
                "type": "results", "session": sid,
                "results": [{"job_id": job_id, "fitness": fitness}],
            })
        if self._journal is not None:
            # pk=1 ⇔ the result sits parked in the session's undelivered
            # queue: replay must re-park it for the re-attaching owner.
            self._journal.record_complete(job_id, fitness,
                                          parked=not delivered)
        return True

    def _on_fail(self, w: _Worker, msg: Dict[str, Any]) -> None:
        job_id = str(msg["job_id"])
        reason = str(msg.get("reason", "unknown"))
        w.in_flight.discard(job_id)
        if job_id not in self._payloads:
            return
        if _health.enabled():
            # Fail is not a round trip: forget without sampling the RTT.
            self._watchdog.job_removed(job_id)
        # Only explicit worker-side failures count toward max_attempts;
        # disconnect/reaper redeliveries are unbounded, like AMQP's.
        self._fail_counts[job_id] = self._fail_counts.get(job_id, 0) + 1
        if self._fail_counts[job_id] >= self._max_attempts:
            logger.error("job %s failed %d times: %s", job_id, self._fail_counts[job_id], reason)
            self._fail_terminal(job_id, reason)
        else:
            logger.warning("job %s failed (%s); requeueing", job_id, reason)
            sid = self._job_session.get(job_id, DEFAULT_SESSION)
            if self._journal is not None:
                self._journal.record_requeue(job_id)
            self._sched.push(sid, job_id)
            self._tele_dispatched.pop(job_id, None)
            if _lineage.enabled():
                _lineage.record(
                    "requeued", self._job_genome.get(job_id),
                    job=job_id, worker=w.worker_id, reason="worker_fail",
                    session=sid if sid != DEFAULT_SESSION else None)
            if _tele.enabled():
                self._tele_enqueued[job_id] = time.monotonic()
            self._dispatch()

    def _on_drain(self, w: _Worker, msg: Dict[str, Any]) -> None:
        """Orderly worker exit (elastic membership, protocol.py ``drain``).

        The worker announces it is leaving and reports the job ids still
        queued-but-unstarted in its local prefetch queue; those requeue
        for redelivery NOW instead of waiting for the disconnect, while
        the batch it is currently evaluating finishes and its results are
        accepted normally.  From this frame on the worker gets no new
        work, grants no credit, and leaves the fleet sums — the engines'
        next live-capacity read shrinks accordingly.  Any dispatched job
        the worker did NOT report (e.g. a ``jobs`` frame that was on the
        wire when it decided to drain) is covered by the disconnect
        requeue; at-least-once delivery makes the overlap harmless.
        """
        if w.draining:
            return  # duplicate drain frame: already winding down
        w.draining = True
        w.credit = 0
        tele = _tele.enabled()
        ops = _health.enabled()
        # OPTIONAL drain attribution (protocol.py "Preemptible-capacity
        # field"): "preempt" marks capacity-reclaim churn; anything else —
        # absent, old worker, hostile — degrades to the plain "drain".
        reason = "preempt" if msg.get("reason") == "preempt" else "drain"
        requeued = 0
        for job_id in msg.get("requeue") or ():
            job_id = str(job_id)
            if job_id not in w.in_flight or job_id not in self._payloads:
                continue  # finished/cancelled since the worker queued it
            w.in_flight.discard(job_id)
            sid = self._job_session.get(job_id, DEFAULT_SESSION)
            if self._journal is not None:
                self._journal.record_requeue(job_id)
            self._sched.push(sid, job_id)
            sess = self._registry.peek(sid)
            if sess is not None:
                sess.requeued += 1
            if _lineage.enabled():
                _lineage.record(
                    "requeued", self._job_genome.get(job_id),
                    job=job_id, worker=w.worker_id, reason=reason,
                    session=sid if sid != DEFAULT_SESSION else None)
            if ops:
                self._watchdog.job_removed(job_id)
            self._tele_dispatched.pop(job_id, None)
            if tele:
                self._tele_enqueued[job_id] = time.monotonic()
            requeued += 1
        logger.info(
            "worker %s draining: requeued %d unstarted job(s), finishing %d "
            "in flight", w.worker_id, requeued, len(w.in_flight))
        if tele:
            _get_registry().counter("worker_drains_total",
                                    worker=w.worker_id).inc()
            if self._seen_preemptible:
                _get_registry().gauge("preemptible_members").set(
                    self.fleet_preemptible())
            self._update_flow_gauges()
        _tele.record_event("worker_draining", {
            "worker_id": w.worker_id, "requeued": requeued,
            "finishing": len(w.in_flight), "reason": reason,
        })
        self._dispatch()

    def _on_advertise(self, w: _Worker, msg: Dict[str, Any]) -> None:
        """Capacity/prefetch re-advertisement (elastic membership).

        A worker whose local resources changed mid-run (chips freed,
        co-tenant gone) updates its hello-time numbers in place; the
        fleet sums — and through them the engines' in-flight targets —
        follow on their next read.  Malformed values keep the old numbers
        (degrade, don't drop, like every other field).  Credit above the
        new window is clamped; already-dispatched jobs are unaffected,
        and growth is granted by the worker's next ``ready`` frame.
        """
        if w.draining:
            return  # a draining worker has no capacity to re-advertise
        if "capacity" in msg:
            try:
                w.capacity = max(1, int(msg["capacity"]))
            except (TypeError, ValueError):
                pass
        if "prefetch_depth" in msg:
            w.prefetch_depth = self._parse_prefetch(msg, w.capacity)
        if "mesh" in msg:
            # Host-mesh workers re-advertise their shape with the new
            # capacity (elastic mesh shrink/grow: device lost or returned).
            w.mesh = self._parse_mesh(msg)
        if "preemptible" in msg:
            # Placement class change (e.g. a spot VM promoted to reserved
            # capacity).  Strict literal check, like hello.
            w.preemptible = msg["preemptible"] is True
            if _tele.enabled() and (w.preemptible or self._seen_preemptible):
                self._seen_preemptible = True
                _get_registry().gauge("preemptible_members").set(
                    self.fleet_preemptible())
        w.credit = min(w.credit, w.window)
        logger.info("worker %s re-advertised capacity=%d prefetch=%d%s",
                    w.worker_id, w.capacity, w.prefetch_depth,
                    " mesh pop=%(pop)d x data=%(data)d" % w.mesh
                    if w.mesh else "")
        _tele.record_event("worker_readvertised", {
            "worker_id": w.worker_id, "capacity": w.capacity,
            "prefetch_depth": w.prefetch_depth, "mesh": w.mesh,
        })
        self._dispatch()


def main(argv=None) -> int:
    """Standalone broker process (``python -m gentun_tpu.distributed.broker``).

    The crash-safety counterpart of the embedded broker: run it under a
    supervisor with ``--journal``, and a restart after ``kill -9`` replays
    to the pre-crash dispatch state — workers re-adopt through their
    reconnect backoff, wire tenants through ``SessionClient`` re-attach.
    """
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m gentun_tpu.distributed.broker",
        description="gentun_tpu job broker (standalone, crash-safe with --journal)",
    )
    ap.add_argument("--host", default="127.0.0.1", help="bind address")
    ap.add_argument("--port", type=int, default=5672, help="bind port (0 = ephemeral)")
    ap.add_argument("--password", default=None, help="shared token workers/tenants must present")
    ap.add_argument("--journal", default=None, metavar="PATH",
                    help="dispatch journal path: replay it on boot (crash "
                         "re-adoption), append this boot's records to it")
    ap.add_argument("--heartbeat-timeout", type=float, default=15.0)
    ap.add_argument("--max-attempts", type=int, default=3)
    ap.add_argument("--admission-rate", type=float, default=None, metavar="N",
                    help="per-tenant token-bucket rate (frames/s) on wire "
                         "session_open/submit; unset = no rate limit")
    ap.add_argument("--admission-burst", type=float, default=None, metavar="N",
                    help="token-bucket burst size (default: max(1, rate))")
    ap.add_argument("--admission-queue-factor", type=float, default=None, metavar="F",
                    help="reject wire submits while backlog > F x live "
                         "capacity (structured admission error with "
                         "retry_after_s); unset = no back-pressure")
    ap.add_argument("--aggregator-url", default=None, metavar="URL")
    ap.add_argument("--ops-port", type=int, default=None, metavar="PORT",
                    help="serve /metrics /healthz /statusz /alertz on "
                         "127.0.0.1:PORT (0 = ephemeral, logged)")
    ap.add_argument("--ops-host", default="127.0.0.1", metavar="ADDR")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)

    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s %(message)s")
    broker = JobBroker(
        host=args.host, port=args.port, token=args.password,
        heartbeat_timeout=args.heartbeat_timeout,
        max_attempts=args.max_attempts,
        aggregator_url=args.aggregator_url,
        journal_path=args.journal,
        admission_rate=args.admission_rate,
        admission_burst=args.admission_burst,
        admission_queue_factor=args.admission_queue_factor,
    )
    broker.start()
    if args.ops_port is not None:
        from ..telemetry import start_ops_server
        start_ops_server(host=args.ops_host, port=args.ops_port)
    logger.info("broker ready on %s:%d (epoch %d%s)", *broker.address,
                broker._epoch, ", journal on" if args.journal else "")
    try:
        while True:
            time.sleep(3600.0)
    except KeyboardInterrupt:
        logger.info("interrupt: stopping broker")
    finally:
        broker.stop()
    return 0


if __name__ == "__main__":  # pragma: no cover - CLI entry
    raise SystemExit(main())
