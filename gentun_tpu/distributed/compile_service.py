"""Fleet-wide compile amortization: a networked executable cache (ROADMAP 5).

Every elastic join (PR 7) and remesh (PR 9) pays cold-start XLA compiles
per worker, even though the masked-supergraph design (PAPER.md) means a
small, enumerable set of ``(pop_bucket, static-key)`` programs serves the
whole search space — at fleet scale the same program is compiled hundreds
of times.  ``utils/xla_cache.py`` already persists compiled executables on
disk, but a directory only reaches processes that mount it.  This module
promotes that cache to a small network service, the exact sibling of
``fitness_service.py`` (same stdlib ``ThreadingHTTPServer`` + bounded LRU
+ ``/healthz``/``/statusz`` + version-skew-409 + standalone ``python -m``
pattern), so whichever worker compiles a shape first publishes the
artifact and every later joiner fetches instead of compiling —
minutes-to-warm becomes seconds.

Three pieces, all stdlib:

- :class:`CompileService` — a byte-budget LRU of serialized compile
  artifacts.  Blobs are content-addressed by their XLA cache-entry name
  (jax's own cache-key hash, which encodes the program, compile options
  and topology) and namespaced by a **platform fingerprint**
  (:func:`platform_fingerprint`: jax/jaxlib versions, device platform and
  kind, relevant XLA env knobs).  A fetch or publish whose fingerprint
  disagrees with the one an entry is stored under is refused with HTTP
  409 — an incompatible binary can never be served, the same
  all-writers-upgrade-together guard the fitness service applies to its
  store version.
- :class:`CompileServiceClient` — read-through ``prefetch()`` of the
  fleet's entries into the local cache dir *before* the first compile,
  and write-behind ``scan_publish()`` of freshly written entries (an
  ``os.stat`` dir-mtime probe keeps the no-change path off the dispatch
  hot cost — measured by ``scripts/broker_throughput.py``).  Any network
  failure degrades the client for a cooldown window with exactly ONE
  ``compile_service_degraded`` telemetry event: cache downtime must never
  fail a search, it only costs recompiles.
- a publish hook (``utils/xla_cache.register_publish_hook``) so
  ``models/cnn.py::_prepare_population_setup`` can trigger a publish scan
  after each first compile without the models layer importing the
  distributed package.

Like the ops endpoints, the service is unauthenticated and binds
127.0.0.1 by default; bind a routable address only on a trusted network.
Run it standalone with ``python -m gentun_tpu.distributed.compile_service
--port 9737``, or in-process via ``CompileService(...).start()``.
"""

from __future__ import annotations

import base64
import binascii
import hashlib
import json
import logging
import os
import re
import threading
import time
import urllib.error
import urllib.request
from collections import OrderedDict, deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

from ..telemetry import spans as _tele
from ..telemetry.registry import get_registry as _get_registry
from ..utils.xla_cache import (
    list_cache_entries,
    register_publish_hook,
    unregister_publish_hook,
)
from .fitness_service import parse_cache_url

__all__ = [
    "COMPILE_PROTOCOL",
    "CompileService",
    "CompileServiceClient",
    "parse_cache_url",
    "platform_components",
    "platform_fingerprint",
]

logger = logging.getLogger("gentun_tpu.distributed")

#: Wire protocol version; bump on any incompatible change to the message
#: shapes below.  Enforced with HTTP 409 exactly like ``FITNESS_PROTOCOL``.
COMPILE_PROTOCOL = 1

#: Request-body ceiling.  Compiled executables are far larger than fitness
#: floats (tens of KB to a few MB serialized, base64 inflates by 4/3), so
#: the ceiling is raised well above the fitness service's 4 MiB.
_MAX_BODY_BYTES = 64 * 1024 * 1024

#: Per-blob ceiling: a single artifact larger than this is never shipped
#: (it would monopolize the service budget; it simply stays local).
_MAX_BLOB_BYTES = 32 * 1024 * 1024

#: Cache-entry names are XLA cache-key hashes (hex-ish file names).  Both
#: sides refuse anything else: the client writes fetched blobs to the
#: filesystem under this name, so the charset IS the path-traversal guard.
_SAFE_NAME = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._+=-]{0,254}$")


def _safe_name(name: Any) -> bool:
    return isinstance(name, str) and bool(_SAFE_NAME.match(name)) and ".." not in name


def platform_components(probe_devices: bool = True) -> Dict[str, str]:
    """The facts that decide whether a compiled artifact is compatible.

    jax/jaxlib versions (serialized executables are not stable across
    releases), the device platform and kind (a TPU v4 binary must never
    reach a v5e, let alone a CPU), and the env knobs that change XLA
    codegen.  ``probe_devices=False`` skips ``jax.devices()`` — probing
    forces backend init, which a jax-free worker (XGBoost species, pure
    tooling) must not pay; such clients still get a stable fingerprint,
    they just never share entries with device-probed ones.
    """
    comps: Dict[str, str] = {}
    try:
        import jax

        comps["jax"] = str(jax.__version__)
        try:
            import jaxlib

            comps["jaxlib"] = str(jaxlib.__version__)
        except Exception:  # pragma: no cover - jaxlib always ships with jax
            comps["jaxlib"] = "unknown"
        if probe_devices:
            dev = jax.devices()[0]
            comps["platform"] = str(dev.platform)
            comps["device_kind"] = str(dev.device_kind)
        else:
            comps["platform"] = "unprobed"
            comps["device_kind"] = "unprobed"
    except Exception:  # jax missing entirely: still a valid (lonely) namespace
        comps["jax"] = "none"
        comps["jaxlib"] = "none"
        comps["platform"] = "none"
        comps["device_kind"] = "none"
    # Env knobs that change generated code.  Topology is deliberately NOT
    # here: XLA's own cache-key (the entry name) already encodes it.
    comps["xla_flags"] = os.environ.get("XLA_FLAGS", "")
    comps["libtpu_init_args"] = os.environ.get("LIBTPU_INIT_ARGS", "")
    return comps


def platform_fingerprint(probe_devices: bool = True) -> str:
    """64-bit blake2b over the canonical components JSON (PR-1 hash width)."""
    blob = json.dumps(platform_components(probe_devices=probe_devices),
                      sort_keys=True, separators=(",", ":")).encode()
    return hashlib.blake2b(blob, digest_size=8).hexdigest()


class FingerprintConflict(Exception):
    """An entry name exists under a different platform fingerprint.

    Names are XLA cache-key hashes, so two *compatible* platforms cannot
    legitimately collide on a name — a conflict means an incompatible
    binary is one fetch away from being served.  The handler maps this to
    HTTP 409 with both fingerprints so the operator can see which side is
    skewed.
    """

    def __init__(self, name: str, stored: str, requested: str):
        super().__init__(
            f"entry {name!r} is stored under platform fingerprint {stored}, "
            f"request carries {requested}")
        self.name = name
        self.stored = stored
        self.requested = requested


class _Handler(BaseHTTPRequestHandler):
    """Request handler; ``self.server.service`` is the CompileService."""

    server_version = "gentun-compile/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # noqa: D102 - silence stderr chatter
        pass

    def _send_json(self, code: int, obj: Any) -> None:
        body = json.dumps(obj, separators=(",", ":")).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _read_body(self) -> Optional[Any]:
        try:
            n = int(self.headers.get("Content-Length", "0"))
        except ValueError:
            n = -1
        if not 0 < n <= _MAX_BODY_BYTES:
            self._send_json(413, {"error": f"body length {n} out of range"})
            return None
        try:
            return json.loads(self.rfile.read(n).decode())
        except (ValueError, UnicodeDecodeError) as e:
            self._send_json(400, {"error": f"bad json: {e}"})
            return None

    def _check_request(self, msg: Dict[str, Any]) -> Optional[str]:
        """Protocol-skew 409 + fingerprint extraction; None refuses."""
        proto = msg.get("protocol")
        if proto != COMPILE_PROTOCOL:
            self._send_json(409, {
                "error": "version skew",
                "protocol": COMPILE_PROTOCOL,
                "client_protocol": proto,
            })
            return None
        fp = msg.get("fingerprint")
        if not isinstance(fp, str) or not fp:
            self._send_json(400, {"error": "fingerprint must be a non-empty string"})
            return None
        return fp

    def do_GET(self):  # noqa: N802 - http.server API
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        svc = self.server.service  # type: ignore[attr-defined]
        if path in ("/", "/healthz"):
            self._send_json(200, {"status": "ok", **svc.stats()})
        elif path == "/statusz":
            self._send_json(200, svc.stats())
        else:
            self._send_json(404, {"error": f"no route {path}"})

    def do_POST(self):  # noqa: N802 - http.server API
        path = self.path.split("?", 1)[0].rstrip("/")
        svc = self.server.service  # type: ignore[attr-defined]
        msg = self._read_body()
        if msg is None:
            return
        if not isinstance(msg, dict):
            self._send_json(400, {"error": "body must be an object"})
            return
        fp = self._check_request(msg)
        if fp is None:
            return
        try:
            if path == "/v1/list":
                self._send_json(200, {"names": svc.list_names(fp)})
            elif path == "/v1/fetch":
                names = msg.get("names")
                if not isinstance(names, list):
                    self._send_json(400, {"error": "names must be a list"})
                    return
                blobs = svc.fetch(fp, names)
                self._send_json(200, {"blobs": {
                    n: base64.b64encode(b).decode("ascii")
                    for n, b in blobs.items()
                }})
            elif path == "/v1/publish":
                entries = msg.get("entries")
                if not isinstance(entries, list):
                    self._send_json(400, {"error": "entries must be a list"})
                    return
                decoded: List[Tuple[str, bytes]] = []
                for entry in entries:
                    if (not isinstance(entry, (list, tuple)) or len(entry) != 2
                            or not _safe_name(entry[0])
                            or not isinstance(entry[1], str)):
                        continue
                    try:
                        decoded.append((entry[0], base64.b64decode(
                            entry[1], validate=True)))
                    except (binascii.Error, ValueError):
                        continue
                self._send_json(200, {"stored": svc.publish(fp, decoded)})
            else:
                self._send_json(404, {"error": f"no route {path}"})
        except FingerprintConflict as e:
            self._send_json(409, {
                "error": "platform fingerprint mismatch",
                "name": e.name,
                "stored_fingerprint": e.stored,
                "client_fingerprint": e.requested,
            })


class CompileService:
    """Byte-budget LRU of compiled artifacts behind a ThreadingHTTPServer.

    State is one ``OrderedDict[(fingerprint, name) → bytes]`` under one
    lock — fetches ``move_to_end`` and publishes evict from the cold end
    while the total payload exceeds ``max_bytes`` (artifacts vary by
    orders of magnitude, so the budget is bytes, not entries).  A
    name→fingerprint index detects cross-platform conflicts
    (:class:`FingerprintConflict` → 409).  Counters are served on
    ``/statusz`` and, when telemetry is enabled in the hosting process,
    mirrored to the metrics registry as
    ``compile_cache_{hits,misses,publishes,evictions}_total``.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 max_bytes: int = 1 * 1024 * 1024 * 1024):
        if max_bytes <= 0:
            raise ValueError(f"max_bytes must be positive, got {max_bytes}")
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._blobs: "OrderedDict[Tuple[str, str], bytes]" = OrderedDict()
        self._owner: Dict[str, str] = {}  # name → fingerprint
        self._bytes = 0
        self._hits = 0
        self._misses = 0
        self._puts = 0
        self._evictions = 0
        self._conflicts = 0
        self._started = time.time()
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.service = self  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    # -- address -----------------------------------------------------------

    @property
    def address(self) -> Tuple[str, int]:
        return self._httpd.server_address[:2]

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "CompileService":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.25},
            name="compile-service", daemon=True)
        self._thread.start()
        logger.info("compile service serving on %s (budget %d MiB)",
                    self.url, self.max_bytes // (1024 * 1024))
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # -- cache ops (also usable in-process, no HTTP) -----------------------

    def _check_owner(self, fp: str, name: str) -> None:
        owner = self._owner.get(name)
        if owner is not None and owner != fp:
            self._conflicts += 1
            raise FingerprintConflict(name, owner, fp)

    def list_names(self, fp: str) -> List[str]:
        with self._lock:
            return [name for (f, name) in self._blobs if f == fp]

    def fetch(self, fp: str, names: List[Any]) -> Dict[str, bytes]:
        out: Dict[str, bytes] = {}
        n_miss = 0
        with self._lock:
            for name in names:
                if not _safe_name(name):
                    n_miss += 1
                    continue
                self._check_owner(fp, name)
                key = (fp, name)
                if key in self._blobs:
                    self._blobs.move_to_end(key)
                    out[name] = self._blobs[key]
                else:
                    n_miss += 1
            self._hits += len(out)
            self._misses += n_miss
        if _tele.enabled():
            reg = _get_registry()
            if out:
                reg.counter("compile_cache_hits_total").inc(len(out))
            if n_miss:
                reg.counter("compile_cache_misses_total").inc(n_miss)
        return out

    def publish(self, fp: str, entries: List[Tuple[str, bytes]]) -> int:
        stored = 0
        evicted = 0
        with self._lock:
            for name, data in entries:
                if not _safe_name(name) or not isinstance(data, bytes):
                    continue
                if len(data) > min(self.max_bytes, _MAX_BLOB_BYTES):
                    continue  # would monopolize (or instantly blow) the budget
                self._check_owner(fp, name)
                key = (fp, name)
                old = self._blobs.get(key)
                if old is not None:
                    # Idempotent re-publish: content-addressed names mean the
                    # payload is the same; just refresh recency.
                    self._bytes -= len(old)
                self._blobs[key] = data
                self._blobs.move_to_end(key)
                self._owner[name] = fp
                self._bytes += len(data)
                stored += 1
            self._puts += stored
            while self._bytes > self.max_bytes and self._blobs:
                (f, name), data = self._blobs.popitem(last=False)
                self._owner.pop(name, None)
                self._bytes -= len(data)
                evicted += 1
            self._evictions += evicted
        if _tele.enabled():
            reg = _get_registry()
            if stored:
                reg.counter("compile_cache_publishes_total").inc(stored)
            if evicted:
                reg.counter("compile_cache_evictions_total").inc(evicted)
        return stored

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "entries": len(self._blobs),
                "bytes": self._bytes,
                "max_bytes": self.max_bytes,
                "fingerprints": len({f for (f, _n) in self._blobs}),
                "hits": self._hits,
                "misses": self._misses,
                "puts": self._puts,
                "evictions": self._evictions,
                "conflicts": self._conflicts,
                "uptime_s": round(time.time() - self._started, 3),
                "protocol": COMPILE_PROTOCOL,
            }


class CompileServiceClient:
    """Read-through prefetch + write-behind publish for the local XLA cache.

    ``prefetch()`` lists the service's entries for this platform
    fingerprint and downloads the ones missing locally into ``cache_dir``
    (atomic tmp+rename, so jax never sees a torn file) — call it BEFORE
    the first compile, and again after ``remesh()`` before re-advertising
    capacity.  ``scan_publish()`` diffs the cache dir against what the
    fleet already has and queues new entries on a write-behind flusher; an
    ``os.stat`` dir-mtime probe makes the steady-state call a
    sub-microsecond no-op, cheap enough to run after every batch.

    Degradation mirrors :class:`FitnessServiceClient`: any network
    failure (refused, timeout, 5xx, 409 skew) marks the service down for
    ``cooldown`` seconds, during which nothing touches the socket; the
    transition emits ONE ``compile_service_degraded`` telemetry event and
    one warning.  Nothing in this class ever raises into the caller —
    losing the service only costs recompiles, never a search.
    """

    def __init__(self, url: str, cache_dir: Optional[str] = None,
                 timeout: float = 5.0, cooldown: float = 5.0,
                 probe_devices: bool = True,
                 fingerprint: Optional[str] = None,
                 max_pending: int = 1024):
        from ..utils.xla_cache import default_cache_dir

        self.url = parse_cache_url(url)
        self.cache_dir = cache_dir if cache_dir is not None else default_cache_dir()
        self.timeout = float(timeout)
        self.cooldown = float(cooldown)
        self._probe_devices = bool(probe_devices)
        self._fp = fingerprint
        self._down_until = 0.0
        self._degraded = False
        self._lock = threading.Lock()
        self._fetched = 0
        self._published = 0
        self._compiled_local = 0
        self._degraded_total = 0
        # Names the fleet already has (listed remotely, fetched, or queued
        # by us): scan_publish never re-ships them.
        self._known: set = set()
        self._last_dir_mtime_ns = -1
        self._pending: deque = deque(maxlen=max_pending)
        self._wake = threading.Event()
        self._closed = False
        self._flusher: Optional[threading.Thread] = None
        # One stable bound method so xla_cache's hook registry can
        # register and unregister the same object.
        self.publish_hook = self.scan_publish

    @property
    def fingerprint(self) -> str:
        """Lazy: device probing (for jax species) waits until first use."""
        if self._fp is None:
            self._fp = platform_fingerprint(probe_devices=self._probe_devices)
        return self._fp

    # -- availability ------------------------------------------------------

    def available(self) -> bool:
        with self._lock:
            return time.monotonic() >= self._down_until

    @property
    def degraded(self) -> bool:
        with self._lock:
            return self._degraded

    def _mark_down(self, err: Exception) -> None:
        with self._lock:
            self._down_until = time.monotonic() + self.cooldown
            first = not self._degraded
            self._degraded = True
            self._degraded_total += 1
        if first:
            logger.warning(
                "compile service %s unreachable (%s); degrading to "
                "local-only compiles, retrying every %.1fs — the search "
                "continues, this worker just compiles what it can't fetch",
                self.url, err, self.cooldown)
            _tele.record_event("compile_service_degraded", {
                "url": self.url, "error": str(err)[:200],
            })
            if _tele.enabled():
                _get_registry().counter("compile_service_degraded_total").inc()

    def _mark_up(self) -> None:
        with self._lock:
            was = self._degraded
            self._degraded = False
        if was:
            logger.info("compile service %s reachable again", self.url)

    # -- http --------------------------------------------------------------

    def _post(self, endpoint: str, payload: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        body = dict(payload)
        body["v"] = 1
        body["protocol"] = COMPILE_PROTOCOL
        body["fingerprint"] = self.fingerprint
        req = urllib.request.Request(
            self.url + endpoint,
            data=json.dumps(body, separators=(",", ":")).encode(),
            headers={"Content-Type": "application/json"},
            method="POST")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                out = json.loads(resp.read().decode())
            self._mark_up()
            return out
        except Exception as e:  # noqa: BLE001 - degradation boundary by design
            self._mark_down(e)
            return None

    # -- read-through ------------------------------------------------------

    def prefetch(self) -> int:
        """Pull the fleet's entries for this platform into ``cache_dir``.

        Returns the number of blobs written.  Never raises; a degraded or
        empty service simply means the first compile pays full price.
        """
        if self.cache_dir is None or not self.available():
            return 0
        out = self._post("/v1/list", {})
        if out is None:
            return 0
        names = [n for n in out.get("names", []) if _safe_name(n)]
        self._known.update(names)  # fleet has them: never publish back
        if not names:
            return 0
        try:
            os.makedirs(self.cache_dir, exist_ok=True)
            local = set(list_cache_entries(self.cache_dir))
        except OSError as e:
            logger.warning("compile prefetch: cache dir %s unusable (%s)",
                           self.cache_dir, e)
            return 0
        missing = [n for n in names if n not in local]
        if not missing:
            return 0
        t0 = time.monotonic()
        fetched = 0
        for i in range(0, len(missing), 32):
            out = self._post("/v1/fetch", {"names": missing[i:i + 32]})
            if out is None:
                break
            blobs = out.get("blobs")
            if not isinstance(blobs, dict):
                continue
            for name, b64 in blobs.items():
                if not _safe_name(name) or not isinstance(b64, str):
                    continue
                try:
                    data = base64.b64decode(b64, validate=True)
                except (binascii.Error, ValueError):
                    continue
                tmp = os.path.join(self.cache_dir, f".fetch-{os.getpid()}.tmp")
                try:
                    with open(tmp, "wb") as f:
                        f.write(data)
                    os.replace(tmp, os.path.join(self.cache_dir, name))
                except OSError as e:
                    logger.warning("compile prefetch: cannot write %s (%s)",
                                   name, e)
                    try:
                        os.unlink(tmp)
                    except OSError:
                        pass
                    continue
                fetched += 1
        if fetched:
            dt = time.monotonic() - t0
            reg = _get_registry()
            reg.histogram("compile_fetch_seconds").observe(dt)
            reg.counter("compile_cache_hits_total").inc(fetched)
            with self._lock:
                self._fetched += fetched
            logger.info(
                "compile prefetch: %d artifact(s) fetched from %s in %.3fs "
                "— this worker skips those compiles", fetched, self.url, dt)
        return fetched

    # -- write-behind ------------------------------------------------------

    def scan_publish(self) -> int:
        """Queue cache entries the fleet doesn't have yet; returns #queued.

        The fast path is one ``os.stat`` on the cache dir: when its mtime
        is unchanged since the last scan there is nothing new and no
        listing, hashing or HTTP happens — that cost rides the dispatch
        loop, so it is gated in ``scripts/broker_throughput.py``.
        """
        if self._closed or self.cache_dir is None:
            return 0
        try:
            st = os.stat(self.cache_dir)
        except OSError:
            return 0  # nothing compiled yet — dir doesn't even exist
        if st.st_mtime_ns == self._last_dir_mtime_ns:
            return 0
        try:
            entries = list_cache_entries(self.cache_dir)
        except OSError:
            return 0
        # Stat taken BEFORE the listing: a write racing the scan bumps the
        # mtime past `st` and re-triggers the next scan, never lost.
        self._last_dir_mtime_ns = st.st_mtime_ns
        queued = 0
        for name, (size, _mtime) in entries.items():
            if name in self._known or not _safe_name(name):
                continue
            if size > _MAX_BLOB_BYTES:
                self._known.add(name)  # too big to ship; don't re-stat forever
                continue
            try:
                with open(os.path.join(self.cache_dir, name), "rb") as f:
                    data = f.read()
            except OSError:
                continue
            self._known.add(name)
            self._pending.append((name, data))
            queued += 1
        if queued:
            with self._lock:
                self._compiled_local += queued
            reg = _get_registry()
            # A locally-written entry IS a fleet cache miss: nobody had
            # this shape, so this worker paid the compile.
            reg.counter("compile_cache_misses_total").inc(queued)
            reg.counter("compile_cache_publishes_total").inc(queued)
            if self._flusher is None:
                with self._lock:
                    if self._flusher is None and not self._closed:
                        self._flusher = threading.Thread(
                            target=self._flush_loop, name="compile-publish",
                            daemon=True)
                        self._flusher.start()
            self._wake.set()
        return queued

    def _drain_batch(self, cap_bytes: int = 8 * 1024 * 1024) -> List[Tuple[str, bytes]]:
        batch: List[Tuple[str, bytes]] = []
        total = 0
        while self._pending and (not batch or total < cap_bytes):
            try:
                name, data = self._pending.popleft()
            except IndexError:  # pragma: no cover - racing producer
                break
            batch.append((name, data))
            total += len(data)
        return batch

    def _flush_loop(self) -> None:
        while True:
            self._wake.wait(timeout=0.5)
            self._wake.clear()
            if self._closed and not self._pending:
                return
            if not self._pending:
                continue
            if not self.available():
                if self._closed:
                    return  # closing while degraded: entries stay local
                time.sleep(min(0.5, self.cooldown))
                continue
            batch = self._drain_batch()
            if batch:
                out = self._post("/v1/publish", {"entries": [
                    [n, base64.b64encode(d).decode("ascii")] for n, d in batch
                ]})
                if out is None:
                    # Failed mid-flight: requeue so a transient blip doesn't
                    # drop artifacts (deque maxlen bounds the worst case).
                    self._pending.extendleft(reversed(batch))
                else:
                    with self._lock:
                        self._published += len(batch)

    def flush(self, timeout: float = 5.0) -> bool:
        """Best-effort wait for the write-behind queue to drain."""
        deadline = time.monotonic() + timeout
        self._wake.set()
        while self._pending and time.monotonic() < deadline:
            if not self.available():
                return False
            time.sleep(0.02)
        return not self._pending

    def close(self, flush_timeout: float = 2.0) -> None:
        """Final scan + flush what we can, then stop the flusher thread."""
        unregister_publish_hook(self.publish_hook)
        self.scan_publish()
        self.flush(timeout=flush_timeout)
        self._closed = True
        self._wake.set()
        t = self._flusher
        if t is not None:
            t.join(timeout=1.0)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "url": self.url,
                "cache_dir": self.cache_dir,
                "fingerprint": self._fp,  # None until first wire use
                "fetched": self._fetched,
                "published": self._published,
                "compiled_local": self._compiled_local,
                "degraded": self._degraded,
                "degraded_total": self._degraded_total,
                "pending_publish": len(self._pending),
            }


def main(argv=None) -> int:
    """Standalone service: ``python -m gentun_tpu.distributed.compile_service``."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m gentun_tpu.distributed.compile_service",
        description="fleet-wide compiled-executable cache service "
                    "(point workers at it with --compile-cache-url)")
    ap.add_argument("--host", default="127.0.0.1",
                    help="bind address (default 127.0.0.1; the endpoints "
                         "are unauthenticated — bind a routable address "
                         "only on a trusted network)")
    ap.add_argument("--port", type=int, default=9737,
                    help="listen port (0 picks an ephemeral port, logged)")
    ap.add_argument("--max-bytes", type=int, default=1 * 1024 * 1024 * 1024,
                    help="byte budget before cold artifacts evict "
                         "(default 1 GiB)")
    args = ap.parse_args(argv)
    if not 0 <= args.port <= 65535:
        raise SystemExit(f"--port must be in [0, 65535], got {args.port}")
    if args.max_bytes <= 0:
        raise SystemExit(f"--max-bytes must be positive, got {args.max_bytes}")
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    svc = CompileService(host=args.host, port=args.port,
                         max_bytes=args.max_bytes).start()
    print(f"compile service on {svc.url} (ctrl-C to stop)", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        svc.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
