"""GA outer loops: generational evolution with selection/elitism.

Reference parity: ``GeneticAlgorithm`` and ``RussianRouletteGA`` in
``gentun/algorithms.py`` [PUB] (SURVEY.md §2.0 rows 2-3, §3.1).  The outer
loop is deliberately identical in shape to the reference — evaluate the
population, log the fittest, select parents, reproduce into the next
generation — because the north star keeps it "untouched" (BASELINE.json).

What's new versus the reference:

- explicit seeded RNG (reproducible searches),
- structured per-generation records including the north-star metric,
  individuals evaluated per hour (SURVEY.md §5 "Metrics"),
- optional generation-boundary checkpointing (SURVEY.md §5
  "Checkpoint / resume" — absent in the reference, required by the rebuild).
"""

from __future__ import annotations

import json
import logging
import time
from typing import Any, Dict, List, Optional

import numpy as np

from .individuals import Individual
from .populations import Population
from .telemetry import health as _health
from .telemetry import lineage as _lineage
from .telemetry import spans as _tele
from .telemetry.registry import get_registry as _get_registry
from .utils.fitness_store import FITNESS_PROTOCOL, is_serializable_key, tuplify

__all__ = ["GeneticAlgorithm", "RussianRouletteGA"]

logger = logging.getLogger("gentun_tpu")


def _initialized_chip_count() -> int:
    """Local accelerator count, WITHOUT triggering jax backend init.

    The GA outer loop is pure bookkeeping; it must not pay (or hang on) TPU
    runtime initialization just to normalise a metric.  Only consult jax when
    the fitness path has already initialized a backend.
    """
    import sys

    if "jax" not in sys.modules:
        return 1
    from .utils.jax_state import backend_used

    if not backend_used():  # backend never initialized: don't force it
        return 1
    try:
        return sys.modules["jax"].local_device_count()
    except Exception:  # pragma: no cover - defensive
        return 1


class GeneticAlgorithm:
    """Tournament-selection GA with elitism (gentun ``GeneticAlgorithm`` [PUB]).

    Per generation: evaluate every individual (lazily/cached), keep the best
    unchanged if ``elitism``, then fill the next generation with children of
    tournament-selected parents (sample ``tournament_size`` members, fittest
    wins — SURVEY.md §2.3 "Selection").

    ``breed_ahead`` (off by default; trajectories are bit-identical when
    off): as soon as a generation is bred, pre-dispatch its cache-missed
    children to the fleet (``Population.predispatch``) so workers' prefetch
    queues refill during the master's checkpoint/log window instead of
    sitting idle across the generation boundary — the generational half of
    the pipelined dispatch plane (DISTRIBUTED.md "Pipelined dispatch").
    The next ``evaluate()`` adopts the in-flight jobs; selection order,
    RNG draws, and fitness values are unchanged either way, because the
    generational trajectory is completion-order independent.  A no-op for
    local populations.
    """

    def __init__(
        self,
        population: Population,
        tournament_size: int = 5,
        elitism: bool = True,
        seed: Optional[int] = None,
        breed_ahead: bool = False,
    ):
        self.population = population
        self.tournament_size = tournament_size
        self.elitism = elitism
        self.breed_ahead = bool(breed_ahead)
        self.rng = np.random.default_rng(seed) if seed is not None else population.rng
        self.generation = 0
        self.history: List[Dict[str, Any]] = []
        self._checkpointer = None
        self._fault_injector = None

    # -- checkpointing hook (wired by utils.checkpoint) --------------------

    def set_checkpointer(self, checkpointer) -> None:
        """Attach a generation-boundary checkpointer (``utils/checkpoint.py``)."""
        self._checkpointer = checkpointer

    def set_fault_injector(self, injector) -> None:
        """Attach a chaos-testing injector (``distributed/faults.py``).

        Its only master-side hook is ``master_boundary``, fired AFTER the
        generation checkpoint is written — a ``kill_master`` fault therefore
        simulates a crash at the exact point resume is guaranteed from.
        """
        self._fault_injector = injector

    # -- selection ---------------------------------------------------------

    def select_parent(self) -> Individual:
        """Tournament selection: sample t individuals, fittest wins."""
        with _tele.span("select"):
            size = len(self.population)
            t = min(self.tournament_size, size)
            idx = self.rng.choice(size, size=t, replace=False)
            contenders = [self.population[int(i)] for i in idx]
            key = lambda ind: ind.get_fitness()
            return max(contenders, key=key) if self.population.maximize else min(contenders, key=key)

    # -- evolution ---------------------------------------------------------

    def evolve_population(self) -> None:
        """One generation step: evaluate → select → reproduce (SURVEY.md §3.1).

        Telemetry: the whole step is a ``generation`` span; ``evaluate``,
        ``select`` (inside :meth:`select_parent`), ``reproduce``, and
        ``checkpoint`` nest under it.  The evaluate span is live while job
        payloads are built, so its context is what rides the wire to
        workers (``DistributedPopulation._evaluate_once``).
        """
        # Advisory heartbeat for /statusz (one bool read when the ops plane
        # is off): a generation legitimately takes unbounded time, so this
        # never gates /healthz — it tells an operator when the engine last
        # crossed a generation boundary.
        _health.beat("engine_loop")
        with _tele.span("generation", {"generation": self.generation}):
            t0 = time.monotonic()
            # Count only the individuals actually trained this step (cached
            # elites, fitness-cache hits, and dedup'd duplicates don't inflate
            # the metric): evaluate() reports exactly how many hit the
            # compute path.
            with _tele.span("evaluate"):
                evaluated = self.population.evaluate() or 0
                fittest = self.population.get_fittest()
            elapsed = max(time.monotonic() - t0, 1e-9)
            self._log_generation(fittest, evaluated, elapsed)

            next_individuals: List[Individual] = []
            if self.elitism:
                next_individuals.append(fittest.copy())  # keeps cached fitness
            with _tele.span("reproduce"):
                lin = _lineage.enabled()
                while len(next_individuals) < len(self.population):
                    mother = self.select_parent()
                    father = self.select_parent()
                    child = mother.reproduce(father, self.rng)
                    if lin:
                        _lineage.record(
                            "born", _lineage.genome_key(child.get_genes()),
                            parents=[
                                _lineage.genome_key(mother.get_genes()),
                                _lineage.genome_key(father.get_genes()),
                            ],
                            op="reproduce",
                            generation=self.generation + 1,
                            genes=child.get_genes())
                    next_individuals.append(child)

            # clone_with keeps the population's concrete type across
            # generations (a DistributedPopulation must carry its broker
            # forward).
            self.population = self.population.clone_with(next_individuals)
            if self.breed_ahead:
                # Ship the freshly-bred generation's jobs BEFORE the
                # checkpoint/log bookkeeping below: the wire time and the
                # workers' decode overlap work the master was going to do
                # anyway.  Resume safety: a pre-dispatch is never
                # checkpointed — a resumed run's evaluate() simply
                # re-submits fresh jobs (at-least-once, dedup on cache key).
                with _tele.span("predispatch"):
                    self.population.predispatch()
            self.generation += 1
            if self._checkpointer is not None:
                with _tele.span("checkpoint"):
                    self._checkpointer.save(self)
            if self._fault_injector is not None:
                # After the checkpoint: a kill here is the recoverable crash.
                self._fault_injector.master_boundary(self.generation)

    def run(self, max_generations: int, checkpointer=None) -> Individual:
        """Run the search; returns the final fittest individual.

        Matches the reference's entry point
        ``GeneticAlgorithm(population).run(n)`` (SURVEY.md §3.1):
        ``max_generations`` means "N more generations from here".

        With ``checkpointer`` (a ``utils/checkpoint.Checkpointer``), run
        becomes crash-resumable: the checkpointer is attached, any existing
        checkpoint is resumed first, and ``max_generations`` is the TOTAL
        generation count for the search — a master killed at generation k
        and re-run with the same arguments executes the remaining
        ``max_generations - k`` and produces the identical trajectory.
        """
        if checkpointer is not None:
            self.set_checkpointer(checkpointer)
            if checkpointer.resume(self):
                logger.info("resumed from checkpoint at generation %d", self.generation)
        remaining = max_generations - self.generation if checkpointer is not None else max_generations
        logger.info(
            "starting %s: population=%d, generations=%d",
            type(self).__name__,
            len(self.population),
            remaining,
        )
        # One root span per run → one trace_id stitching every generation
        # (and, via payload propagation, every worker span) together.
        # Engine status is keyed by search session (multi-tenant brokers:
        # N engines sharing a fleet each get a /statusz row instead of
        # last-writer-wins); single-tenant runs key under "default".
        self._status_session = getattr(self.population, "session", None) or "default"
        _health.register_engine_status(self._status_session, self._ops_status)
        try:
            with _tele.span("run", {"generations": max(remaining, 0)}) as run_span:
                # /statusz "active trace_id": the no-op span has no
                # trace_id attribute, so this stays None when disabled.
                self._run_trace_id = getattr(run_span, "trace_id", None)
                for _ in range(max(remaining, 0)):
                    self.evolve_population()
                # The final offspring still need fitness; give the pass its
                # own evaluate span so its worker spans parent consistently.
                with _tele.span("evaluate"):
                    self.population.evaluate()
                    best = self.population.get_fittest()
        finally:
            _health.unregister_engine_status(self._status_session, self._ops_status)
            # End-of-run fleet push: the final generation's counters reach
            # the aggregator even if the caller keeps the population open.
            # No-op (an empty-dict read) when nothing is wired.
            from .telemetry.aggregator import flush_active_pushers

            flush_active_pushers()
        logger.info("search done: best fitness %.6g, genes %s", best.get_fitness(), best.get_genes())
        return best

    def _ops_status(self) -> Dict[str, Any]:
        """The ``/statusz`` "engine" block (``telemetry/health.py`` status
        provider, polled from HTTP threads — snapshot reads only)."""
        # Ever-best across the whole run, not just the last generation —
        # without elitism a generation's best can regress.
        fits = [h["best_fitness"] for h in self.history
                if h.get("best_fitness") is not None]
        best = None
        if fits:
            best = max(fits) if self.population.maximize else min(fits)
        return {
            "mode": "generational",
            "session": getattr(self, "_status_session", "default"),
            "generation": self.generation,
            "population_size": len(self.population),
            "best_fitness": best,
            "trace_id": getattr(self, "_run_trace_id", None),
        }

    # -- logging -----------------------------------------------------------

    def _log_generation(self, fittest: Individual, evaluated: int, elapsed_s: float) -> None:
        # Distributed sweeps record the connected fleet's advertised chip
        # total (workers' `hello` → broker.fleet_chips()); that is the true
        # denominator for the per-chip metric — the master process itself
        # never initializes jax, so its local count would always be 1.
        stats = getattr(self.population, "eval_stats", None) or {}
        n_chips = int(stats.get("n_chips") or 0) or _initialized_chip_count()
        record = {
            "generation": self.generation,
            "best_fitness": fittest.get_fitness(),
            "best_genes": fittest.get_genes(),
            "population_size": len(self.population),
            "evaluated": int(evaluated),  # individuals that actually trained
            "eval_wall_s": round(elapsed_s, 3),
            "n_chips": n_chips,
            # the north-star metric (BASELINE.json): individuals/hour/chip
            "individuals_per_hour_per_chip": round(evaluated / (elapsed_s / 3600.0) / n_chips, 2),
        }
        # Search-progress gauges for the fleet dashboard (once per
        # generation — off the dispatch hot path; always-on like the mesh
        # gauges so an aggregator-wired master reports progress even with
        # span telemetry off).
        sess = (getattr(self, "_status_session", None)
                or getattr(self.population, "session", None) or "default")
        reg = _get_registry()
        reg.gauge("engine_generation", session=sess,
                  mode="generational").set(self.generation)
        fit = fittest.get_fitness()
        if fit is not None:
            reg.gauge("engine_best_fitness", session=sess,
                      mode="generational").set(float(fit))
        # Distributed populations report their failure-recovery bookkeeping
        # (bounded retries / penalized stragglers) — record it so a resumed
        # or audited search can see exactly which generations degraded.
        if stats and (stats.get("retries") or stats.get("penalized")):
            record["evaluate_attempts"] = stats["attempts"]
            record["evaluate_retries"] = stats["retries"]
            record["penalized"] = stats["penalized"]
        self.history.append(record)
        logger.info("generation %s", json.dumps(record, default=str))

    # -- (de)serialization state for checkpoint/resume ---------------------

    def state_dict(self) -> Dict[str, Any]:
        # Fitness-cache keys are nested tuples, usually of JSON-native
        # leaves (Individual.cache_key); JSON turns tuples into lists and
        # tuplify() reverses that exactly on load (the shared convention —
        # utils/fitness_store.py).  Unserializable keys are skipped: the
        # checkpoint must never crash the search over a cache entry, and a
        # dropped entry only costs a retrain after resume.
        fitness_cache = [
            [k, v]
            for k, v in self.population.fitness_cache.items()
            if is_serializable_key(k)
        ]
        return {
            "algorithm": type(self).__name__,
            "fitness_protocol": FITNESS_PROTOCOL,
            "fitness_cache": fitness_cache,
            "generation": self.generation,
            "tournament_size": self.tournament_size,
            "elitism": self.elitism,
            "breed_ahead": self.breed_ahead,
            "rng_state": self.rng.bit_generator.state,
            "history": self.history,
            "population": {
                "maximize": self.population.maximize,
                "crossover_rate": self.population.crossover_rate,
                "mutation_rate": self.population.mutation_rate,
                "additional_parameters": self.population.additional_parameters,
                "individuals": [
                    {
                        "genes": ind.get_genes(),
                        "fitness": ind._fitness,
                    }
                    for ind in self.population
                ],
            },
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        algo = state.get("algorithm")
        if algo == "AsyncEvolution":
            raise ValueError(
                "checkpoint was written by AsyncEvolution — steady-state "
                "scheduler state (completion counters, in-flight children) "
                "has no generational equivalent; resume it with AsyncEvolution")
        self.generation = int(state["generation"])
        self.tournament_size = int(state["tournament_size"])
        self.elitism = bool(state["elitism"])
        if "breed_ahead" in state:  # absent in pre-pipelining checkpoints
            self.breed_ahead = bool(state["breed_ahead"])
        self.rng.bit_generator.state = state["rng_state"]
        self.history = list(state["history"])
        pop_state = state["population"]
        # Restore population config BEFORE spawning, so individuals are built
        # with the checkpoint's genome spec and operator rates, not whatever
        # config the receiving population happened to be constructed with.
        self.population.maximize = bool(pop_state["maximize"])
        self.population.crossover_rate = float(pop_state["crossover_rate"])
        self.population.mutation_rate = float(pop_state["mutation_rate"])
        self.population.additional_parameters = dict(pop_state["additional_parameters"])
        # A checkpoint written under an older fitness-measurement RNG
        # protocol carries values a resumed search cannot compare against
        # fresh ones (utils/fitness_store.FITNESS_PROTOCOL): drop every
        # stored fitness — genes, RNG state, and history survive, the
        # current population re-measures.  Loud: re-measuring costs real
        # chip time and the user should know why.
        proto = state.get("fitness_protocol", 1)
        proto_ok = proto == FITNESS_PROTOCOL
        if not proto_ok:
            logger.warning(
                "checkpoint was written under fitness RNG protocol %s "
                "(current: %s); discarding its fitness values and cache — "
                "the resumed search re-measures the current generation "
                "instead of mixing incomparable measurements", proto,
                FITNESS_PROTOCOL,
            )
        individuals = []
        for ind_state in pop_state["individuals"]:
            ind = self.population.spawn(genes=ind_state["genes"])
            if ind_state["fitness"] is not None and proto_ok:
                ind.set_fitness(ind_state["fitness"])
            individuals.append(ind)
        self.population.individuals = individuals
        restored = {
            tuplify(key): float(fit) for key, fit in state.get("fitness_cache", [])
        } if proto_ok else {}
        # A ServiceBackedCache (distributed/fitness_service.py) must keep its
        # shared-service backing across resume; rebase() swaps contents in
        # place instead of being replaced by a plain dict.
        cache = self.population.fitness_cache
        if hasattr(cache, "rebase"):
            cache.rebase(restored)
        else:
            self.population.fitness_cache = restored


class RussianRouletteGA(GeneticAlgorithm):
    """Fitness-proportional (roulette) selection, per the Genetic-CNN paper.

    gentun ``RussianRouletteGA`` [BASELINE names it; PUB for mechanism]
    (SURVEY.md §2.0 row 3).  Parents are drawn with probability proportional
    to fitness (shifted to be positive; inverted when minimising), instead of
    by tournament.

    ``selection_floor`` (VERDICT r4 weak #5 — a DOCUMENTED deviation knob,
    see docs/ARCHITECTURE.md "Roulette selection floor"): the default 0.1
    range-shifts the weights so the generation's worst member keeps a
    non-zero selection chance — without it, range-normalised weights give
    the worst member probability exactly 0 every generation, which is
    effectively an extra deterministic truncation step the paper doesn't
    have.  ``selection_floor=None`` selects the EXACT paper behavior:
    weights proportional to the raw (positive) fitness values — for
    accuracy-valued fitness in [0, 1] the spread between members is small
    relative to the mean, so exact-proportional selection pressure is far
    weaker than the floored range-shifted variant, not stronger.
    """

    def __init__(self, *args, selection_floor: Optional[float] = 0.1, **kwargs):
        super().__init__(*args, **kwargs)
        if selection_floor is not None and selection_floor < 0:
            raise ValueError(f"selection_floor must be >= 0 or None, got {selection_floor}")
        if selection_floor is None and not self.population.maximize:
            # p ∝ f is meaningless for losses (negated fitnesses are all
            # negative, so every generation would hit the degenerate shift
            # that zeroes the worst member — the opposite of what the exact
            # mode advertises).
            raise ValueError(
                "selection_floor=None (exact p ∝ f roulette) requires a "
                "maximizing population with positive fitnesses; use a "
                "numeric floor when minimizing"
            )
        self.selection_floor = selection_floor

    def _selection_weights(self) -> np.ndarray:
        # Fitnesses are fixed during the reproduction loop, so the weight
        # vector is reused across the ~2N parent draws of a generation; the
        # weights are a pure function of the fitness values, so those alone
        # key the cache (in-place set_fitness() changes them and invalidates).
        fit_list = self.population.get_fitnesses()
        key = tuple(fit_list)
        cached = getattr(self, "_weights_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        fits = np.asarray(fit_list, dtype=np.float64)
        if not self.population.maximize:
            fits = -fits
        lo, hi = fits.min(), fits.max()
        if hi == lo:
            weights = np.full(len(fits), 1.0 / len(fits))
        elif self.selection_floor is None:
            # Exact paper roulette: p_i ∝ fitness_i.  Defined for positive
            # fitness (the paper's recognition accuracies); anything else
            # falls back to the minimal shift that makes weights valid.
            if lo <= 0:
                if not getattr(self, "_warned_nonpositive", False):
                    self._warned_nonpositive = True
                    logger.warning(
                        "exact roulette (selection_floor=None) needs positive "
                        "fitnesses; min is %.6g — shifting by it (warned once)", lo,
                    )
                fits = fits - lo
            weights = fits / fits.sum()
        else:
            # Range-shift so the worst member keeps a small non-zero chance.
            shifted = fits - lo + self.selection_floor * (hi - lo)
            weights = shifted / shifted.sum()
        self._weights_cache = (key, weights)
        return weights

    def select_parent(self) -> Individual:
        with _tele.span("select"):
            weights = self._selection_weights()
            idx = int(self.rng.choice(len(self.population), p=weights))
            return self.population[idx]

    # selection_floor must ride checkpoints like its sibling hyperparams
    # (tournament_size, elitism): an exact-paper (None) study must not
    # silently resume with the default floored selection.

    def state_dict(self) -> Dict[str, Any]:
        state = super().state_dict()
        state["selection_floor"] = self.selection_floor
        return state

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        super().load_state_dict(state)
        if "selection_floor" in state:
            self.selection_floor = state["selection_floor"]
        # The weights cache is keyed on the fitness tuple alone; a restored
        # floor must not serve weights computed under the old one.
        self._weights_cache = None
