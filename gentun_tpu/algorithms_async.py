"""Asynchronous steady-state evolution: the generation barrier, removed.

The generational loop (``algorithms.py``) evaluates a whole population,
waits at a barrier, then breeds the next generation — so a fleet is only
busy while a generation is wide, and the converged tail (1-4 fresh
individuals per generation, PERF.md "Tail generations") pays a
program-switch + dispatch + RPC floor per generation while most worker
capacity idles.

:class:`AsyncEvolution` replaces the barrier with *regularized evolution*
(Real et al. 2019, "Regularized Evolution for Image Classifier Architecture
Search") driven by a completion loop in the barrier-free worker style of
population-based training (Jaderberg et al. 2017):

- a bounded, age-ordered population (the *ring*): youngest appended,
  oldest **evicted by age** — never by fitness — each time a child joins;
- **aging tournament selection**: parents are the fittest of a uniform
  sample of evaluated ring members;
- a configurable number of evaluations (default: the fleet's total
  capacity) stays in flight at all times — every completed evaluation
  immediately breeds and dispatches a replacement child, so the fleet
  stays busy through the tail.

The engine is mode-agnostic: a data-holding :class:`Population` evaluates
on a local thread pool; a ``DistributedPopulation`` uses the broker's
completion-driven API (``wait_any``) with one coalesced submit per wake-up.
Canonical-dedup and fitness-store reuse apply at dispatch: a child whose
``cache_key`` is already measured completes instantly without occupying a
worker slot, and a child identical to one already in flight attaches to it
as a *follower* instead of training twice.

Determinism: the engine consumes randomness only from its own generator,
and every breeding decision is driven by the completion stream — with a
deterministic completion order (one in-flight slot, or a single capacity-1
worker) the whole trajectory is a pure function of the seed, checkpoints
included.  The generational mode is untouched: ``GeneticAlgorithm`` remains
the default and stays bit-identical.

Multi-fidelity (``fidelity_ladder=``): asynchronous successive halving
(ASHA, Li et al. 2020) layered onto the same completion loop.  The ladder
is a list of ``additional_parameters`` overlays, rung 0 (cheap proxy
schedule) to the top (full schedule); every child is dispatched at rung 0
and, once a rung has seen ``eta`` completions per promotion slot, its
top-``1/eta`` ring members are promoted — a *promotion probe* (same
genes, next rung's overlay) rides the ordinary dispatch path, so rungs
never barrier and a straggling promotion never blocks breeding.  When a
probe lands, the member's fitness is replaced in place by the
higher-fidelity measurement (selection therefore always compares each
member at its highest completed rung) and the proxy/full results live
under disjoint fitness-cache keys (the overlay is part of the key).
``fidelity_ladder=None`` (default) is the pre-ladder engine, bit for bit.
See DISTRIBUTED.md "Multi-fidelity evolution".

Surrogate rung −1 (``surrogate=``): a :class:`~gentun_tpu.surrogate.SurrogateGate`
threads a host-side learned ranker UNDER the ladder — every bred child is
scored before dispatch and only the top ``1/eta`` fraction of recent
scores enters rung 0; a rejected child is recorded (``gate_rejected``
lineage event + counter) and immediately replaced by re-breeding, so the
in-flight target stays saturated and rejected children never consume
budget.  The gate feeds on every completion, refits periodically, and
serializes into checkpoint schema v4 (model + window + pending
decisions), so kill/resume mid-gate is bit-identical.  ``surrogate=None``
(default) is the ungated engine, bit for bit — the sites read one
attribute.  See DISTRIBUTED.md "Surrogate rung −1".
"""

from __future__ import annotations

import itertools
import logging
import queue as _queue
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from .individuals import Individual
from .populations import Population
from .surrogate import SurrogateGate
from .telemetry import health as _health
from .telemetry import lineage as _lineage
from .telemetry import spans as _tele
from .telemetry.registry import get_registry as _get_registry
from .utils.fitness_store import (
    FITNESS_PROTOCOL,
    fidelity_fingerprint,
    is_serializable_key,
    tuplify,
)

__all__ = ["AsyncEvolution"]

logger = logging.getLogger("gentun_tpu")

#: event tuple: (token, fitness-or-None, error-reason-or-None)
_Event = Tuple[Any, Optional[float], Optional[str]]


class _Work:
    """One owed evaluation, as the scheduler tracks it.

    ``ind`` is the individual actually shipped (it carries the rung's
    config overlay).  ``is_member`` marks unevaluated RING members being
    measured in place (the pre-ladder cohort path).  ``target`` — when not
    None — is the ring member this result belongs to: promotion probes and
    ladder-mode cohort probes evaluate a config-overlaid twin of the
    member, then write the fitness back to the member itself.
    """

    __slots__ = ("ind", "is_member", "rung", "target")

    def __init__(self, ind: Individual, is_member: bool, rung: int = 0,
                 target: Optional[Individual] = None):
        self.ind = ind
        self.is_member = is_member
        self.rung = int(rung)
        self.target = target


class _LocalEvaluator:
    """Thread-pool evaluation for data-holding populations.

    One worker thread per in-flight slot; completions land on a queue in
    finish order.  With a single thread the executor is FIFO, which is the
    deterministic configuration the seeded-determinism and kill/resume
    tests rely on.
    """

    def __init__(self, n_threads: int):
        self._n = max(1, int(n_threads))
        self._pool = ThreadPoolExecutor(
            max_workers=self._n, thread_name_prefix="gentun-async-eval")
        self._done: _queue.Queue = _queue.Queue()
        self._seq = itertools.count()
        self._futures: Dict[int, Any] = {}

    def default_capacity(self) -> int:
        return self._n

    def live_capacity(self) -> int:
        return self._n  # thread pools don't resize mid-run

    def submit(self, individuals: List[Individual]) -> List[int]:
        tokens = []
        lin = _lineage.enabled()
        for ind in individuals:
            token = next(self._seq)
            fn = self._timed_fitness(ind) if lin else ind.get_fitness
            fut = self._pool.submit(fn)
            fut.add_done_callback(lambda f, t=token: self._done.put((t, f)))
            self._futures[token] = fut
            tokens.append(token)
        return tokens

    @staticmethod
    def _timed_fitness(ind: Individual):
        """Forensics wrapper: attribute the evaluation's device-seconds to
        the genome (docs/OBSERVABILITY.md "Search forensics").  Charged
        even when the evaluation raises — the chip time was spent."""
        def run():
            t0 = time.monotonic()
            try:
                return ind.get_fitness()
            finally:
                if not _tele.capturing():
                    _lineage.emit_device(
                        time.monotonic() - t0,
                        _lineage.genome_key(ind.get_genes()),
                        rung=(getattr(ind, "_fidelity_tag", None)
                              or {}).get("rung", 0),
                        start_monotonic=t0)
        return run

    def wait_any(self, timeout: Optional[float]) -> List[_Event]:
        try:
            token, fut = self._done.get(timeout=timeout)
        except _queue.Empty:
            return []
        events = [self._event(token, fut)]
        while True:  # drain whatever else already finished
            try:
                token, fut = self._done.get_nowait()
            except _queue.Empty:
                return events
            events.append(self._event(token, fut))

    def _event(self, token: int, fut) -> _Event:
        self._futures.pop(token, None)
        if fut.cancelled():
            return (token, None, "cancelled")
        exc = fut.exception()
        if exc is not None:
            return (token, None, repr(exc))
        return (token, float(fut.result()), None)

    def cancel(self, tokens) -> None:
        for t in tokens:
            fut = self._futures.pop(t, None)
            if fut is not None:
                fut.cancel()

    def close(self) -> None:
        try:
            self._pool.shutdown(wait=False, cancel_futures=True)
        except TypeError:  # pragma: no cover - pre-3.9 fallback
            self._pool.shutdown(wait=False)


class _DistributedEvaluator:
    """Completion-driven evaluation through a ``DistributedPopulation``.

    Thin: payload construction and the broker's ``wait_any``/``cancel``
    live on the population (``distributed/server.py``), keeping the wire
    format single-owner.  Tokens are broker job ids.
    """

    def __init__(self, population):
        self._pop = population
        self._open: set = set()

    def default_capacity(self) -> int:
        # Wait briefly for the fleet so "capacity" means the real fleet,
        # not the pre-connect instant — and keep watching after the first
        # worker appears, because its peers are usually mid-handshake: a
        # cap that stops growing for 0.75 s is taken as the fleet.
        deadline = time.monotonic() + 10.0
        cap, last_growth = 0, time.monotonic()
        while time.monotonic() < deadline:
            now = self._pop.fleet_capacity()
            if now > cap:
                cap, last_growth = now, time.monotonic()
            elif cap > 0 and time.monotonic() - last_growth >= 0.75:
                break
            time.sleep(0.05)
        # Breed ahead to the fleet's full dispatch WINDOW — evaluation
        # slots plus the workers' advertised prefetch queues — so every
        # worker always has a decoded next window waiting (the engine half
        # of the pipelined dispatch plane).  A fleet advertising no
        # prefetch yields exactly the old target, keeping prefetch_depth=0
        # trajectories bit-identical.
        prefetch = getattr(self._pop, "fleet_prefetch", lambda: 0)()
        return max(1, cap) + max(0, int(prefetch))

    def live_capacity(self) -> int:
        """Instant dispatch-window read of the CURRENT fleet — no settling
        wait.  0 means "no live workers right now" (drain, crash-reconnect
        gap); the engine keeps its last-known target through that instant
        rather than stalling the refill loop."""
        cap = self._pop.fleet_capacity()
        if cap <= 0:
            return 0
        prefetch = getattr(self._pop, "fleet_prefetch", lambda: 0)()
        return cap + max(0, int(prefetch))

    def submit(self, individuals: List[Individual]) -> List[str]:
        ids = self._pop.submit_individuals(individuals)
        self._open.update(ids)
        return ids

    def wait_any(self, timeout: Optional[float]) -> List[_Event]:
        if not self._open:
            return []
        results, failures = self._pop.wait_any_results(list(self._open), timeout=timeout)
        self._open -= set(results) | set(failures)
        return ([(j, f, None) for j, f in results.items()]
                + [(j, None, r) for j, r in failures.items()])

    def cancel(self, tokens) -> None:
        ids = [t for t in tokens if t in self._open]
        self._open -= set(ids)
        if ids:
            self._pop.cancel_jobs(ids)

    def close(self) -> None:
        pass  # population/broker lifecycle belongs to the caller


class AsyncEvolution:
    """Steady-state aging-tournament evolution without a generation barrier.

    Parameters
    ----------
    population:
        The initial cohort — a :class:`Population` (local evaluation) or a
        ``DistributedPopulation`` (broker-backed).  Its size is the ring's
        bound for the whole search.
    tournament_size:
        Members sampled per parent draw; the fittest wins.
    max_in_flight:
        Evaluations kept in flight at all times.  ``None`` (default)
        resolves at :meth:`run` to the connected fleet's total capacity
        (distributed) or 1 (local).
    seed:
        Seeds the engine's own RNG; ``None`` shares the population's.
    checkpoint_every:
        Completions between checkpoint saves (and ``master_boundary``
        fault hooks) when a checkpointer is attached.
    job_timeout:
        Max seconds to wait for ANY completion before raising — ``None``
        waits forever (the generational default).
    fidelity_ladder:
        ``None`` (default): single-fidelity, the pre-ladder engine bit for
        bit.  Otherwise a sequence of ``additional_parameters`` overlays,
        rung 0 (proxy) → last (full schedule; ``{}`` means "the
        population's own config").  Children dispatch at rung 0; the
        top-``1/eta`` of each rung promote asynchronously.
    eta:
        ASHA reduction factor: one promotion slot per ``eta`` completions
        at a rung.  Ignored without a ladder.
    surrogate:
        ``None`` (default): no rung −1, the engine bit for bit.
        Otherwise a :class:`~gentun_tpu.surrogate.SurrogateGate` that
        scores every bred child on the host before dispatch and admits
        only the top fraction; rejected children are re-bred in place
        (they never occupy a slot or consume budget).  Checkpoints carry
        the gate (schema v4); on resume the checkpoint's gate state wins.
    """

    def __init__(
        self,
        population: Population,
        tournament_size: int = 5,
        max_in_flight: Optional[int] = None,
        seed: Optional[int] = None,
        checkpoint_every: int = 8,
        job_timeout: Optional[float] = None,
        fidelity_ladder: Optional[Sequence[Mapping[str, Any]]] = None,
        eta: int = 4,
        surrogate: Optional[SurrogateGate] = None,
    ):
        self.population = population
        self.tournament_size = int(tournament_size)
        self.max_in_flight = None if max_in_flight is None else max(1, int(max_in_flight))
        self.checkpoint_every = max(1, int(checkpoint_every))
        self.job_timeout = job_timeout
        if fidelity_ladder is not None:
            ladder = [dict(r) for r in fidelity_ladder]
            if not ladder:
                raise ValueError("fidelity_ladder must name at least one rung "
                                 "(use None for single-fidelity)")
            if int(eta) < 2:
                raise ValueError(f"eta must be >= 2 (got {eta}): promoting "
                                 "every completion is not a ladder")
            self._ladder: Optional[List[Dict[str, Any]]] = ladder
        else:
            self._ladder = None
        self.eta = int(eta)
        #: rung −1 — ``None`` is the ungated engine (every site below
        #: reads this one attribute, the PR-2 off-path contract).
        self._surrogate = surrogate
        #: per-rung fitnesses of every completion at that rung, in
        #: completion order — the ASHA promotion quota reads this, so it is
        #: serialized for deterministic resume.
        self._rung_completions: List[List[float]] = (
            [[] for _ in (self._ladder or ())])
        #: per-rung ever-best (copies, like ``best``); ``best`` itself is
        #: the best at the HIGHEST rung with any completion, because proxy
        #: and full-schedule fitnesses are not comparable numbers.
        self._best_by_rung: Dict[int, Individual] = {}
        self.rng = np.random.default_rng(seed) if seed is not None else population.rng
        self.pop_size = len(population)
        self.completed = 0
        self.dispatched = 0
        self.history: List[Dict[str, Any]] = []
        #: copy of the best individual EVER completed — aging eviction may
        #: remove the champion from the ring, so the ring's fittest member
        #: is not the search's answer.
        self.best: Optional[Individual] = None
        self._checkpointer = None
        self._fault_injector = None
        self._last_ckpt = 0
        # Scheduler state (also serialized): children bred/promotions
        # decided and dispatched but not yet completed, in dispatch order —
        # the piece a resumed run must re-dispatch to continue the same
        # trajectory.  Values are _Work records keyed by id(work.ind).
        self._open_children: Dict[int, _Work] = {}
        self._restored_in_flight: List[_Work] = []
        # Run-local maps (rebuilt by run()).
        self._queue: List[_Work] = []
        self._inflight: Dict[Any, _Work] = {}
        self._followers: Dict[Any, List[_Work]] = {}
        self._key_to_token: Dict[Any, Any] = {}
        self._cap = 1
        self._elastic = False
        self._evaluator = None

    # -- hooks (same contract as GeneticAlgorithm) -------------------------

    def set_checkpointer(self, checkpointer) -> None:
        """Attach a completion-boundary checkpointer (``utils/checkpoint.py``)."""
        self._checkpointer = checkpointer

    def set_fault_injector(self, injector) -> None:
        """Attach a chaos injector; ``master_boundary`` fires with the
        completion count, AFTER each checkpoint save — a ``kill_master``
        fault therefore lands exactly where resume is guaranteed from."""
        self._fault_injector = injector

    # -- selection ---------------------------------------------------------

    def select_parent(self) -> Individual:
        """Aging tournament over the ring's evaluated members."""
        with _tele.span("select"):
            members = [i for i in self.population if i.fitness_evaluated]
            t = min(self.tournament_size, len(members))
            idx = self.rng.choice(len(members), size=t, replace=False)
            contenders = [members[int(i)] for i in idx]
            key = lambda ind: ind.get_fitness()
            return max(contenders, key=key) if self.population.maximize else min(contenders, key=key)

    # -- the completion loop -----------------------------------------------

    def run(self, max_evaluations: int, checkpointer=None) -> Individual:
        """Run until ``max_evaluations`` evaluations completed (TOTAL, like
        the generational ``run`` under a checkpointer: the initial cohort
        counts, cache-answered children count, permanently failed
        evaluations count — the budget is completions, so the loop always
        terminates).  Returns a copy of the best individual ever measured.

        With ``checkpointer``, the run is crash-resumable: any existing
        checkpoint is restored first (ring, RNG state, history, best, and
        the children that were in flight), and a killed master re-run with
        the same arguments continues the search — deterministically, when
        the completion order is deterministic (see the module docstring).
        """
        if checkpointer is not None:
            self.set_checkpointer(checkpointer)
            if checkpointer.resume(self):
                logger.info("resumed async search at %d completion(s)", self.completed)
        budget = int(max_evaluations)
        evaluator = self._make_evaluator()
        self._evaluator = evaluator
        cap = self.max_in_flight
        # An explicit max_in_flight pins the target; None means "track the
        # fleet" — resolved once here (with the settling wait) and then
        # re-read every wake-up so the in-flight target follows workers
        # joining, draining, and re-advertising mid-run.
        self._elastic = cap is None
        if cap is None:
            cap = evaluator.default_capacity()
        self._cap = max(1, int(cap))
        self._last_ckpt = self.completed
        # Everything whose evaluation is owed but not running: unevaluated
        # ring members first (initial cohort / in-flight-at-kill members),
        # then checkpointed in-flight children/promotions in dispatch
        # order.  With a ladder, cohort members are measured through a
        # rung-0 probe (same genes, proxy overlay) so the whole search —
        # founders included — starts at proxy fidelity.
        self._queue = []
        for ind in self.population:
            if ind.fitness_evaluated:
                continue
            if self._ladder is None:
                self._queue.append(_Work(ind, True))
            else:
                probe = self.population.spawn(
                    genes=ind.get_genes(), additional_parameters=self._ladder[0])
                self._queue.append(_Work(probe, False, rung=0, target=ind))
        self._queue += self._restored_in_flight
        self._restored_in_flight = []
        self._inflight = {}
        self._followers = {}
        self._key_to_token = {}
        self._open_children = {}
        # Re-dispatch re-counts the queued work (members and restored
        # children alike), so the budget gate stays consistent on resume.
        self.dispatched = self.completed
        logger.info(
            "starting AsyncEvolution: ring=%d, budget=%d (%d done), in-flight target=%d",
            self.pop_size, budget, self.completed, self._cap,
        )
        self._status_session = getattr(self.population, "session", None) or "default"
        if self._surrogate is not None:
            # Bind the gate to this search (objective direction, per-tenant
            # dataset space, warm-start).  Idempotent — a resumed gate
            # (checkpoint carried ``prepared``) skips the refetch.
            self._surrogate.prepare(
                self.population.individuals[0].get_genes(),
                self.population.maximize, session=self._status_session)
        _health.register_engine_status(self._status_session, self._ops_status)
        with _tele.span("run", {"mode": "async", "budget": budget,
                                "max_in_flight": self._cap}) as run_span:
            # /statusz "active trace_id" (None while telemetry is off —
            # the no-op span has no trace_id attribute).
            self._run_trace_id = getattr(run_span, "trace_id", None)
            try:
                self._refill(evaluator, budget)
                while self.completed < budget and (self._inflight or self._queue):
                    # Advisory /statusz beat: one bool read when the ops
                    # plane is off.  Never gates /healthz — a wake-up can
                    # legitimately be an evaluation-time apart.
                    _health.beat("engine_loop")
                    events = evaluator.wait_any(self.job_timeout)
                    if not events:
                        raise TimeoutError(
                            f"no evaluation completed within {self.job_timeout}s "
                            f"({len(self._inflight)} in flight, "
                            f"{self.completed}/{budget} done)")
                    for token, fitness, error in events:
                        self._on_event(token, fitness, error)
                    if self._elastic:
                        # Elastic fleet: follow live membership.  A 0 read
                        # is a transient (every worker mid-reconnect or
                        # draining) — keep the last-known target so the
                        # refill gate doesn't collapse to zero and wedge.
                        live = evaluator.live_capacity()
                        if live > 0 and live != self._cap:
                            logger.info(
                                "in-flight target %d -> %d (fleet resized)",
                                self._cap, live)
                            self._cap = live
                    self._refill(evaluator, budget)
                    self._boundary()
            finally:
                _health.unregister_engine_status(self._status_session, self._ops_status)
                leftover = list(self._inflight)
                if leftover:
                    # Budget reached with children still training: their
                    # results are unwanted — withdraw instead of waiting.
                    evaluator.cancel(leftover)
                    for token in leftover:
                        work = self._inflight.pop(token)
                        self._open_children.pop(id(work.ind), None)
                        if work.target is not None:
                            work.target._promo_pending = False
                    self._key_to_token = {}
                    self._followers = {}
                for work in self._queue:
                    if work.target is not None:
                        work.target._promo_pending = False
                self._evaluator = None
                evaluator.close()
                # End-of-run fleet push (no-op when nothing is wired):
                # the final completion counters reach the aggregator.
                from .telemetry.aggregator import flush_active_pushers

                flush_active_pushers()
        if self.best is None:
            raise RuntimeError("no evaluation ever completed successfully")
        logger.info(
            "async search done: %d completion(s), best fitness %.6g, genes %s",
            self.completed, self.best.get_fitness(), self.best.get_genes(),
        )
        return self.best

    def _ops_status(self) -> Dict[str, Any]:
        """The ``/statusz`` "engine" block while an async search runs
        (``telemetry/health.py`` status provider; snapshot reads only —
        ``self.best`` is replaced wholesale, never mutated in place)."""
        best = self.best
        status = {
            "mode": "async",
            "session": getattr(self, "_status_session", "default"),
            "completed": self.completed,
            "dispatched": self.dispatched,
            "in_flight": len(self._inflight),
            "in_flight_target": self._cap,
            "queued": len(self._queue),
            "ring_size": self.pop_size,
            "best_fitness": best.get_fitness() if best is not None else None,
            "trace_id": getattr(self, "_run_trace_id", None),
        }
        if self._ladder is not None:
            # Per-rung ladder snapshot (docs/OBSERVABILITY.md): how far up
            # the fidelity ladder the search has climbed, at a glance.
            pending = [0] * len(self._ladder)
            for w in list(self._queue) + list(self._inflight.values()):
                if w.target is not None and w.rung < len(pending):
                    pending[w.rung] += 1
            status["rungs"] = [
                {
                    "rung": r,
                    "completions": len(self._rung_completions[r]),
                    "best_fitness": (self._best_by_rung[r].get_fitness()
                                     if r in self._best_by_rung else None),
                    "probes_pending": pending[r],
                }
                for r in range(len(self._ladder))
            ]
        if self._surrogate is not None:
            status["surrogate"] = self._surrogate.status()
        return status

    # -- internals ---------------------------------------------------------

    def _make_evaluator(self):
        if hasattr(self.population, "broker"):
            return _DistributedEvaluator(self.population)
        return _LocalEvaluator(self.max_in_flight or 1)

    def _can_breed(self) -> bool:
        return any(i.fitness_evaluated for i in self.population)

    def _breed(self) -> Individual:
        with _tele.span("reproduce"):
            mother = self.select_parent()
            father = self.select_parent()
            child = mother.reproduce(father, self.rng)
            if self._ladder is not None:
                # Every child enters the ladder at the proxy rung: same
                # genes, rung-0 overlay (spawn with explicit genes draws no
                # randomness, so the trajectory stays seed-pure).
                child = self.population.spawn(
                    genes=child.get_genes(),
                    additional_parameters=self._ladder[0])
            if _lineage.enabled():
                _lineage.record(
                    "born", _lineage.genome_key(child.get_genes()),
                    parents=[_lineage.genome_key(mother.get_genes()),
                             _lineage.genome_key(father.get_genes())],
                    op="reproduce", genes=child.get_genes())
            return child

    def _next_child(self) -> Individual:
        """Breed the next dispatchable child — through the surrogate gate
        (rung −1) when one is attached.  A rejected child is recorded
        (``gate_rejected`` lineage event + counter inside the gate) and
        immediately replaced by re-breeding, so the caller always gets a
        child and the in-flight target stays saturated; the gate's
        reject-streak cap bounds the loop.  Rejections happen BEFORE the
        dispatch count, so they never consume budget."""
        child = self._breed()
        gate = self._surrogate
        if gate is None:
            return child
        while True:
            admit, score = gate.decide(child.get_genes(), rung=0)
            if admit:
                return child
            if _lineage.enabled():
                _lineage.record(
                    "gate_rejected", _lineage.genome_key(child.get_genes()),
                    score=score, rung=0)
            child = self._breed()

    def _tag_fidelity(self, work: _Work) -> None:
        """Stamp the wire fidelity tag on an outgoing individual (OPTIONAL
        per-job ``fidelity`` field, see ``distributed/protocol.py``) —
        workers cross-check it against the shipped config before training."""
        if self._ladder is None:
            return
        work.ind._fidelity_tag = {
            "v": 1,
            "rung": work.rung,
            "fingerprint": fidelity_fingerprint(work.ind.additional_parameters),
        }

    def _refill(self, evaluator, budget: int) -> None:
        """Top the in-flight set back up to the target, breeding as needed.

        Children bred in one wake-up ship as ONE submit (one coalesced
        ``jobs`` frame per worker window downstream).  Dispatch-side dedup:
        a child already in the fitness cache (this search or a loaded
        fitness store) completes instantly; a child identical to an
        in-flight job becomes its follower.  Neither occupies a slot, so
        the loop keeps breeding until real work fills the capacity or the
        budget is spent.  Promotion probes queued by completions take
        strict priority over fresh breeding (they are the scarce
        high-fidelity work the ladder exists to schedule).
        """
        tele = _tele.enabled()
        to_submit: List[Tuple[_Work, Any]] = []
        while (self.dispatched < budget
               and len(self._inflight) + len(to_submit) < self._cap):
            if self._queue:
                work = self._queue.pop(0)
            elif self._can_breed():
                work = _Work(self._next_child(), False)
            else:
                break  # nothing evaluated yet: wait for the cohort
            self.dispatched += 1
            key = self.population._safe_cache_key(work.ind)
            cached = self.population.fitness_cache.get(key) if key is not None else None
            if cached is not None:
                if tele:
                    _get_registry().counter(
                        "fitness_cache_hits_total", rung=str(work.rung)).inc()
                if _lineage.enabled():
                    _lineage.record(
                        "cache_hit",
                        _lineage.genome_key(work.ind.get_genes()),
                        source="local", rung=work.rung)
                self._complete(work, float(cached), cached=True)
                continue
            if tele:
                _get_registry().counter(
                    "fitness_cache_misses_total", rung=str(work.rung)).inc()
            token = self._key_to_token.get(key) if key is not None else None
            if token is not None:
                self._followers.setdefault(token, []).append(work)
                self._track_open(work)
                if _lineage.enabled():
                    _lineage.record(
                        "follower_attach",
                        _lineage.genome_key(work.ind.get_genes()),
                        rung=work.rung)
                continue
            to_submit.append((work, key))
        if to_submit:
            for work, _ in to_submit:
                self._tag_fidelity(work)
            tokens = evaluator.submit([w.ind for w, _ in to_submit])
            for token, (work, key) in zip(tokens, to_submit):
                self._inflight[token] = work
                if key is not None:
                    self._key_to_token[key] = token
                self._track_open(work)

    def _track_open(self, work: _Work) -> None:
        """Record dispatched-but-unfinished work the checkpoint must carry.

        Children and PROMOTION probes are serialized (the breeding RNG
        draws / promotion decision behind them are already spent, so a
        resumed run must re-dispatch exactly these).  Ladder-mode COHORT
        probes are not: an unevaluated ring member re-probes from the ring
        state alone.
        """
        if work.is_member:
            return
        if work.target is not None and not work.target.fitness_evaluated:
            return  # cohort probe — reconstructed from the ring on resume
        self._open_children[id(work.ind)] = work

    def _on_event(self, token, fitness: Optional[float], error: Optional[str]) -> None:
        work = self._inflight.pop(token, None)
        if work is None:
            return  # cancelled/stale
        key = self.population._safe_cache_key(work.ind)
        if key is not None and self._key_to_token.get(key) is token:
            del self._key_to_token[key]
        followers = self._followers.pop(token, [])
        if error is not None:
            self._fail(work, error)
            for f in followers:
                self._fail(f, error)
            return
        self._complete(work, fitness)
        for f in followers:
            self._complete(f, fitness)

    def _complete(self, work: _Work, fitness: float, cached: bool = False) -> None:
        """One evaluation finished: membership, cache, best, history,
        and — with a ladder — the ASHA promotion sweep at this rung."""
        ind = work.ind
        if not ind.fitness_evaluated:
            ind.set_fitness(fitness)
        key = self.population._safe_cache_key(ind)
        if key is not None and not cached:
            self.population.fitness_cache[key] = float(fitness)
        self._open_children.pop(id(ind), None)
        if work.target is not None:
            # Probe landing: the measurement belongs to the ring member.
            # A promotion REPLACES the member's lower-rung fitness in
            # place, so tournament selection always compares each member
            # at its highest completed rung.
            member = work.target
            member.set_fitness(float(fitness))
            member._rung = work.rung
            member._promo_pending = False
        elif not work.is_member:
            # Steady-state transition: child in (youngest), oldest out.
            if self._ladder is not None:
                ind._rung = work.rung
            self.population.insert(ind)
            if len(self.population) > self.pop_size:
                evicted = self.population.evict_oldest()
                if evicted is not None:
                    self._cancel_promotions_for(evicted)
                    if _lineage.enabled():
                        _lineage.record(
                            "evicted",
                            _lineage.genome_key(evicted.get_genes()))
        elif self._ladder is not None:
            ind._rung = work.rung
        self._update_best(work, float(fitness))
        self.completed += 1
        if self._surrogate is not None:
            # Every completion trains rung −1 (members, probes, cached and
            # failed-over followers alike) and resolves the child's pending
            # gate decision into the precision@k buffer.
            self._surrogate.observe_result(
                ind.get_genes(), work.rung, float(fitness))
        if _lineage.enabled():
            _lineage.record(
                "completed", _lineage.genome_key(ind.get_genes()),
                fitness=float(fitness), rung=work.rung,
                cached=bool(cached) or None,
                promotion=(work.target is not None and work.rung > 0) or None)
        entry = {
            "completed": self.completed,
            "fitness": float(fitness),
            "best_fitness": self.best.get_fitness(),
            "in_flight": len(self._inflight),
            "cached": bool(cached),
        }
        if self._ladder is not None:
            entry["rung"] = work.rung
            entry["promotion"] = work.target is not None and work.rung > 0
            self._rung_completions[work.rung].append(float(fitness))
            self._maybe_promote(work.rung)
        self.history.append(entry)

    def _update_best(self, work: _Work, fitness: float) -> None:
        maximize = self.population.maximize

        def _better(f, incumbent):
            if incumbent is None:
                return True
            inc = incumbent.get_fitness()
            return f > inc if maximize else f < inc

        if self._ladder is None:
            if _better(fitness, self.best):
                self.best = work.ind.copy()  # keeps the fitness
            return
        # Ladder mode: proxy and full-schedule fitnesses are different
        # quantities — track a best per rung, and expose the best at the
        # highest rung that has completed anything as THE best.
        if _better(fitness, self._best_by_rung.get(work.rung)):
            b = work.ind.copy()
            b.set_fitness(fitness)
            b._rung = work.rung
            self._best_by_rung[work.rung] = b
        self.best = self._best_by_rung[max(self._best_by_rung)]

    def _maybe_promote(self, rung: int) -> None:
        """ASHA promotion sweep after a completion at ``rung``: the rung
        owns ``completions // eta`` promotion slots, of which the sweep
        fills the still-open ones — best ring member first, and only with
        members whose fitness makes the top-``quota`` cut.  Filling at
        most the open slots is what keeps the rung sizes geometric
        (≈ 1/eta of the rung below); the cut alone would over-promote,
        because ring turnover keeps producing members above a historical
        threshold.  No barrier: the sweep never waits for stragglers, it
        only reads what has already completed (Li et al. 2020, §3.1)."""
        if self._ladder is None or rung + 1 >= len(self._ladder):
            return
        vals = self._rung_completions[rung]
        quota = len(vals) // self.eta
        if quota <= 0:
            return
        # Promotions already spent from this rung: completions at rung+1
        # (everything above rung 0 got there only by promotion) plus probes
        # still queued or training.  Derived, not counted — so a cancelled
        # or failed probe refunds its slot automatically and a resumed
        # checkpoint reconstructs the same number from the same state.
        spent = len(self._rung_completions[rung + 1]) + sum(
            1 for w in list(self._queue) + list(self._inflight.values())
            if w.target is not None and w.rung == rung + 1)
        open_slots = quota - spent
        if open_slots <= 0:
            return
        cut = sorted(vals, reverse=self.population.maximize)[quota - 1]
        candidates = []
        for member in list(self.population):
            if getattr(member, "_rung", None) != rung:
                continue
            if getattr(member, "_promo_pending", False):
                continue
            if getattr(member, "_promo_failed_rung", None) == rung + 1:
                continue  # its probe failed permanently — no retry loop
            if not member.fitness_evaluated:
                continue
            f = member.get_fitness()
            if (f < cut) if self.population.maximize else (f > cut):
                continue
            candidates.append(member)
        # Best-first within the open slots (stable sort → ring order breaks
        # ties deterministically).
        candidates.sort(key=lambda m: m.get_fitness(),
                        reverse=self.population.maximize)
        tele = _tele.enabled()
        lin = _lineage.enabled()
        for member in candidates[:open_slots]:
            probe = self.population.spawn(
                genes=member.get_genes(),
                additional_parameters=self._ladder[rung + 1])
            member._promo_pending = True
            self._queue.append(_Work(probe, False, rung=rung + 1, target=member))
            if tele:
                _get_registry().counter(
                    "promotions_total", rung=str(rung + 1)).inc()
            if lin:
                _lineage.record(
                    "promoted", _lineage.genome_key(member.get_genes()),
                    from_rung=rung, to_rung=rung + 1)

    def _cancel_promotions_for(self, member: Individual) -> None:
        """Withdraw any queued or in-flight promotion probe targeting an
        evicted member: its result could no longer join the ring, and an
        abandoned in-flight probe would leak a ``jobs_in_flight`` slot.
        The broker's cancel restores the worker's credit; the dispatch
        count is retracted so the budget still measures completions."""
        if self._ladder is None or not getattr(member, "_promo_pending", False):
            return
        # Queued probes were never dispatched — dropping them costs nothing.
        self._queue = [w for w in self._queue if w.target is not member]
        stale = [tok for tok, w in self._inflight.items() if w.target is member]
        for tok in stale:
            w = self._inflight.pop(tok)
            key = self.population._safe_cache_key(w.ind)
            if key is not None and self._key_to_token.get(key) is tok:
                del self._key_to_token[key]
            self._open_children.pop(id(w.ind), None)
            self.dispatched -= 1  # retracted, never completing
            for f in self._followers.pop(tok, []):
                # Followers ride another token's evaluation; with it
                # cancelled they go back to the queue (their dispatch is
                # retracted too — they re-count when re-popped).
                self.dispatched -= 1
                self._open_children.pop(id(f.ind), None)
                if f.target is not member:
                    self._queue.insert(0, f)
        if stale and self._evaluator is not None:
            self._evaluator.cancel(stale)
            if _tele.enabled():
                _get_registry().counter(
                    "promotions_cancelled_total").inc(len(stale))
        member._promo_pending = False

    def _fail(self, work: _Work, reason: str) -> None:
        """A permanently failed evaluation consumes budget and breeds a
        replacement (via the next refill) but never joins the ring — a
        failed MEMBER leaves it, so aging eviction never has to step over
        a corpse, and a failed PROMOTION probe leaves its member exactly
        as it was (lower-rung fitness intact, marked so the ladder never
        retries the same doomed promotion)."""
        logger.warning("async evaluation failed permanently: %s", reason)
        ind = work.ind
        if self._surrogate is not None:
            self._surrogate.forget(ind.get_genes())
        if _lineage.enabled():
            _lineage.record(
                "failed", _lineage.genome_key(ind.get_genes()),
                rung=work.rung, reason=str(reason)[:200])
        self._open_children.pop(id(ind), None)
        if work.target is not None:
            work.target._promo_pending = False
            if work.target.fitness_evaluated:
                work.target._promo_failed_rung = work.rung
            else:
                # A failed COHORT probe: the member never got a fitness at
                # all — it leaves the ring like any failed member would.
                try:
                    self.population.individuals.remove(work.target)
                except ValueError:  # pragma: no cover - defensive
                    pass
        elif work.is_member:
            try:
                self.population.individuals.remove(ind)
            except ValueError:  # pragma: no cover - defensive
                pass
        self.completed += 1
        entry = {
            "completed": self.completed,
            "fitness": None,
            "best_fitness": None if self.best is None else self.best.get_fitness(),
            "in_flight": len(self._inflight),
            "failed": True,
        }
        if self._ladder is not None:
            entry["rung"] = work.rung
        self.history.append(entry)

    def _boundary(self) -> None:
        """Checkpoint (and fire the chaos boundary hook) every
        ``checkpoint_every`` completions — the async analogue of the
        generation boundary."""
        if self.completed - self._last_ckpt < self.checkpoint_every:
            return
        self._last_ckpt = self.completed
        # Search-progress gauges for the fleet dashboard — the async
        # analogue of the generational engine's per-generation set, at the
        # same cadence as the checkpoint boundary (never per completion).
        sess = getattr(self, "_status_session", None) or "default"
        reg = _get_registry()
        reg.gauge("engine_completions", session=sess,
                  mode="async").set(self.completed)
        if self.best is not None and self.best.get_fitness() is not None:
            reg.gauge("engine_best_fitness", session=sess,
                      mode="async").set(float(self.best.get_fitness()))
        if self._checkpointer is not None:
            with _tele.span("checkpoint"):
                self._checkpointer.save(self)
        if self._fault_injector is not None:
            # After the checkpoint: a kill here is the recoverable crash.
            self._fault_injector.master_boundary(self.completed)

    # -- (de)serialization state for checkpoint/resume ---------------------

    def _member_index(self, member: Individual) -> Optional[int]:
        for i, ind in enumerate(self.population.individuals):
            if ind is member:
                return i
        return None

    def _work_state(self, w: _Work) -> Optional[Dict[str, Any]]:
        """One laddered in-flight/queued checkpoint entry, or None for a
        promotion whose member already left the ring (eviction cancels
        those — nothing to resume)."""
        entry: Dict[str, Any] = {
            "genes": w.ind.get_genes(),
            "rung": w.rung,
            "kind": "child" if w.target is None else "promotion",
        }
        if w.target is not None:
            idx = self._member_index(w.target)
            if idx is None:  # pragma: no cover - eviction cancels these
                return None
            entry["member_index"] = idx
        return entry

    def state_dict(self) -> Dict[str, Any]:
        fitness_cache = [
            [k, v]
            for k, v in self.population.fitness_cache.items()
            if is_serializable_key(k)
        ]
        if self._ladder is None:
            # Ladderless: the exact v2 in-flight shape (a list of genes).
            open_children: List[Any] = [
                w.ind.get_genes() for w in self._open_children.values()]
        else:
            # v3: enough to resume a promotion AS a promotion — the rung,
            # and which ring member the probe reports to.
            open_children = []
            for w in self._open_children.values():
                entry = self._work_state(w)
                if entry is not None:
                    open_children.append(entry)
            # Decided-but-undispatched work (the queue): promotion probes
            # and requeued children waiting for an in-flight slot.  Cohort
            # probes are NOT serialized — their members are unevaluated in
            # the ring, so ``run()`` reconstructs them — but a queued
            # promotion dropped here would silently demote its member on
            # resume and diverge from the uninterrupted trajectory.
            queued = []
            for w in self._queue:
                if w.target is not None and not w.target.fitness_evaluated:
                    continue  # cohort probe: rebuilt from the ring
                entry = self._work_state(w)
                if entry is not None:
                    queued.append(entry)
        state = {
            "algorithm": "AsyncEvolution",
            "fitness_protocol": FITNESS_PROTOCOL,
            "fitness_cache": fitness_cache,
            "completed": self.completed,
            "dispatched": self.completed + len(open_children),
            "tournament_size": self.tournament_size,
            "max_in_flight": self.max_in_flight,
            "checkpoint_every": self.checkpoint_every,
            "rng_state": self.rng.bit_generator.state,
            "history": self.history,
            "best": None if self.best is None else {
                "genes": self.best.get_genes(),
                "fitness": self.best.get_fitness(),
            },
            "population": {
                "size": self.pop_size,
                "maximize": self.population.maximize,
                "crossover_rate": self.population.crossover_rate,
                "mutation_rate": self.population.mutation_rate,
                "additional_parameters": self.population.additional_parameters,
                "individuals": [
                    self._member_state(ind) for ind in self.population
                ],
            },
            # Children bred-but-uncompleted, in dispatch order: a resumed
            # run re-dispatches exactly these (the breeding RNG draws that
            # produced them are already consumed in rng_state).
            "in_flight": open_children,
        }
        if self._ladder is not None:
            state["queued"] = queued
            state["ladder"] = self._ladder
            state["eta"] = self.eta
            state["rung_completions"] = self._rung_completions
            state["best_by_rung"] = [
                {"rung": r, "genes": b.get_genes(), "fitness": b.get_fitness()}
                for r, b in sorted(self._best_by_rung.items())
            ]
        if self._surrogate is not None:
            # Schema v4: the whole rung −1 — model weights AND training
            # samples, score window, pending gate decisions — so a killed
            # master resumes the gated trajectory bit-identically.
            state["surrogate"] = self._surrogate.state_dict()
        return state

    def _member_state(self, ind: Individual) -> Dict[str, Any]:
        entry: Dict[str, Any] = {"genes": ind.get_genes(), "fitness": ind._fitness}
        if self._ladder is not None:
            entry["rung"] = getattr(ind, "_rung", 0)
            failed = getattr(ind, "_promo_failed_rung", None)
            if failed is not None:
                entry["promo_failed_rung"] = failed
        return entry

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        algo = state.get("algorithm")
        if algo not in (None, "AsyncEvolution"):
            raise ValueError(
                f"checkpoint was written by {algo}, not AsyncEvolution — "
                "generational and steady-state scheduler state are not "
                "interchangeable; resume it with the matching class")
        self.completed = int(state["completed"])
        self.tournament_size = int(state["tournament_size"])
        if state.get("max_in_flight") is not None:
            self.max_in_flight = int(state["max_in_flight"])
        self.checkpoint_every = int(state.get("checkpoint_every", self.checkpoint_every))
        self.rng.bit_generator.state = state["rng_state"]
        self.history = list(state["history"])
        pop_state = state["population"]
        self.pop_size = int(pop_state.get("size", len(pop_state["individuals"])))
        self.population.maximize = bool(pop_state["maximize"])
        self.population.crossover_rate = float(pop_state["crossover_rate"])
        self.population.mutation_rate = float(pop_state["mutation_rate"])
        self.population.additional_parameters = dict(pop_state["additional_parameters"])
        # Same cross-protocol guard as the generational loader: fitnesses
        # measured under an older fitness-RNG protocol are incomparable —
        # drop them (loudly) and let the ring re-measure.
        proto = state.get("fitness_protocol", 1)
        proto_ok = proto == FITNESS_PROTOCOL
        if not proto_ok:
            logger.warning(
                "checkpoint was written under fitness RNG protocol %s "
                "(current: %s); discarding its fitness values and cache — "
                "the resumed search re-measures instead of mixing "
                "incomparable measurements", proto, FITNESS_PROTOCOL,
            )
        # Ladder state (schema v3).  The checkpoint's ladder wins over the
        # constructor's, like every other serialized knob — a resumed run
        # continues the SAME search, not a reconfigured one.
        ladder = state.get("ladder")
        if ladder is not None:
            self._ladder = [dict(r) for r in ladder]
            self.eta = int(state.get("eta", self.eta))
            self._rung_completions = [
                [float(v) for v in rung]
                for rung in state.get("rung_completions",
                                      [[] for _ in self._ladder])
            ]
        # Surrogate state (schema v4).  The checkpoint's gate wins over the
        # constructor's (same precedent as the ladder): a resumed run
        # continues the SAME gated search.  A v3 file (no "surrogate" key)
        # under a gated ctor keeps the ctor's fresh gate — it just starts
        # untrained, i.e. admit-all.
        sur_state = state.get("surrogate")
        if sur_state is not None:
            if self._surrogate is None:
                self._surrogate = SurrogateGate.from_state(sur_state)
            else:
                self._surrogate.load_state_dict(sur_state)
        individuals = []
        for ind_state in pop_state["individuals"]:
            ind = self.population.spawn(genes=ind_state["genes"])
            if ind_state["fitness"] is not None and proto_ok:
                ind.set_fitness(ind_state["fitness"])
                if self._ladder is not None:
                    ind._rung = int(ind_state.get("rung", 0))
            if ind_state.get("promo_failed_rung") is not None:
                ind._promo_failed_rung = int(ind_state["promo_failed_rung"])
            individuals.append(ind)
        self.population.individuals = individuals
        restored = {
            tuplify(key): float(fit) for key, fit in state.get("fitness_cache", [])
        } if proto_ok else {}
        # Keep a ServiceBackedCache's shared-service backing across resume
        # (same duck-typed hook as GeneticAlgorithm.load_state_dict).
        cache = self.population.fitness_cache
        if hasattr(cache, "rebase"):
            cache.rebase(restored)
        else:
            self.population.fitness_cache = restored
        best = state.get("best")
        if best is not None and proto_ok:
            b = self.population.spawn(genes=best["genes"])
            b.set_fitness(best["fitness"])
            self.best = b
        else:
            self.best = None
        self._best_by_rung = {}
        if self._ladder is not None and proto_ok:
            for entry in state.get("best_by_rung", []):
                r = int(entry["rung"])
                overlay = self._ladder[min(r, len(self._ladder) - 1)]
                b = self.population.spawn(
                    genes=entry["genes"], additional_parameters=overlay)
                b.set_fitness(entry["fitness"])
                b._rung = r
                self._best_by_rung[r] = b
            if self._best_by_rung:
                self.best = self._best_by_rung[max(self._best_by_rung)]
        self._restored_in_flight = []
        # In-flight first, then the undispatched queue — the original
        # dispatch order, so the resumed trajectory replays it.
        for entry in list(state.get("in_flight", [])) + list(state.get("queued", [])):
            if self._ladder is None:
                # v2 shape: the entry IS the genes dict of a rung-0 child.
                self._restored_in_flight.append(
                    _Work(self.population.spawn(genes=entry), False))
                continue
            if "kind" not in entry:  # v2 file resumed WITH a ladder ctor
                entry = {"genes": entry, "rung": 0, "kind": "child"}
            rung = min(int(entry.get("rung", 0)), len(self._ladder) - 1)
            overlay = self._ladder[rung]
            probe = self.population.spawn(
                genes=entry["genes"], additional_parameters=overlay)
            target = None
            if entry.get("kind") == "promotion":
                idx = entry.get("member_index")
                if idx is not None and 0 <= int(idx) < len(individuals):
                    target = individuals[int(idx)]
                    target._promo_pending = True
                else:  # pragma: no cover - defensive
                    continue
            self._restored_in_flight.append(
                _Work(probe, False, rung=rung, target=target))
        self._last_ckpt = self.completed
