"""Asynchronous steady-state evolution: the generation barrier, removed.

The generational loop (``algorithms.py``) evaluates a whole population,
waits at a barrier, then breeds the next generation — so a fleet is only
busy while a generation is wide, and the converged tail (1-4 fresh
individuals per generation, PERF.md "Tail generations") pays a
program-switch + dispatch + RPC floor per generation while most worker
capacity idles.

:class:`AsyncEvolution` replaces the barrier with *regularized evolution*
(Real et al. 2019, "Regularized Evolution for Image Classifier Architecture
Search") driven by a completion loop in the barrier-free worker style of
population-based training (Jaderberg et al. 2017):

- a bounded, age-ordered population (the *ring*): youngest appended,
  oldest **evicted by age** — never by fitness — each time a child joins;
- **aging tournament selection**: parents are the fittest of a uniform
  sample of evaluated ring members;
- a configurable number of evaluations (default: the fleet's total
  capacity) stays in flight at all times — every completed evaluation
  immediately breeds and dispatches a replacement child, so the fleet
  stays busy through the tail.

The engine is mode-agnostic: a data-holding :class:`Population` evaluates
on a local thread pool; a ``DistributedPopulation`` uses the broker's
completion-driven API (``wait_any``) with one coalesced submit per wake-up.
Canonical-dedup and fitness-store reuse apply at dispatch: a child whose
``cache_key`` is already measured completes instantly without occupying a
worker slot, and a child identical to one already in flight attaches to it
as a *follower* instead of training twice.

Determinism: the engine consumes randomness only from its own generator,
and every breeding decision is driven by the completion stream — with a
deterministic completion order (one in-flight slot, or a single capacity-1
worker) the whole trajectory is a pure function of the seed, checkpoints
included.  The generational mode is untouched: ``GeneticAlgorithm`` remains
the default and stays bit-identical.
"""

from __future__ import annotations

import itertools
import logging
import queue as _queue
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .individuals import Individual
from .populations import Population
from .telemetry import health as _health
from .telemetry import spans as _tele
from .utils.fitness_store import FITNESS_PROTOCOL, is_serializable_key, tuplify

__all__ = ["AsyncEvolution"]

logger = logging.getLogger("gentun_tpu")

#: event tuple: (token, fitness-or-None, error-reason-or-None)
_Event = Tuple[Any, Optional[float], Optional[str]]


class _LocalEvaluator:
    """Thread-pool evaluation for data-holding populations.

    One worker thread per in-flight slot; completions land on a queue in
    finish order.  With a single thread the executor is FIFO, which is the
    deterministic configuration the seeded-determinism and kill/resume
    tests rely on.
    """

    def __init__(self, n_threads: int):
        self._n = max(1, int(n_threads))
        self._pool = ThreadPoolExecutor(
            max_workers=self._n, thread_name_prefix="gentun-async-eval")
        self._done: _queue.Queue = _queue.Queue()
        self._seq = itertools.count()
        self._futures: Dict[int, Any] = {}

    def default_capacity(self) -> int:
        return self._n

    def submit(self, individuals: List[Individual]) -> List[int]:
        tokens = []
        for ind in individuals:
            token = next(self._seq)
            fut = self._pool.submit(ind.get_fitness)
            fut.add_done_callback(lambda f, t=token: self._done.put((t, f)))
            self._futures[token] = fut
            tokens.append(token)
        return tokens

    def wait_any(self, timeout: Optional[float]) -> List[_Event]:
        try:
            token, fut = self._done.get(timeout=timeout)
        except _queue.Empty:
            return []
        events = [self._event(token, fut)]
        while True:  # drain whatever else already finished
            try:
                token, fut = self._done.get_nowait()
            except _queue.Empty:
                return events
            events.append(self._event(token, fut))

    def _event(self, token: int, fut) -> _Event:
        self._futures.pop(token, None)
        if fut.cancelled():
            return (token, None, "cancelled")
        exc = fut.exception()
        if exc is not None:
            return (token, None, repr(exc))
        return (token, float(fut.result()), None)

    def cancel(self, tokens) -> None:
        for t in tokens:
            fut = self._futures.pop(t, None)
            if fut is not None:
                fut.cancel()

    def close(self) -> None:
        try:
            self._pool.shutdown(wait=False, cancel_futures=True)
        except TypeError:  # pragma: no cover - pre-3.9 fallback
            self._pool.shutdown(wait=False)


class _DistributedEvaluator:
    """Completion-driven evaluation through a ``DistributedPopulation``.

    Thin: payload construction and the broker's ``wait_any``/``cancel``
    live on the population (``distributed/server.py``), keeping the wire
    format single-owner.  Tokens are broker job ids.
    """

    def __init__(self, population):
        self._pop = population
        self._open: set = set()

    def default_capacity(self) -> int:
        # Wait briefly for the fleet so "capacity" means the real fleet,
        # not the pre-connect instant — and keep watching after the first
        # worker appears, because its peers are usually mid-handshake: a
        # cap that stops growing for 0.75 s is taken as the fleet.
        deadline = time.monotonic() + 10.0
        cap, last_growth = 0, time.monotonic()
        while time.monotonic() < deadline:
            now = self._pop.fleet_capacity()
            if now > cap:
                cap, last_growth = now, time.monotonic()
            elif cap > 0 and time.monotonic() - last_growth >= 0.75:
                break
            time.sleep(0.05)
        # Breed ahead to the fleet's full dispatch WINDOW — evaluation
        # slots plus the workers' advertised prefetch queues — so every
        # worker always has a decoded next window waiting (the engine half
        # of the pipelined dispatch plane).  A fleet advertising no
        # prefetch yields exactly the old target, keeping prefetch_depth=0
        # trajectories bit-identical.
        prefetch = getattr(self._pop, "fleet_prefetch", lambda: 0)()
        return max(1, cap) + max(0, int(prefetch))

    def submit(self, individuals: List[Individual]) -> List[str]:
        ids = self._pop.submit_individuals(individuals)
        self._open.update(ids)
        return ids

    def wait_any(self, timeout: Optional[float]) -> List[_Event]:
        if not self._open:
            return []
        results, failures = self._pop.wait_any_results(list(self._open), timeout=timeout)
        self._open -= set(results) | set(failures)
        return ([(j, f, None) for j, f in results.items()]
                + [(j, None, r) for j, r in failures.items()])

    def cancel(self, tokens) -> None:
        ids = [t for t in tokens if t in self._open]
        self._open -= set(ids)
        if ids:
            self._pop.cancel_jobs(ids)

    def close(self) -> None:
        pass  # population/broker lifecycle belongs to the caller


class AsyncEvolution:
    """Steady-state aging-tournament evolution without a generation barrier.

    Parameters
    ----------
    population:
        The initial cohort — a :class:`Population` (local evaluation) or a
        ``DistributedPopulation`` (broker-backed).  Its size is the ring's
        bound for the whole search.
    tournament_size:
        Members sampled per parent draw; the fittest wins.
    max_in_flight:
        Evaluations kept in flight at all times.  ``None`` (default)
        resolves at :meth:`run` to the connected fleet's total capacity
        (distributed) or 1 (local).
    seed:
        Seeds the engine's own RNG; ``None`` shares the population's.
    checkpoint_every:
        Completions between checkpoint saves (and ``master_boundary``
        fault hooks) when a checkpointer is attached.
    job_timeout:
        Max seconds to wait for ANY completion before raising — ``None``
        waits forever (the generational default).
    """

    def __init__(
        self,
        population: Population,
        tournament_size: int = 5,
        max_in_flight: Optional[int] = None,
        seed: Optional[int] = None,
        checkpoint_every: int = 8,
        job_timeout: Optional[float] = None,
    ):
        self.population = population
        self.tournament_size = int(tournament_size)
        self.max_in_flight = None if max_in_flight is None else max(1, int(max_in_flight))
        self.checkpoint_every = max(1, int(checkpoint_every))
        self.job_timeout = job_timeout
        self.rng = np.random.default_rng(seed) if seed is not None else population.rng
        self.pop_size = len(population)
        self.completed = 0
        self.dispatched = 0
        self.history: List[Dict[str, Any]] = []
        #: copy of the best individual EVER completed — aging eviction may
        #: remove the champion from the ring, so the ring's fittest member
        #: is not the search's answer.
        self.best: Optional[Individual] = None
        self._checkpointer = None
        self._fault_injector = None
        self._last_ckpt = 0
        # Scheduler state (also serialized): children bred and dispatched
        # but not yet completed, in dispatch order — the piece a resumed
        # run must re-dispatch to continue the same trajectory.
        self._open_children: Dict[int, Individual] = {}
        self._restored_in_flight: List[Individual] = []
        # Run-local maps (rebuilt by run()).
        self._queue: List[Tuple[Individual, bool]] = []
        self._inflight: Dict[Any, Tuple[Individual, bool]] = {}
        self._followers: Dict[Any, List[Tuple[Individual, bool]]] = {}
        self._key_to_token: Dict[Any, Any] = {}
        self._cap = 1

    # -- hooks (same contract as GeneticAlgorithm) -------------------------

    def set_checkpointer(self, checkpointer) -> None:
        """Attach a completion-boundary checkpointer (``utils/checkpoint.py``)."""
        self._checkpointer = checkpointer

    def set_fault_injector(self, injector) -> None:
        """Attach a chaos injector; ``master_boundary`` fires with the
        completion count, AFTER each checkpoint save — a ``kill_master``
        fault therefore lands exactly where resume is guaranteed from."""
        self._fault_injector = injector

    # -- selection ---------------------------------------------------------

    def select_parent(self) -> Individual:
        """Aging tournament over the ring's evaluated members."""
        with _tele.span("select"):
            members = [i for i in self.population if i.fitness_evaluated]
            t = min(self.tournament_size, len(members))
            idx = self.rng.choice(len(members), size=t, replace=False)
            contenders = [members[int(i)] for i in idx]
            key = lambda ind: ind.get_fitness()
            return max(contenders, key=key) if self.population.maximize else min(contenders, key=key)

    # -- the completion loop -----------------------------------------------

    def run(self, max_evaluations: int, checkpointer=None) -> Individual:
        """Run until ``max_evaluations`` evaluations completed (TOTAL, like
        the generational ``run`` under a checkpointer: the initial cohort
        counts, cache-answered children count, permanently failed
        evaluations count — the budget is completions, so the loop always
        terminates).  Returns a copy of the best individual ever measured.

        With ``checkpointer``, the run is crash-resumable: any existing
        checkpoint is restored first (ring, RNG state, history, best, and
        the children that were in flight), and a killed master re-run with
        the same arguments continues the search — deterministically, when
        the completion order is deterministic (see the module docstring).
        """
        if checkpointer is not None:
            self.set_checkpointer(checkpointer)
            if checkpointer.resume(self):
                logger.info("resumed async search at %d completion(s)", self.completed)
        budget = int(max_evaluations)
        evaluator = self._make_evaluator()
        cap = self.max_in_flight
        if cap is None:
            cap = evaluator.default_capacity()
        self._cap = max(1, int(cap))
        self._last_ckpt = self.completed
        # Everything whose evaluation is owed but not running: unevaluated
        # ring members first (initial cohort / in-flight-at-kill members),
        # then checkpointed in-flight children in dispatch order.
        self._queue = [(ind, True) for ind in self.population if not ind.fitness_evaluated]
        self._queue += [(ind, False) for ind in self._restored_in_flight]
        self._restored_in_flight = []
        self._inflight = {}
        self._followers = {}
        self._key_to_token = {}
        self._open_children = {}
        # Re-dispatch re-counts the queued work (members and restored
        # children alike), so the budget gate stays consistent on resume.
        self.dispatched = self.completed
        logger.info(
            "starting AsyncEvolution: ring=%d, budget=%d (%d done), in-flight target=%d",
            self.pop_size, budget, self.completed, self._cap,
        )
        _health.register_status_provider("engine", self._ops_status)
        with _tele.span("run", {"mode": "async", "budget": budget,
                                "max_in_flight": self._cap}) as run_span:
            # /statusz "active trace_id" (None while telemetry is off —
            # the no-op span has no trace_id attribute).
            self._run_trace_id = getattr(run_span, "trace_id", None)
            try:
                self._refill(evaluator, budget)
                while self.completed < budget and (self._inflight or self._queue):
                    # Advisory /statusz beat: one bool read when the ops
                    # plane is off.  Never gates /healthz — a wake-up can
                    # legitimately be an evaluation-time apart.
                    _health.beat("engine_loop")
                    events = evaluator.wait_any(self.job_timeout)
                    if not events:
                        raise TimeoutError(
                            f"no evaluation completed within {self.job_timeout}s "
                            f"({len(self._inflight)} in flight, "
                            f"{self.completed}/{budget} done)")
                    for token, fitness, error in events:
                        self._on_event(token, fitness, error)
                    self._refill(evaluator, budget)
                    self._boundary()
            finally:
                _health.unregister_status_provider("engine", self._ops_status)
                leftover = list(self._inflight)
                if leftover:
                    # Budget reached with children still training: their
                    # results are unwanted — withdraw instead of waiting.
                    evaluator.cancel(leftover)
                    for token in leftover:
                        ind, _ = self._inflight.pop(token)
                        self._open_children.pop(id(ind), None)
                    self._key_to_token = {}
                    self._followers = {}
                evaluator.close()
        if self.best is None:
            raise RuntimeError("no evaluation ever completed successfully")
        logger.info(
            "async search done: %d completion(s), best fitness %.6g, genes %s",
            self.completed, self.best.get_fitness(), self.best.get_genes(),
        )
        return self.best

    def _ops_status(self) -> Dict[str, Any]:
        """The ``/statusz`` "engine" block while an async search runs
        (``telemetry/health.py`` status provider; snapshot reads only —
        ``self.best`` is replaced wholesale, never mutated in place)."""
        best = self.best
        return {
            "mode": "async",
            "completed": self.completed,
            "dispatched": self.dispatched,
            "in_flight": len(self._inflight),
            "queued": len(self._queue),
            "ring_size": self.pop_size,
            "best_fitness": best.get_fitness() if best is not None else None,
            "trace_id": getattr(self, "_run_trace_id", None),
        }

    # -- internals ---------------------------------------------------------

    def _make_evaluator(self):
        if hasattr(self.population, "broker"):
            return _DistributedEvaluator(self.population)
        return _LocalEvaluator(self.max_in_flight or 1)

    def _can_breed(self) -> bool:
        return any(i.fitness_evaluated for i in self.population)

    def _breed(self) -> Individual:
        with _tele.span("reproduce"):
            mother = self.select_parent()
            father = self.select_parent()
            return mother.reproduce(father, self.rng)

    def _refill(self, evaluator, budget: int) -> None:
        """Top the in-flight set back up to the target, breeding as needed.

        Children bred in one wake-up ship as ONE submit (one coalesced
        ``jobs`` frame per worker window downstream).  Dispatch-side dedup:
        a child already in the fitness cache (this search or a loaded
        fitness store) completes instantly; a child identical to an
        in-flight job becomes its follower.  Neither occupies a slot, so
        the loop keeps breeding until real work fills the capacity or the
        budget is spent.
        """
        to_submit: List[Tuple[Individual, bool, Any]] = []
        while (self.dispatched < budget
               and len(self._inflight) + len(to_submit) < self._cap):
            if self._queue:
                ind, is_member = self._queue.pop(0)
            elif self._can_breed():
                ind, is_member = self._breed(), False
            else:
                break  # nothing evaluated yet: wait for the cohort
            self.dispatched += 1
            key = self.population._safe_cache_key(ind)
            cached = self.population.fitness_cache.get(key) if key is not None else None
            if cached is not None:
                self._complete(ind, float(cached), is_member, cached=True)
                continue
            token = self._key_to_token.get(key) if key is not None else None
            if token is not None:
                self._followers.setdefault(token, []).append((ind, is_member))
                if not is_member:
                    self._open_children[id(ind)] = ind
                continue
            to_submit.append((ind, is_member, key))
        if to_submit:
            tokens = evaluator.submit([ind for ind, _, _ in to_submit])
            for token, (ind, is_member, key) in zip(tokens, to_submit):
                self._inflight[token] = (ind, is_member)
                if key is not None:
                    self._key_to_token[key] = token
                if not is_member:
                    self._open_children[id(ind)] = ind

    def _on_event(self, token, fitness: Optional[float], error: Optional[str]) -> None:
        entry = self._inflight.pop(token, None)
        if entry is None:
            return  # cancelled/stale
        ind, is_member = entry
        key = self.population._safe_cache_key(ind)
        if key is not None and self._key_to_token.get(key) is token:
            del self._key_to_token[key]
        followers = self._followers.pop(token, [])
        if error is not None:
            self._fail(ind, is_member, error)
            for f_ind, f_member in followers:
                self._fail(f_ind, f_member, error)
            return
        self._complete(ind, fitness, is_member)
        for f_ind, f_member in followers:
            self._complete(f_ind, fitness, f_member)

    def _complete(self, ind: Individual, fitness: float, is_member: bool,
                  cached: bool = False) -> None:
        """One evaluation finished: membership, cache, best, history."""
        if not ind.fitness_evaluated:
            ind.set_fitness(fitness)
        key = self.population._safe_cache_key(ind)
        if key is not None and not cached:
            self.population.fitness_cache[key] = float(fitness)
        self._open_children.pop(id(ind), None)
        if not is_member:
            # Steady-state transition: child in (youngest), oldest out.
            self.population.insert(ind)
            if len(self.population) > self.pop_size:
                self.population.evict_oldest()
        if self.best is None:
            better = True
        elif self.population.maximize:
            better = fitness > self.best.get_fitness()
        else:
            better = fitness < self.best.get_fitness()
        if better:
            self.best = ind.copy()  # keeps the fitness
        self.completed += 1
        self.history.append({
            "completed": self.completed,
            "fitness": float(fitness),
            "best_fitness": self.best.get_fitness(),
            "in_flight": len(self._inflight),
            "cached": bool(cached),
        })

    def _fail(self, ind: Individual, is_member: bool, reason: str) -> None:
        """A permanently failed evaluation consumes budget and breeds a
        replacement (via the next refill) but never joins the ring — and a
        failed MEMBER leaves it, so aging eviction never has to step over a
        corpse."""
        logger.warning("async evaluation failed permanently: %s", reason)
        self._open_children.pop(id(ind), None)
        if is_member:
            try:
                self.population.individuals.remove(ind)
            except ValueError:  # pragma: no cover - defensive
                pass
        self.completed += 1
        self.history.append({
            "completed": self.completed,
            "fitness": None,
            "best_fitness": None if self.best is None else self.best.get_fitness(),
            "in_flight": len(self._inflight),
            "failed": True,
        })

    def _boundary(self) -> None:
        """Checkpoint (and fire the chaos boundary hook) every
        ``checkpoint_every`` completions — the async analogue of the
        generation boundary."""
        if self.completed - self._last_ckpt < self.checkpoint_every:
            return
        self._last_ckpt = self.completed
        if self._checkpointer is not None:
            with _tele.span("checkpoint"):
                self._checkpointer.save(self)
        if self._fault_injector is not None:
            # After the checkpoint: a kill here is the recoverable crash.
            self._fault_injector.master_boundary(self.completed)

    # -- (de)serialization state for checkpoint/resume ---------------------

    def state_dict(self) -> Dict[str, Any]:
        fitness_cache = [
            [k, v]
            for k, v in self.population.fitness_cache.items()
            if is_serializable_key(k)
        ]
        open_children = [ind.get_genes() for ind in self._open_children.values()]
        return {
            "algorithm": "AsyncEvolution",
            "fitness_protocol": FITNESS_PROTOCOL,
            "fitness_cache": fitness_cache,
            "completed": self.completed,
            "dispatched": self.completed + len(open_children),
            "tournament_size": self.tournament_size,
            "max_in_flight": self.max_in_flight,
            "checkpoint_every": self.checkpoint_every,
            "rng_state": self.rng.bit_generator.state,
            "history": self.history,
            "best": None if self.best is None else {
                "genes": self.best.get_genes(),
                "fitness": self.best.get_fitness(),
            },
            "population": {
                "size": self.pop_size,
                "maximize": self.population.maximize,
                "crossover_rate": self.population.crossover_rate,
                "mutation_rate": self.population.mutation_rate,
                "additional_parameters": self.population.additional_parameters,
                "individuals": [
                    {"genes": ind.get_genes(), "fitness": ind._fitness}
                    for ind in self.population
                ],
            },
            # Children bred-but-uncompleted, in dispatch order: a resumed
            # run re-dispatches exactly these (the breeding RNG draws that
            # produced them are already consumed in rng_state).
            "in_flight": open_children,
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        algo = state.get("algorithm")
        if algo not in (None, "AsyncEvolution"):
            raise ValueError(
                f"checkpoint was written by {algo}, not AsyncEvolution — "
                "generational and steady-state scheduler state are not "
                "interchangeable; resume it with the matching class")
        self.completed = int(state["completed"])
        self.tournament_size = int(state["tournament_size"])
        if state.get("max_in_flight") is not None:
            self.max_in_flight = int(state["max_in_flight"])
        self.checkpoint_every = int(state.get("checkpoint_every", self.checkpoint_every))
        self.rng.bit_generator.state = state["rng_state"]
        self.history = list(state["history"])
        pop_state = state["population"]
        self.pop_size = int(pop_state.get("size", len(pop_state["individuals"])))
        self.population.maximize = bool(pop_state["maximize"])
        self.population.crossover_rate = float(pop_state["crossover_rate"])
        self.population.mutation_rate = float(pop_state["mutation_rate"])
        self.population.additional_parameters = dict(pop_state["additional_parameters"])
        # Same cross-protocol guard as the generational loader: fitnesses
        # measured under an older fitness-RNG protocol are incomparable —
        # drop them (loudly) and let the ring re-measure.
        proto = state.get("fitness_protocol", 1)
        proto_ok = proto == FITNESS_PROTOCOL
        if not proto_ok:
            logger.warning(
                "checkpoint was written under fitness RNG protocol %s "
                "(current: %s); discarding its fitness values and cache — "
                "the resumed search re-measures instead of mixing "
                "incomparable measurements", proto, FITNESS_PROTOCOL,
            )
        individuals = []
        for ind_state in pop_state["individuals"]:
            ind = self.population.spawn(genes=ind_state["genes"])
            if ind_state["fitness"] is not None and proto_ok:
                ind.set_fitness(ind_state["fitness"])
            individuals.append(ind)
        self.population.individuals = individuals
        self.population.fitness_cache = {
            tuplify(key): float(fit) for key, fit in state.get("fitness_cache", [])
        } if proto_ok else {}
        best = state.get("best")
        if best is not None and proto_ok:
            b = self.population.spawn(genes=best["genes"])
            b.set_fitness(best["fitness"])
            self.best = b
        else:
            self.best = None
        self._restored_in_flight = [
            self.population.spawn(genes=g) for g in state.get("in_flight", [])
        ]
        self._last_ckpt = self.completed
