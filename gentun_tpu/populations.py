"""Populations: collections of individuals sharing training data.

Reference parity: ``Population`` and ``GridPopulation`` in
``gentun/populations.py`` [PUB] (SURVEY.md §2.0 row 4).  A population holds
the individuals plus the shared ``(x_train, y_train)`` and the ``maximize``
flag; it knows how to random-init ``size`` individuals, enumerate a grid of
gene values, and report the fittest member.

TPU-first departure from the reference: :meth:`Population.evaluate` is a
first-class population-level operation.  When the species' fitness model
supports it, the *whole population* is evaluated in a single batched
(vmapped) XLA program — every genome shares one compiled supergraph, so
evaluating N individuals costs one compile + one batched train instead of N
sequential Keras fits (SURVEY.md §7 "hard parts" #1, the main
individuals/hour/chip lever).  The per-individual lazy path
(``Individual.get_fitness``) still works and is what distributed workers use.
"""

from __future__ import annotations

from typing import Any, Dict, List, Mapping, Optional, Sequence, Type

import numpy as np

from .individuals import Individual

__all__ = ["Population", "GridPopulation"]


class Population:
    """A fixed-size set of individuals of one species.

    Args mirror the reference constructor (``gentun/populations.py`` [PUB]):
    ``species`` (the Individual subclass), shared data, either ``size`` for
    random init or an explicit ``individual_list``, operator rates, the
    optimisation direction, and ``additional_parameters`` forwarded to every
    individual.  ``seed`` is new: it makes the whole run reproducible.
    """

    def __init__(
        self,
        species: Type[Individual],
        x_train=None,
        y_train=None,
        individual_list: Optional[Sequence[Individual]] = None,
        size: Optional[int] = None,
        crossover_rate: float = 0.5,
        mutation_rate: float = 0.015,
        maximize: bool = True,
        additional_parameters: Optional[Dict[str, Any]] = None,
        seed: Optional[int] = None,
        rng: Optional[np.random.Generator] = None,
    ):
        self.species = species
        self.x_train = x_train
        self.y_train = y_train
        self.crossover_rate = crossover_rate
        self.mutation_rate = mutation_rate
        self.maximize = maximize
        self.additional_parameters = dict(additional_parameters or {})
        self.rng = rng if rng is not None else np.random.default_rng(seed)

        if individual_list is not None:
            self.individuals: List[Individual] = list(individual_list)
        elif size is not None:
            self.individuals = [self.spawn() for _ in range(size)]
        else:
            raise ValueError("provide either `size` or `individual_list`")

    # -- construction ------------------------------------------------------

    def spawn(self, genes: Optional[Mapping[str, Any]] = None) -> Individual:
        """Create one individual of this population's species."""
        return self.species(
            x_train=self.x_train,
            y_train=self.y_train,
            genes=dict(genes) if genes is not None else None,
            crossover_rate=self.crossover_rate,
            mutation_rate=self.mutation_rate,
            maximize=self.maximize,
            rng=self.rng,
            additional_parameters=dict(self.additional_parameters),
        )

    def add_individual(self, individual: Individual) -> None:
        self.individuals.append(individual)

    def populate_from_grid(self, genes_grid: Optional[Mapping[str, Sequence[Any]]] = None) -> None:
        """Append one individual per point of the gene-value grid.

        Shared by ``GridPopulation`` and ``DistributedGridPopulation``
        (SURVEY.md §2.0 rows 4, 10): enumeration itself lives in
        :meth:`GenomeSpec.grid`.
        """
        probe = self.spawn()
        for genome in probe.spec.grid(gene_values=genes_grid):
            self.add_individual(self.spawn(genes=genome))

    # -- container protocol (gentun exposes the same) ----------------------

    def __len__(self) -> int:
        return len(self.individuals)

    def get_size(self) -> int:
        return len(self.individuals)

    def __getitem__(self, item: int) -> Individual:
        return self.individuals[item]

    def __iter__(self):
        return iter(self.individuals)

    def get_species(self) -> Type[Individual]:
        return self.species

    def get_data(self):
        return self.x_train, self.y_train

    # -- fitness -----------------------------------------------------------

    def evaluate(self) -> None:
        """Ensure every individual has a fitness.

        Batched TPU path: if the species' fitness model exposes
        ``cross_validate_population`` (see ``models/cnn.py``), all unevaluated
        individuals with identical ``additional_parameters`` are trained in
        one vmapped program.  Falls back to the reference's sequential lazy
        loop otherwise (SURVEY.md §3.1).
        """
        pending = [ind for ind in self.individuals if not ind.fitness_evaluated]
        if not pending:
            return
        if not self._evaluate_batched(pending):
            for ind in pending:
                ind.get_fitness()

    def _evaluate_batched(self, pending: List[Individual]) -> bool:
        """Try the single-program population evaluation; True on success."""
        if self.x_train is None or self.y_train is None:
            return False
        model_cls = getattr(self.species, "model_cls", None)
        if model_cls is None:
            from .individuals import GeneticCnnIndividual

            if not issubclass(self.species, GeneticCnnIndividual):
                return False
            try:
                from .models.cnn import GeneticCnnModel
            except Exception:  # pragma: no cover - jax missing
                return False
            model_cls = GeneticCnnModel
        batch_fn = getattr(model_cls, "cross_validate_population", None)
        if batch_fn is None:
            return False
        # Batched evaluation requires one shared config across the population.
        # Individuals added via add_individual() can carry divergent
        # additional_parameters (e.g. different stage sizes); those must take
        # the sequential path or they'd be decoded under the wrong config.
        if any(ind.additional_parameters != self.additional_parameters for ind in pending):
            return False
        genomes = [ind.get_genes() for ind in pending]
        fitnesses = batch_fn(self.x_train, self.y_train, genomes, **self.additional_parameters)
        for ind, fit in zip(pending, fitnesses):
            ind.set_fitness(float(fit))
        return True

    # -- generational continuity ------------------------------------------

    def clone_with(self, individuals: Sequence[Individual]) -> "Population":
        """A next-generation population with this one's config and data.

        The GA outer loop calls this instead of naming a class, so
        subclasses (notably ``DistributedPopulation``, which must carry its
        broker across generations) stay subclasses through evolution.
        ``GridPopulation`` deliberately degrades to a plain ``Population``:
        grid enumeration only describes generation zero.
        """
        return Population(
            species=self.species,
            x_train=self.x_train,
            y_train=self.y_train,
            individual_list=list(individuals),
            crossover_rate=self.crossover_rate,
            mutation_rate=self.mutation_rate,
            maximize=self.maximize,
            additional_parameters=self.additional_parameters,
            rng=self.rng,
        )

    def get_fittest(self) -> Individual:
        """Best individual under the population's direction (evaluating lazily)."""
        self.evaluate()
        key = lambda ind: ind.get_fitness()
        return max(self.individuals, key=key) if self.maximize else min(self.individuals, key=key)

    def get_fitnesses(self) -> List[float]:
        self.evaluate()
        return [ind.get_fitness() for ind in self.individuals]


class GridPopulation(Population):
    """Population initialised from the cartesian product of per-gene grids.

    Mirrors gentun's ``GridPopulation`` (``gentun/populations.py`` [PUB];
    SURVEY.md §2.3 "Initialization"): instead of random genomes, enumerate
    every combination of the provided per-gene value lists.

    ``genes_grid`` maps gene name → list of values; genes not present use
    their full ``grid_values()`` (careful: binary genes enumerate 2**length).
    """

    def __init__(
        self,
        species: Type[Individual],
        x_train=None,
        y_train=None,
        genes_grid: Optional[Mapping[str, Sequence[Any]]] = None,
        crossover_rate: float = 0.5,
        mutation_rate: float = 0.015,
        maximize: bool = True,
        additional_parameters: Optional[Dict[str, Any]] = None,
        seed: Optional[int] = None,
        rng: Optional[np.random.Generator] = None,
    ):
        super().__init__(
            species,
            x_train=x_train,
            y_train=y_train,
            individual_list=[],
            crossover_rate=crossover_rate,
            mutation_rate=mutation_rate,
            maximize=maximize,
            additional_parameters=additional_parameters,
            seed=seed,
            rng=rng,
        )
        self.populate_from_grid(genes_grid)
