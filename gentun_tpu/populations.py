"""Populations: collections of individuals sharing training data.

Reference parity: ``Population`` and ``GridPopulation`` in
``gentun/populations.py`` [PUB] (SURVEY.md §2.0 row 4).  A population holds
the individuals plus the shared ``(x_train, y_train)`` and the ``maximize``
flag; it knows how to random-init ``size`` individuals, enumerate a grid of
gene values, and report the fittest member.

TPU-first departure from the reference: :meth:`Population.evaluate` is a
first-class population-level operation.  When the species' fitness model
supports it, the *whole population* is evaluated in a single batched
(vmapped) XLA program — every genome shares one compiled supergraph, so
evaluating N individuals costs one compile + one batched train instead of N
sequential Keras fits (SURVEY.md §7 "hard parts" #1, the main
individuals/hour/chip lever).  The per-individual lazy path
(``Individual.get_fitness``) still works and is what distributed workers use.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Mapping, Optional, Sequence, Type

import numpy as np

from .individuals import Individual
from .telemetry import lineage as _lineage
from .telemetry import spans as _tele
from .telemetry.registry import get_registry as _get_registry

__all__ = ["Population", "GridPopulation"]

logger = logging.getLogger("gentun_tpu")

#: species whose cache_key() already raised once (log each species once)
_cache_key_warned: set = set()

#: memo sentinel: this individual's key is known-unusable, don't retry
_UNCACHEABLE = object()


def _compile_bucket(n: int) -> int:
    """Mirror of ``models/cnn._pop_bucket`` (kept jax-free here: the GA
    path must never import jax).  ``tests/test_populations_speculative.py``
    asserts the two stay in lockstep."""
    if n >= 16:
        return n
    b = 2  # floor 2, matching _pop_bucket: singleton programs are
    while b < n:  # numerically distinct (see models/cnn._pop_bucket)
        b *= 2
    return b


class Population:
    """A fixed-size set of individuals of one species.

    Args mirror the reference constructor (``gentun/populations.py`` [PUB]):
    ``species`` (the Individual subclass), shared data, either ``size`` for
    random init or an explicit ``individual_list``, operator rates, the
    optimisation direction, and ``additional_parameters`` forwarded to every
    individual.  ``seed`` is new: it makes the whole run reproducible.
    """

    def __init__(
        self,
        species: Type[Individual],
        x_train=None,
        y_train=None,
        individual_list: Optional[Sequence[Individual]] = None,
        size: Optional[int] = None,
        crossover_rate: float = 0.5,
        mutation_rate: float = 0.015,
        maximize: bool = True,
        additional_parameters: Optional[Dict[str, Any]] = None,
        seed: Optional[int] = None,
        rng: Optional[np.random.Generator] = None,
        fitness_cache: Optional[Dict[Any, float]] = None,
        speculative_fill=False,
    ):
        self.species = species
        self.x_train = x_train
        self.y_train = y_train
        self.crossover_rate = crossover_rate
        self.mutation_rate = mutation_rate
        self.maximize = maximize
        #: False = off; True = fill only the compile bucket's padding slots
        #: (free); int N = fill small batches up to at least N (opt-in cost).
        self.speculative_fill = speculative_fill
        self.additional_parameters = dict(additional_parameters or {})
        self.rng = rng if rng is not None else np.random.default_rng(seed)
        # Fitness by Individual.cache_key(): shared across generations via
        # clone_with, so an architecture (not just an Individual object) is
        # trained at most once per search (SURVEY.md §7 hard part #1).
        self.fitness_cache: Dict[Any, float] = fitness_cache if fitness_cache is not None else {}

        if individual_list is not None:
            self.individuals: List[Individual] = list(individual_list)
        elif size is not None:
            self.individuals = [self.spawn() for _ in range(size)]
            if _lineage.enabled():
                # Random init is where every founder lineage starts: record
                # the births here (not in spawn(), which the ladder and
                # promotion probes also call for genome *copies*).
                for ind in self.individuals:
                    _lineage.record(
                        "born", _lineage.genome_key(ind.get_genes()),
                        op="spawn", genes=ind.get_genes())
        else:
            raise ValueError("provide either `size` or `individual_list`")

    # -- construction ------------------------------------------------------

    def spawn(
        self,
        genes: Optional[Mapping[str, Any]] = None,
        additional_parameters: Optional[Mapping[str, Any]] = None,
    ) -> Individual:
        """Create one individual of this population's species.

        ``additional_parameters`` overrides the population's own config for
        this ONE individual — the multi-fidelity engine uses it to dispatch
        the same genes under per-rung training schedules (the cache key
        embeds the merged config, so rungs never share fitness entries).
        """
        params = dict(self.additional_parameters)
        if additional_parameters is not None:
            params.update(additional_parameters)
        return self.species(
            x_train=self.x_train,
            y_train=self.y_train,
            genes=dict(genes) if genes is not None else None,
            crossover_rate=self.crossover_rate,
            mutation_rate=self.mutation_rate,
            maximize=self.maximize,
            rng=self.rng,
            additional_parameters=params,
        )

    def add_individual(self, individual: Individual) -> None:
        self.individuals.append(individual)

    # -- steady-state (asynchronous) membership ----------------------------
    #
    # The async engine (algorithms_async.AsyncEvolution) treats the
    # individuals list as an AGE-ORDERED ring: index 0 is the oldest member,
    # appends are the youngest.  Insert/evict are incremental — no
    # generation-sized rebuild, no clone_with — so a completed evaluation
    # updates membership in O(1)/O(n) while other evaluations stay in flight.

    def insert(self, individual: Individual) -> None:
        """Append ``individual`` as the population's youngest member."""
        self.individuals.append(individual)

    def evict_oldest(self, require_evaluated: bool = True) -> Optional[Individual]:
        """Remove and return the oldest member (aging eviction, Real et al.
        2019: age, not fitness, decides who dies — the regularization that
        forces rediscovery of good architectures).

        With ``require_evaluated`` (the default) the oldest EVALUATED member
        goes instead, skipping members whose evaluation is still in flight —
        evicting those would orphan a result the scheduler already paid for.
        Returns None when no member is eligible.
        """
        for i, ind in enumerate(self.individuals):
            if not require_evaluated or ind.fitness_evaluated:
                return self.individuals.pop(i)
        return None

    def populate_from_grid(self, genes_grid: Optional[Mapping[str, Sequence[Any]]] = None) -> None:
        """Append one individual per point of the gene-value grid.

        Shared by ``GridPopulation`` and ``DistributedGridPopulation``
        (SURVEY.md §2.0 rows 4, 10): enumeration itself lives in
        :meth:`GenomeSpec.grid`.
        """
        probe = self.spawn()
        for genome in probe.spec.grid(gene_values=genes_grid):
            self.add_individual(self.spawn(genes=genome))

    # -- container protocol (gentun exposes the same) ----------------------

    def __len__(self) -> int:
        return len(self.individuals)

    def get_size(self) -> int:
        return len(self.individuals)

    def __getitem__(self, item: int) -> Individual:
        return self.individuals[item]

    def __iter__(self):
        return iter(self.individuals)

    def get_species(self) -> Type[Individual]:
        return self.species

    def get_data(self):
        return self.x_train, self.y_train

    # -- fitness -----------------------------------------------------------

    def evaluate(self) -> int:
        """Ensure every individual has a fitness; returns the number that
        actually *trained* (cache hits and dedup'd duplicates don't count —
        the GA uses this for the individuals/hour/chip metric).

        Order of attack, each step narrowing the pending set:

        1. **cache** — individuals whose :meth:`Individual.cache_key` was
           already trained (this generation or an earlier one, via the
           cache ``clone_with`` carries forward) get the stored fitness;
        2. **dedup** — of the rest, one representative per distinct key
           trains; duplicates inherit its result;
        3. **group-wise batched training** — representatives are grouped by
           ``additional_parameters`` and each group trains as ONE vmapped
           program when the species' model exposes
           ``cross_validate_population`` (``models/cnn.py``) — divergent
           configs no longer force the whole population sequential;
        4. **sequential fallback** — anything else takes the reference's
           lazy per-individual path (SURVEY.md §3.1).
        """
        # Telemetry (docs/OBSERVABILITY.md): counters are incremented once
        # per aggregate — never per individual — and only when enabled, so
        # the disabled path does no extra work beyond one bool read.
        tele = _tele.enabled()
        pending = [ind for ind in self.individuals if not ind.fitness_evaluated]
        n_before = len(pending)
        pending = self._fill_from_cache(pending)
        if tele and n_before > len(pending):
            _get_registry().counter(
                "population_cache_hits_total", species=self.species.__name__,
            ).inc(n_before - len(pending))
        trained = 0
        for group in self._group_by_params(pending):
            reps = self._dedupe_group(group)
            if tele and len(group) > len(reps):
                _get_registry().counter(
                    "population_dedup_collapsed_total", species=self.species.__name__,
                ).inc(len(group) - len(reps))
            batch = reps
            spec: List[Individual] = []
            if self.speculative_fill and reps and self._batch_fn(reps) is not None:
                # Tail-generation mitigation (VERDICT r4 weak #2): the
                # compile-shape bucket pads a small batch anyway, and the
                # padding slots train DISCARDED dummy genomes.  Fill them
                # with mutated copies of the current elite instead — near
                # convergence most children ARE small mutations of the
                # elite, so these results cache-hit future generations.
                # speculative_fill=True fills only the existing padding
                # slots (strictly free); an int raises the fill target to
                # that batch size (extra compute traded for cache hits —
                # use a bucket size, e.g. 8 or 16, to reuse compiled shapes).
                seen = {k for k in (self._safe_cache_key(i) for i in reps) if k is not None}
                spec = self._speculative_individuals(
                    self._fill_target(len(reps), reps[0].additional_parameters) - len(reps),
                    seen,
                    template=reps[0],
                )
                batch = reps + spec
                if tele and spec:
                    _get_registry().counter(
                        "population_speculative_total", species=self.species.__name__,
                    ).inc(len(spec))
            # The `train` span covers the group's actual compute — batched
            # OR the sequential fallback — so every species (a worker-side
            # OneMax as much as a vmapped CNN) reports training time.
            # cnn.py's finer compile/train/eval spans nest inside this one.
            # Forensics (docs/OBSERVABILITY.md "Search forensics"): local
            # evaluation attributes its own device-seconds — an even share
            # of the group's train wall time per representative.  Skipped
            # inside a worker capture (the worker's own per-job device
            # spans are the ones the broker bills — never both).
            lin = _lineage.enabled() and not _tele.capturing()
            t_train0 = time.monotonic()
            if tele:
                with _tele.span("train", {"individuals": len(batch),
                                          "species": self.species.__name__}) as sp:
                    batched_ok = self._train_group(batch, reps)
                    sp.set(batched=batched_ok)
            else:
                batched_ok = self._train_group(batch, reps)
            if lin and reps:
                share = (time.monotonic() - t_train0) / len(reps)
                for i, ind in enumerate(reps):
                    _lineage.emit_device(
                        share, _lineage.genome_key(ind.get_genes()),
                        rung=(getattr(ind, "_fidelity_tag", None)
                              or {}).get("rung", 0),
                        start_monotonic=t_train0 + i * share)
            if batched_ok:
                for ind in spec:
                    key = self._safe_cache_key(ind)
                    if key is not None:
                        self.fitness_cache[key] = ind.get_fitness()
            trained += len(reps)
            self._publish_group(group, reps)
        return trained

    def predispatch(self) -> int:
        """Breed-ahead hook: start this population's fitness work early.

        Local evaluation has nowhere to send work ahead of time, so the
        base class is a no-op returning 0 — the knob
        (``GeneticAlgorithm(breed_ahead=True)``) is harmless without a
        fleet.  ``DistributedPopulation`` overrides this to ship the
        cache-missed individuals to the broker immediately and lets the
        next ``evaluate()`` adopt the in-flight jobs (DISTRIBUTED.md
        "Pipelined dispatch").
        """
        return 0

    def _train_group(self, batch: List[Individual], reps: List[Individual]) -> bool:
        """Train one parameter-group: batched if the species supports it,
        else the reference's sequential per-individual path.  Returns
        whether the batched path ran (speculative results only exist
        then)."""
        if self._evaluate_batched(batch):
            return True
        for ind in reps:  # sequential fallback: skip speculation
            ind.get_fitness()
        return False

    def _fill_target(self, n_real: int, params: Optional[Mapping[str, Any]] = None) -> int:
        """Batch size speculation fills to: the compile bucket (free mode,
        ``speculative_fill=True``), or at least the configured int target.

        With ``pop_padding=False`` in the group's config the model pads
        nothing, so free mode has NO free slots — only an explicit int
        target adds (paid-for) speculation there.
        """
        pads = (params or {}).get("pop_padding", True)
        target = _compile_bucket(n_real) if pads else n_real
        if self.speculative_fill is not True and self.speculative_fill:
            target = max(target, int(self.speculative_fill))
        return target

    def _speculative_individuals(
        self, n_slots: int, exclude_keys: set, template: Optional["Individual"] = None
    ) -> List["Individual"]:
        """Up to ``n_slots`` fresh unevaluated individuals speculatively
        worth training: mutated copies of the best already-evaluated member
        (the GA's future children concentrate around the elite).  The
        children are built from ``template`` (an individual of the batch
        being trained) so they carry the BATCH's additional_parameters —
        caching an elite-genes mutant trained under another group's config
        would poison the cache.  Never duplicates a pending key, a cached
        architecture, or another speculative pick; returns [] when there is
        no evaluated member yet (generation 0 fills its bucket with real
        work anyway)."""
        if n_slots <= 0:
            return []
        evaluated = [i for i in self.individuals if i.fitness_evaluated]
        if not evaluated:
            return []
        key_fn = lambda i: i.get_fitness()
        parent = max(evaluated, key=key_fn) if self.maximize else min(evaluated, key=key_fn)
        if template is None:
            template = parent
        # Speculation must NOT perturb the search: drawing mutants from
        # self.rng would shift every subsequent selection/reproduction draw,
        # making a speculative run a different search from a non-speculative
        # one under the same seed.  A dedicated deterministic stream keeps
        # trajectories identical with the feature on or off.
        spec_rng = getattr(self, "_spec_rng", None)
        if spec_rng is None:
            spec_rng = self._spec_rng = np.random.default_rng(0x5BEC)
        # The mutate-until-changed loop compares against the parent's GENES
        # under the template's params, so cross-group gene seeding works.
        base_key = self._safe_cache_key(template.copy(genes=parent.get_genes()))
        out: List[Individual] = []
        for _ in range(4 * n_slots):  # bounded attempts: duplicates happen
            if len(out) >= n_slots:
                break
            child = template.copy(genes=parent.get_genes())
            # At reference mutation rates (~0.015/bit) a single mutate() is
            # usually a no-op; keep mutating until the ARCHITECTURE actually
            # changes (bounded — a rate of 0 must not spin forever).
            key = None
            for _ in range(32):
                child.mutate(spec_rng)
                key = self._safe_cache_key(child)
                if key is not None and key != base_key:
                    break
            if key is None or key == base_key or key in exclude_keys or key in self.fitness_cache:
                continue
            exclude_keys.add(key)
            out.append(child)
        return out

    # -- cache / dedup plumbing -------------------------------------------

    @staticmethod
    def _safe_cache_key(ind: Individual):
        """``ind.cache_key()``, or None (= never cached) if it can't be built
        or isn't usable as a dict key (hashable).

        A failure downgrades the search to cache-less behavior (correct but
        retrains every genome), so the first one per species is logged loudly
        rather than swallowed.  The key is memoized on the individual
        (invalidated by ``set_genes``/``mutate``): canonicalising a
        Genetic-CNN DAG is not free, and evaluate() needs the key at several
        steps per generation.
        """
        memo = getattr(ind, "_cache_key_memo", None)
        if memo is not None:
            return None if memo is _UNCACHEABLE else memo
        try:
            key = ind.cache_key()
            hash(key)  # must be usable for dict lookup, not merely built
        except Exception:
            ind._cache_key_memo = _UNCACHEABLE
            species = type(ind).__name__
            if species not in _cache_key_warned:
                _cache_key_warned.add(species)
                logger.warning(
                    "cache_key() failed for species %s — fitness caching and "
                    "dedup are DISABLED for it (every genome will retrain)",
                    species,
                    exc_info=True,
                )
            return None
        ind._cache_key_memo = key
        return key

    def _fill_from_cache(self, pending: List[Individual]) -> List[Individual]:
        """Assign cached fitnesses; return the individuals still unevaluated."""
        remaining: List[Individual] = []
        for ind in pending:
            key = self._safe_cache_key(ind)
            if key is not None and key in self.fitness_cache:
                ind.set_fitness(self.fitness_cache[key])
            else:
                remaining.append(ind)
        return remaining

    @staticmethod
    def _group_by_params(pending: List[Individual]) -> List[List[Individual]]:
        """Partition by ``additional_parameters`` (batched training needs one
        shared config per compiled program — same grouping the distributed
        worker applies, ``distributed/client.py``).  Keys via ``_freeze``:
        collision-free even for numpy-array params, unlike ``repr``."""
        from .individuals import _freeze

        groups: Dict[Any, List[Individual]] = {}
        for ind in pending:
            try:
                key = _freeze(ind.additional_parameters)
                hash(key)
            except TypeError:
                # Unhashable config (e.g. a bytearray param): degrade that
                # individual to its own sequential group instead of crashing.
                key = ("__unhashable__", id(ind))
            groups.setdefault(key, []).append(ind)
        return list(groups.values())

    def _dedupe_group(self, group: List[Individual]) -> List[Individual]:
        """First individual per distinct cache key; un-keyable ones all pass."""
        reps: List[Individual] = []
        seen = set()
        for ind in group:
            key = self._safe_cache_key(ind)
            if key is None or key not in seen:
                if key is not None:
                    seen.add(key)
                reps.append(ind)
        return reps

    def _publish_group(self, group: List[Individual], reps: List[Individual]) -> None:
        """Store representatives' results in the cache; fan out to duplicates."""
        for ind in reps:
            key = self._safe_cache_key(ind)
            if key is not None:
                self.fitness_cache[key] = ind.get_fitness()
        for ind in group:
            if not ind.fitness_evaluated:
                ind.set_fitness(self.fitness_cache[self._safe_cache_key(ind)])

    def _batch_fn(self, pending: List[Individual]):
        """The species' population-batched trainer, or None when the group
        can only evaluate sequentially.  Checked BEFORE speculation so
        sequential species never pay the mutant-generation cost."""
        if self.x_train is None or self.y_train is None:
            return None
        model_cls = getattr(self.species, "model_cls", None)
        if model_cls is None:
            from .individuals import GeneticCnnIndividual

            if not issubclass(self.species, GeneticCnnIndividual):
                return None
            try:
                from .models.cnn import GeneticCnnModel
            except Exception:  # pragma: no cover - jax missing
                return None
            model_cls = GeneticCnnModel
        return getattr(model_cls, "cross_validate_population", None)

    def _evaluate_batched(self, pending: List[Individual]) -> bool:
        """Try the single-program batched evaluation; True on success.

        ``pending`` shares one ``additional_parameters`` dict by construction
        (:meth:`_group_by_params`), so the whole group decodes under one
        config and trains as one vmapped XLA program.
        """
        if not pending:
            return True
        batch_fn = self._batch_fn(pending)
        if batch_fn is None:
            return False
        params = pending[0].additional_parameters
        genomes = [ind.get_genes() for ind in pending]
        fitnesses = batch_fn(self.x_train, self.y_train, genomes, **params)
        for ind, fit in zip(pending, fitnesses):
            ind.set_fitness(float(fit))
        return True

    # -- generational continuity ------------------------------------------

    def clone_with(self, individuals: Sequence[Individual]) -> "Population":
        """A next-generation population with this one's config and data.

        The GA outer loop calls this instead of naming a class, so
        subclasses (notably ``DistributedPopulation``, which must carry its
        broker across generations) stay subclasses through evolution.
        ``GridPopulation`` deliberately degrades to a plain ``Population``:
        grid enumeration only describes generation zero.
        """
        clone = Population(
            species=self.species,
            x_train=self.x_train,
            y_train=self.y_train,
            individual_list=list(individuals),
            crossover_rate=self.crossover_rate,
            mutation_rate=self.mutation_rate,
            maximize=self.maximize,
            additional_parameters=self.additional_parameters,
            rng=self.rng,
            fitness_cache=self.fitness_cache,
            speculative_fill=self.speculative_fill,
        )
        self._carry_spec_rng(clone)
        return clone

    def _carry_spec_rng(self, clone: "Population") -> None:
        """Carry the speculative RNG stream across generations (like
        fitness_cache): re-seeding each clone would replay already-cached
        elite mutants until the bounded attempt budget starves and
        speculation silently stops filling slots."""
        spec_rng = getattr(self, "_spec_rng", None)
        if spec_rng is not None:
            clone._spec_rng = spec_rng

    def get_fittest(self) -> Individual:
        """Best individual under the population's direction (evaluating lazily)."""
        self.evaluate()
        key = lambda ind: ind.get_fitness()
        return max(self.individuals, key=key) if self.maximize else min(self.individuals, key=key)

    def get_fitnesses(self) -> List[float]:
        self.evaluate()
        return [ind.get_fitness() for ind in self.individuals]


class GridPopulation(Population):
    """Population initialised from the cartesian product of per-gene grids.

    Mirrors gentun's ``GridPopulation`` (``gentun/populations.py`` [PUB];
    SURVEY.md §2.3 "Initialization"): instead of random genomes, enumerate
    every combination of the provided per-gene value lists.

    ``genes_grid`` maps gene name → list of values; genes not present use
    their full ``grid_values()`` (careful: binary genes enumerate 2**length).
    """

    def __init__(
        self,
        species: Type[Individual],
        x_train=None,
        y_train=None,
        genes_grid: Optional[Mapping[str, Sequence[Any]]] = None,
        crossover_rate: float = 0.5,
        mutation_rate: float = 0.015,
        maximize: bool = True,
        additional_parameters: Optional[Dict[str, Any]] = None,
        seed: Optional[int] = None,
        rng: Optional[np.random.Generator] = None,
    ):
        super().__init__(
            species,
            x_train=x_train,
            y_train=y_train,
            individual_list=[],
            crossover_rate=crossover_rate,
            mutation_rate=mutation_rate,
            maximize=maximize,
            additional_parameters=additional_parameters,
            seed=seed,
            rng=rng,
        )
        self.populate_from_grid(genes_grid)
