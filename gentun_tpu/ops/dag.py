"""Genetic-CNN DAG decoding: bit-strings → stage mask arrays.

Reference parity: gentun decodes each stage's bit-string into a Keras graph of
``Conv+ReLU`` nodes at model-build time (``gentun/models/keras_models.py``
[PUB]; SURVEY.md §2.3 "Encoding", §3.4).  The decode rules are the Xie &
Yuille (ICCV 2017) rules the reference implements:

- gene ``S_s`` has ``K_s * (K_s - 1) / 2`` bits, one per ordered node pair
  ``(i, j)`` with ``i < j``, grouped by target node: the first bit is edge
  1→2, the next two are 1→3 and 2→3, and so on;
- a node with neither in- nor out-edges is *isolated* and dropped entirely;
- every non-isolated node with no in-edges is fed by the stage's default
  input node;
- every non-isolated node with no out-edges feeds the stage's default
  output node;
- multi-input nodes element-wise **sum** their inputs.

TPU-first departure (the core architectural decision of this rebuild,
SURVEY.md §7 "hard parts" #1): instead of building a different program per
genome — which would pay an XLA compile per individual — the decode produces
fixed-shape **mask arrays** over a stage *supergraph* of all ``K_s`` nodes.
The masks are plain data: one jitted train step serves every genome in the
search space, and a population axis can be ``vmap``-ed over the masks so the
whole population trains as a single batched XLA program.

Everything in this module is pure numpy (no jax import): it runs on the host,
once per genome, and is trivially testable by exhaustive enumeration.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Mapping, Sequence, Tuple

import numpy as np

__all__ = [
    "StageMasks",
    "triangular_index",
    "bits_to_adjacency",
    "adjacency_to_bits",
    "decode_stage",
    "decode_genome",
    "stack_genome_masks",
    "canonical_key",
]


def triangular_index(i: int, j: int) -> int:
    """Position of edge ``i → j`` (``i < j``) in the stage bit-string.

    Bits are grouped by target node j: edges into node j occupy positions
    ``j*(j-1)/2 ... j*(j-1)/2 + j - 1``, ordered by source i.  (Nodes are
    0-indexed here; the paper's node 1 is index 0.)
    """
    if not 0 <= i < j:
        raise ValueError(f"need 0 <= i < j, got ({i}, {j})")
    return j * (j - 1) // 2 + i


def bits_to_adjacency(bits: Sequence[int], k: int) -> np.ndarray:
    """Bit-string → strictly-upper-triangular adjacency matrix ``(k, k)``."""
    bits = np.asarray(bits, dtype=np.int64)
    expected = k * (k - 1) // 2
    if bits.shape != (expected,):
        raise ValueError(f"stage with {k} nodes needs {expected} bits, got {bits.shape}")
    adj = np.zeros((k, k), dtype=np.float32)
    for j in range(1, k):
        base = j * (j - 1) // 2
        adj[:j, j] = bits[base : base + j]
    return adj


def adjacency_to_bits(adj: np.ndarray) -> Tuple[int, ...]:
    """Inverse of :func:`bits_to_adjacency` (used by tests / canonicalization)."""
    k = adj.shape[0]
    out: List[int] = []
    for j in range(1, k):
        out.extend(int(adj[i, j]) for i in range(j))
    return tuple(out)


@dataclasses.dataclass(frozen=True)
class StageMasks:
    """Fixed-shape masks describing one stage's DAG on the node supergraph.

    Attributes (all float32, shapes fixed by the node count ``k`` alone):

    - ``adj``: ``(k, k)`` strictly upper triangular; ``adj[i, j] == 1`` ⇒
      node i's output is summed into node j's input.
    - ``active``: ``(k,)``; 0 for isolated (dropped) nodes.  An inactive
      node's output is forced to zero so it cannot leak into any sum.
    - ``entry``: ``(k,)``; 1 ⇒ the stage input feeds this node.
    - ``exit``: ``(k,)``; 1 ⇒ this node's output is summed into the stage
      output.
    - ``has_active``: scalar; 0 ⇒ the stage has no active nodes and the
      stage output is the *default input node* (in the consumer,
      ``models/cnn.py``, that is the stage-entry Conv+ReLU output — not the
      raw stage input); pooling still applies.
    """

    adj: np.ndarray
    active: np.ndarray
    entry: np.ndarray
    exit: np.ndarray
    has_active: np.ndarray

    @property
    def k(self) -> int:
        return int(self.adj.shape[0])


def decode_stage(bits: Sequence[int], k: int) -> StageMasks:
    """Apply the Xie & Yuille decode rules to one stage's bit-string."""
    adj = bits_to_adjacency(bits, k)
    in_deg = adj.sum(axis=0)
    out_deg = adj.sum(axis=1)
    isolated = (in_deg == 0) & (out_deg == 0)
    active = (~isolated).astype(np.float32)
    entry = ((in_deg == 0) & ~isolated).astype(np.float32)
    exit_ = ((out_deg == 0) & ~isolated).astype(np.float32)
    has_active = np.float32(1.0 if active.any() else 0.0)
    # Zero out edges touching inactive nodes (defensive: by construction an
    # edge implies both endpoints active, so this is a no-op; it guarantees
    # the invariant for hand-built adjacency matrices too).
    adj = adj * active[:, None] * active[None, :]
    return StageMasks(adj=adj, active=active, entry=entry, exit=exit_, has_active=has_active)


def decode_genome(
    genes: Mapping[str, Any],
    nodes: Sequence[int],
) -> List[StageMasks]:
    """Decode a full genome dict ``{"S_1": bits, ...}`` into per-stage masks.

    Gene naming matches :func:`gentun_tpu.genes.genetic_cnn_genome`: stage
    ``s`` (1-based) has gene ``S_s`` with ``K_s(K_s-1)/2`` bits.
    """
    masks = []
    for s, k in enumerate(nodes):
        name = f"S_{s + 1}"
        if name not in genes:
            raise KeyError(f"genome missing gene {name!r} for stage {s + 1}")
        masks.append(decode_stage(genes[name], k))
    return masks


def stack_genome_masks(
    genomes: Sequence[Mapping[str, Any]],
    nodes: Sequence[int],
) -> List[Dict[str, np.ndarray]]:
    """Stack P genomes' masks along a leading population axis, per stage.

    Returns one dict per stage with keys ``adj (P,k,k)``, ``active (P,k)``,
    ``entry (P,k)``, ``exit (P,k)``, ``has_active (P,)`` — the exact pytree
    the population-batched (vmapped) train step consumes (``models/cnn.py``).
    """
    per_stage: List[Dict[str, np.ndarray]] = []
    decoded = [decode_genome(g, nodes) for g in genomes]
    for s in range(len(nodes)):
        stage = [d[s] for d in decoded]
        per_stage.append(
            {
                "adj": np.stack([m.adj for m in stage]),
                "active": np.stack([m.active for m in stage]),
                "entry": np.stack([m.entry for m in stage]),
                "exit": np.stack([m.exit for m in stage]),
                "has_active": np.stack([m.has_active for m in stage]),
            }
        )
    return per_stage


def _canonical_stage_bits(bits: Sequence[int], k: int, max_brute_k: int = 6) -> Tuple[int, ...]:
    """Lexicographically-smallest bit-string over DAG-preserving relabelings.

    Distinct bit-strings can decode to *architecturally identical* networks:
    every stage node is the same Conv+ReLU block, so any relabeling of nodes
    that keeps edges pointing from lower to higher index (a linear extension
    of the DAG) yields the same computation.  E.g. for k=3, the single-edge
    graphs 1→2 and 2→3 are both "a 2-node chain plus one isolated node".
    Canonicalising collapses these so the fitness cache / dedup layer never
    trains the same architecture twice (SURVEY.md §7 "hard parts" #1).

    Brute force over all k! permutations, keeping those that preserve
    upper-triangularity; fine for the reference's stage sizes (k ≤ 5 ⇒ ≤120
    permutations).  Stages larger than ``max_brute_k`` fall back to the raw
    bits (correct, just less dedup).
    """
    if k > max_brute_k:
        return tuple(int(b) for b in bits)
    import itertools

    adj = bits_to_adjacency(bits, k).astype(np.int64)
    best: Tuple[int, ...] | None = None
    for perm in itertools.permutations(range(k)):
        p = np.asarray(perm)
        relabeled = adj[np.ix_(p, p)]
        if np.any(np.tril(relabeled)):  # not a linear extension
            continue
        candidate = adjacency_to_bits(relabeled)
        if best is None or candidate < best:
            best = candidate
    assert best is not None  # identity permutation always qualifies
    return best


def canonical_key(genes: Mapping[str, Any], nodes: Sequence[int]) -> Tuple[Tuple[int, ...], ...]:
    """A hashable key identifying the *effective* architecture of a genome.

    Two genomes get the same key iff their decoded stages are identical up to
    the node relabelings of :func:`_canonical_stage_bits`.  Used for fitness
    caching across generations and population-level dedup.
    """
    out = []
    for s, k in enumerate(nodes):
        out.append(_canonical_stage_bits(genes[f"S_{s + 1}"], k))
    return tuple(out)
