"""Dataset loaders for the canonical workloads (BASELINE.md configs).

The reference's examples load MNIST/CIFAR via Keras and UCI tables from
disk (gentun examples [PUB]).  This machine has NO network (SURVEY.md §0),
so each loader resolves in priority order:

1. a real on-disk copy, if ``data_dir`` (or ``GENTUN_TPU_DATA``) points at
   numpy archives of the expected shape;
2. real sklearn-bundled data where a faithful stand-in exists
   (``load_digits`` for MNIST-class work, ``load_wine`` /
   ``load_breast_cancer`` for the UCI control path — these ship with
   sklearn, no download);
3. deterministic synthetic data of the exact target shape (class
   prototypes + Gaussian noise), clearly flagged in the return value.

Every loader returns ``(x, y, meta)`` with ``meta["synthetic"]`` telling
the caller (and the benchmark record) what it actually got.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Tuple

import numpy as np

__all__ = [
    "load_mnist",
    "load_cifar10",
    "load_cifar100",
    "load_uci_wine",
    "load_uci_binary",
    "synthetic_images",
]

Arrays = Tuple[np.ndarray, np.ndarray, Dict[str, Any]]


def _data_dir(data_dir: Optional[str]) -> Optional[str]:
    return data_dir or os.environ.get("GENTUN_TPU_DATA")


def _try_npz(data_dir: Optional[str], name: str, shape_hwc: Tuple[int, int, int]) -> Optional[Arrays]:
    d = _data_dir(data_dir)
    if not d:
        return None
    path = os.path.join(d, f"{name}.npz")
    if not os.path.exists(path):
        return None
    with np.load(path) as z:
        x, y = np.asarray(z["x"], np.float32), np.asarray(z["y"], np.int32)
    if x.ndim == 3:
        x = x[..., None]
    if x.shape[1:] != shape_hwc:
        raise ValueError(f"{path}: expected images {shape_hwc}, got {x.shape[1:]}")
    if x.max() > 1.5:  # raw 0-255 → normalise
        x = x / 255.0
    return x, y, {"synthetic": False, "source": path}


def synthetic_images(
    n: int,
    shape_hwc: Tuple[int, int, int],
    n_classes: int,
    noise: float = 0.5,
    seed: int = 0,
    sample_seed: Optional[int] = None,
) -> Arrays:
    """Class-prototype + noise images: learnable, deterministic, any shape.

    ``sample_seed`` draws the *samples* (labels + noise) from a separate
    stream while keeping the class prototypes from ``seed`` — i.e. a fresh
    disjoint draw from the SAME underlying task.  Use it to build a holdout
    set for a training set generated with ``sample_seed=None``: the default
    path is bit-identical to the original single-stream draw, so existing
    artifacts and seeded comparisons are unaffected.
    """
    if sample_seed == seed:
        raise ValueError(
            "sample_seed must differ from seed: equal seeds would draw the "
            "samples from the same stream positions that generated the class "
            "prototypes, correlating the 'fresh' noise with the task itself"
        )
    rng = np.random.default_rng(seed)
    protos = rng.normal(size=(n_classes, *shape_hwc)).astype(np.float32)
    if sample_seed is not None:
        rng = np.random.default_rng(sample_seed)
    y = rng.integers(0, n_classes, size=n).astype(np.int32)
    x = protos[y] + noise * rng.normal(size=(n, *shape_hwc)).astype(np.float32)
    src = f"synthetic(seed={seed})" if sample_seed is None else f"synthetic(seed={seed},sample_seed={sample_seed})"
    return x, y, {"synthetic": True, "source": src}


def load_mnist(n: Optional[int] = None, data_dir: Optional[str] = None, seed: int = 0) -> Arrays:
    """28×28×1, 10 classes (BASELINE config #1).

    Fallback #2 is sklearn's real ``load_digits`` (1797 genuine handwritten
    digits at 8×8) upscaled to 28×28 — real data beats synthetic for
    accuracy comparisons even if the resolution is nearer MNIST-small.
    """
    found = _try_npz(data_dir, "mnist", (28, 28, 1))
    if found is not None:
        x, y, meta = found
    else:
        try:
            from sklearn.datasets import load_digits

            digits = load_digits()
            imgs = digits.images.astype(np.float32) / 16.0  # (1797, 8, 8)
            x = np.repeat(np.repeat(imgs, 4, axis=1), 4, axis=2)[..., None]  # 8×8 → 32×32
            x = x[:, 2:30, 2:30, :]  # centre-crop 32 → 28, the canonical shape
            y = digits.target.astype(np.int32)
            meta = {"synthetic": False, "source": "sklearn.load_digits upscaled 8x8→28x28"}
        except ImportError:  # pragma: no cover
            x, y, meta = synthetic_images(4096, (28, 28, 1), 10, seed=seed)
    return _subsample((x, y, meta), n, seed)


def _subsample(found: Arrays, n: Optional[int], seed: int) -> Arrays:
    """Uniform random subsample to ``n`` rows (no-op when n >= len)."""
    x, y, meta = found
    if n is not None and n < len(x):
        idx = np.random.default_rng(seed).permutation(len(x))[:n]
        x, y = x[idx], y[idx]
    return x, y, meta


def load_cifar10(n: int = 10_000, data_dir: Optional[str] = None, seed: int = 0) -> Arrays:
    """32×32×3, 10 classes (BASELINE config #2)."""
    found = _try_npz(data_dir, "cifar10", (32, 32, 3))
    if found is not None:
        return _subsample(found, n, seed)
    return synthetic_images(n, (32, 32, 3), 10, seed=seed)


def load_cifar100(n: int = 10_000, data_dir: Optional[str] = None, seed: int = 0) -> Arrays:
    """32×32×3, 100 classes (BASELINE config #5)."""
    found = _try_npz(data_dir, "cifar100", (32, 32, 3))
    if found is not None:
        return _subsample(found, n, seed)
    return synthetic_images(n, (32, 32, 3), 100, seed=seed)


def load_uci_wine() -> Arrays:
    """Real UCI wine (ships with sklearn) — BASELINE config #3."""
    from sklearn.datasets import load_wine

    data = load_wine()
    return (
        data.data.astype(np.float64),
        data.target.astype(np.int64),
        {"synthetic": False, "source": "sklearn.load_wine (UCI)"},
    )


def load_uci_binary() -> Arrays:
    """Real binary-classification UCI-style table (breast cancer, sklearn)."""
    from sklearn.datasets import load_breast_cancer

    data = load_breast_cancer()
    return (
        data.data.astype(np.float64),
        data.target.astype(np.int64),
        {"synthetic": False, "source": "sklearn.load_breast_cancer (UCI)"},
    )
