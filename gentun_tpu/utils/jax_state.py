"""Tracks whether this process's fitness path has initialized a jax backend.

The GA outer loop (``algorithms.py``) is pure bookkeeping and must never
trigger (or hang on) TPU runtime initialization just to normalise the
individuals/hour/chip metric.  jax offers no public "is a backend already
live?" probe, so instead of poking ``jax._src`` internals the fitness
entry points — the only code in this package that touches devices —
call :func:`mark_backend_used` right before their first device access,
and the GA consults :func:`backend_used`.

A false negative (some exotic caller touches jax outside the fitness
entry points) only degrades the metric to per-host instead of per-chip;
it can never force a backend init.
"""

from __future__ import annotations

_backend_used = False


def mark_backend_used() -> None:
    """Record that a jax backend has been (or is about to be) initialized."""
    global _backend_used
    _backend_used = True


def backend_used() -> bool:
    """True once any fitness entry point has touched jax devices."""
    return _backend_used
