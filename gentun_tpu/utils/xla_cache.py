"""Persistent XLA compilation cache (SURVEY.md §7 "hard parts" #1).

The masked-supergraph design already means one in-process compile serves the
whole search space (``models/cnn.py``), but a *restarted* search — the whole
point of the checkpoint/resume subsystem (``utils/checkpoint.py``) — would
pay the full XLA compile again.  jax ships a persistent on-disk compilation
cache; this module is the one place that manages it, so every entry point
(models, bench, examples) shares the same knob.

The cache is **ON by default** at ``~/.cache/gentun_tpu/xla`` (measured
3-6× cheaper than recompiling on restart — DISTRIBUTED.md).  Control it:

- ``GENTUN_TPU_CACHE_DIR=/path/to/cache`` relocates it;
- ``GENTUN_TPU_CACHE_DIR=off`` (or ``0``/``none``/``disabled``) turns it
  off, as does ``cache_dir=False`` on ``GeneticCnnModel`` /
  ``additional_parameters``;
- ``enable_compilation_cache("/path")`` enables it programmatically.

An unwritable cache directory degrades to caching disabled with a loud
warning — it must never take the training path down.

The thresholds are dropped to zero because GA fitness programs are small by
XLA standards: the default "only cache compiles > 1 s / > 0 bytes" heuristics
would skip exactly the programs we want cached.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Callable, Dict, Optional, Tuple

__all__ = [
    "cache_stats",
    "default_cache_dir",
    "enable_compilation_cache",
    "list_cache_entries",
    "register_publish_hook",
    "run_publish_hooks",
    "unregister_publish_hook",
]

logger = logging.getLogger("gentun_tpu")

_enabled_dir: Optional[str] = None
_failed_dirs: set = set()  # dirs that failed makedirs — don't retry/re-warn
_missing_knobs: set = set()  # jax config keys this jax lacks — warn once each

# Publish hooks: the compile cache service client
# (``distributed/compile_service.py``) registers its scan-and-publish here
# so ``models/cnn.py`` can announce "a first compile may just have written
# an entry" without the models layer importing the distributed package
# (which would pull the broker stack into every model import).
_publish_hooks: list = []


def default_cache_dir() -> Optional[str]:
    """The persistent-cache directory, ON by default (opt out explicitly).

    Resolution: ``GENTUN_TPU_CACHE_DIR`` if set (the values ``0``, ``off``
    and ``none`` disable caching); otherwise ``~/.cache/gentun_tpu/xla``.
    Measured on the real chip (DISTRIBUTED.md): a restarted search pays
    15-25 s per program to load from this cache versus 70-145 s to
    recompile — too big a win to leave opt-in.
    """
    d = os.environ.get("GENTUN_TPU_CACHE_DIR", "").strip()
    if d.lower() in ("0", "off", "none", "disabled"):
        return None
    if d:
        return d
    return os.path.join(os.path.expanduser("~"), ".cache", "gentun_tpu", "xla")


def enable_compilation_cache(cache_dir: str) -> Optional[str]:
    """Point jax's persistent compilation cache at ``cache_dir``.

    Idempotent; safe to call before or after jax backend init (the cache is
    consulted at compile time, not at backend-init time).  Returns the
    directory on success, or ``None`` when it could not be enabled (ADVICE
    r4: callers must be able to tell the difference — and a failed dir must
    not shadow a previously-enabled one, which stays active in jax).
    """
    global _enabled_dir
    cache_dir = os.path.abspath(os.path.expanduser(str(cache_dir)))
    if _enabled_dir == cache_dir:
        return cache_dir
    if cache_dir in _failed_dirs:
        return None
    try:
        os.makedirs(cache_dir, exist_ok=True)
    except OSError as e:
        # On-by-default must not break environments with unwritable HOMEs
        # (read-only containers, HOME=/nonexistent CI): degrade loudly.
        _failed_dirs.add(cache_dir)  # don't retry (and re-warn) every call
        if _enabled_dir is not None:
            logger.warning(
                "persistent XLA cache dir %s is unusable (%s); jax keeps "
                "caching at the previously-enabled %s", cache_dir, e, _enabled_dir,
            )
        else:
            logger.warning(
                "persistent XLA cache dir %s is unusable (%s); caching DISABLED "
                "— set GENTUN_TPU_CACHE_DIR to a writable path or to 'off' to "
                "silence this", cache_dir, e,
            )
        return None
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
    except Exception as e:  # noqa: BLE001 - version probe, not control flow
        # A jax without the persistent cache at all (ancient or exotic
        # build): degrade loudly instead of raising out of every entry
        # point — the training path must survive, it just recompiles.
        _failed_dirs.add(cache_dir)
        logger.warning(
            "this jax (%s) does not support the persistent compilation "
            "cache (%s); caching DISABLED — restarts and elastic joins "
            "will pay full recompiles", getattr(jax, "__version__", "?"), e)
        return None
    if _enabled_dir is not None and _enabled_dir != cache_dir:
        # jax materializes its cache object lazily and keeps it for the
        # process lifetime: without a reset, writes keep landing in the OLD
        # dir even though the config now names the new one (silently, as a
        # UserWarning per entry once the old dir disappears).
        try:
            from jax.experimental.compilation_cache import compilation_cache as _cc

            _cc.reset_cache()
        except Exception as e:  # noqa: BLE001 - version probe
            logger.warning(
                "could not reset jax's compilation-cache object while "
                "switching %s -> %s (%s); cache writes may keep using the "
                "old directory", _enabled_dir, cache_dir, e)
    # GA fitness programs compile in well under the default 1 s threshold on
    # CPU test runs; cache everything.  jax versions that lack these knobs
    # keep the cache enabled with their default thresholds — degraded
    # loudly (once per knob), because small programs may silently not be
    # cached there.
    # The third knob makes cache keys independent of the cache dir PATH:
    # by default jax derives an xla_gpu_per_fusion_autotune_cache_dir
    # under the cache dir and hashes that absolute path into every cache
    # key, so two hosts mounting the cache at different paths could never
    # reuse each other's artifacts through the compile service.
    for knob, value in (
            ("jax_persistent_cache_min_compile_time_secs", 0.0),
            ("jax_persistent_cache_min_entry_size_bytes", -1),
            ("jax_persistent_cache_enable_xla_caches", "none")):
        try:
            jax.config.update(knob, value)
        except Exception as e:  # noqa: BLE001 - version probe
            if knob not in _missing_knobs:
                _missing_knobs.add(knob)
                logger.warning(
                    "this jax (%s) has no %s config key (%s); the "
                    "persistent cache stays enabled with jax's default "
                    "threshold — small/fast programs may not be cached",
                    getattr(jax, "__version__", "?"), knob, e)
    _enabled_dir = cache_dir
    logger.info("persistent XLA compilation cache enabled at %s", cache_dir)
    return cache_dir


def list_cache_entries(cache_dir: Optional[str] = None) -> Dict[str, Tuple[int, float]]:
    """``{entry_name: (size_bytes, mtime)}`` for the cache directory.

    Entry names are jax's own cache-key hashes — they already encode the
    program, compile options and topology, which is what makes them valid
    content addresses for the compile service.  Dotfiles (in-flight
    ``.tmp`` writes) and subdirectories are skipped.  Defaults to the
    currently-enabled dir, falling back to :func:`default_cache_dir`.
    A missing directory is an empty cache, not an error.
    """
    d = cache_dir if cache_dir is not None else (_enabled_dir or default_cache_dir())
    if d is None:
        return {}
    out: Dict[str, Tuple[int, float]] = {}
    try:
        with os.scandir(d) as it:
            for entry in it:
                if entry.name.startswith("."):
                    continue
                try:
                    if not entry.is_file(follow_symlinks=False):
                        continue
                    st = entry.stat(follow_symlinks=False)
                except OSError:
                    continue
                out[entry.name] = (st.st_size, st.st_mtime)
    except FileNotFoundError:
        return {}
    return out


def cache_stats(cache_dir: Optional[str] = None) -> Dict[str, Any]:
    """Entry count + total bytes for ``/statusz``-style reporting."""
    d = cache_dir if cache_dir is not None else (_enabled_dir or default_cache_dir())
    entries = list_cache_entries(d)
    return {
        "dir": d,
        "enabled": _enabled_dir is not None and d == _enabled_dir,
        "entries": len(entries),
        "bytes": sum(size for size, _mtime in entries.values()),
    }


def register_publish_hook(fn: Callable[[], Any]) -> None:
    """Register a zero-arg callable to run after potential first compiles."""
    if fn not in _publish_hooks:
        _publish_hooks.append(fn)


def unregister_publish_hook(fn: Callable[[], Any]) -> None:
    _publish_hooks[:] = [h for h in _publish_hooks if h != fn]


def run_publish_hooks() -> None:
    """Run registered hooks; a failing hook never takes the caller down.

    Called from ``models/cnn.py::_prepare_population_setup`` right after
    the compile path runs — with no hooks registered this is one empty
    list iteration, so the default (service-less) configuration pays
    nothing.
    """
    for fn in list(_publish_hooks):
        try:
            fn()
        except Exception:  # noqa: BLE001 - hook boundary by design
            logger.warning("compile-cache publish hook %r failed", fn,
                           exc_info=True)
