"""Persistent XLA compilation cache (SURVEY.md §7 "hard parts" #1).

The masked-supergraph design already means one in-process compile serves the
whole search space (``models/cnn.py``), but a *restarted* search — the whole
point of the checkpoint/resume subsystem (``utils/checkpoint.py``) — would
pay the full XLA compile again.  jax ships a persistent on-disk compilation
cache; this module is the one place that manages it, so every entry point
(models, bench, examples) shares the same knob.

The cache is **ON by default** at ``~/.cache/gentun_tpu/xla`` (measured
3-6× cheaper than recompiling on restart — DISTRIBUTED.md).  Control it:

- ``GENTUN_TPU_CACHE_DIR=/path/to/cache`` relocates it;
- ``GENTUN_TPU_CACHE_DIR=off`` (or ``0``/``none``/``disabled``) turns it
  off, as does ``cache_dir=False`` on ``GeneticCnnModel`` /
  ``additional_parameters``;
- ``enable_compilation_cache("/path")`` enables it programmatically.

An unwritable cache directory degrades to caching disabled with a loud
warning — it must never take the training path down.

The thresholds are dropped to zero because GA fitness programs are small by
XLA standards: the default "only cache compiles > 1 s / > 0 bytes" heuristics
would skip exactly the programs we want cached.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

__all__ = ["enable_compilation_cache", "default_cache_dir"]

logger = logging.getLogger("gentun_tpu")

_enabled_dir: Optional[str] = None
_failed_dirs: set = set()  # dirs that failed makedirs — don't retry/re-warn


def default_cache_dir() -> Optional[str]:
    """The persistent-cache directory, ON by default (opt out explicitly).

    Resolution: ``GENTUN_TPU_CACHE_DIR`` if set (the values ``0``, ``off``
    and ``none`` disable caching); otherwise ``~/.cache/gentun_tpu/xla``.
    Measured on the real chip (DISTRIBUTED.md): a restarted search pays
    15-25 s per program to load from this cache versus 70-145 s to
    recompile — too big a win to leave opt-in.
    """
    d = os.environ.get("GENTUN_TPU_CACHE_DIR", "").strip()
    if d.lower() in ("0", "off", "none", "disabled"):
        return None
    if d:
        return d
    return os.path.join(os.path.expanduser("~"), ".cache", "gentun_tpu", "xla")


def enable_compilation_cache(cache_dir: str) -> Optional[str]:
    """Point jax's persistent compilation cache at ``cache_dir``.

    Idempotent; safe to call before or after jax backend init (the cache is
    consulted at compile time, not at backend-init time).  Returns the
    directory on success, or ``None`` when it could not be enabled (ADVICE
    r4: callers must be able to tell the difference — and a failed dir must
    not shadow a previously-enabled one, which stays active in jax).
    """
    global _enabled_dir
    cache_dir = os.path.abspath(os.path.expanduser(str(cache_dir)))
    if _enabled_dir == cache_dir:
        return cache_dir
    if cache_dir in _failed_dirs:
        return None
    try:
        os.makedirs(cache_dir, exist_ok=True)
    except OSError as e:
        # On-by-default must not break environments with unwritable HOMEs
        # (read-only containers, HOME=/nonexistent CI): degrade loudly.
        _failed_dirs.add(cache_dir)  # don't retry (and re-warn) every call
        if _enabled_dir is not None:
            logger.warning(
                "persistent XLA cache dir %s is unusable (%s); jax keeps "
                "caching at the previously-enabled %s", cache_dir, e, _enabled_dir,
            )
        else:
            logger.warning(
                "persistent XLA cache dir %s is unusable (%s); caching DISABLED "
                "— set GENTUN_TPU_CACHE_DIR to a writable path or to 'off' to "
                "silence this", cache_dir, e,
            )
        return None
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # GA fitness programs compile in well under the default 1 s threshold on
    # CPU test runs; cache everything.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    _enabled_dir = cache_dir
    logger.info("persistent XLA compilation cache enabled at %s", cache_dir)
    return cache_dir
