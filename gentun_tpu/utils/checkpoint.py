"""Generation-boundary checkpoint/resume.

The reference has NO checkpointing: the population lives in master memory
and a crash loses the whole search (SURVEY.md §5 "Checkpoint / resume").
The rebuild adds the subsystem the survey prescribes: at every generation
boundary, persist {genes, fitness, RNG state, history} as JSON — tiny,
human-readable, and enough to resume a search bit-exactly (the GA consumes
randomness only from its own generator, whose state is saved).

Model weights are deliberately NOT checkpointed: fitness evaluation is
stateless by design (every individual trains from scratch), so there is no
model state worth resuming — which is also why JSON suffices over orbax.

Schema versioning: every checkpoint written carries ``schema_version``.
Version history:

- **1** (implicit — files without the field): generational GA state only.
- **2**: adds the asynchronous steady-state scheduler state
  (``AsyncEvolution``: completion counters, dispatch-ordered in-flight
  children, ever-best individual) and the ``algorithm`` tag both loaders
  use to refuse each other's files.
- **3**: adds the multi-fidelity ladder state (``AsyncEvolution`` with
  ``fidelity_ladder=``): the ladder itself, per-rung completion records,
  per-member rung/promotion markers, per-rung best genomes, and in-flight
  entries widened from bare genes to ``{genes, rung, kind, member_index}``
  so an in-flight PROMOTION resumes as a promotion of the same ring
  member, not as a fresh child.  v2 files load (their in-flight lists
  read as rung-0 children), and ladderless runs still write a state v2
  readers would recognize field-for-field — the version is bumped because
  a v2 reader resuming a LADDERED file would silently drop every rung.
- **4**: adds the surrogate rung −1 state (``AsyncEvolution`` with
  ``surrogate=``): the ridge model (weights AND training samples), the
  rolling score window, pending gate decisions (admitted score awaiting
  its realized fitness), precision@k pairs, and the degradation flag —
  everything a killed master needs to resume the gated trajectory
  bit-identically.  v3 (and older) files load fine; the version is
  bumped because a v3 reader resuming a GATED file would silently drop
  the model and window, replaying admissions against empty state and
  diverging from the uninterrupted trajectory.

Loading is backward-compatible (a v1 file loads fine) but not
forward-compatible: a file stamped NEWER than this code understands is
refused loudly rather than half-restored.
"""

from __future__ import annotations

import json
import os
import re
import tempfile
from typing import Any, Dict, Optional

__all__ = ["Checkpointer", "load_checkpoint", "namespaced_path",
           "CHECKPOINT_SCHEMA"]

#: Newest checkpoint layout this code can write and read (see the module
#: docstring for the version history).
CHECKPOINT_SCHEMA = 4


def _to_jsonable(obj: Any) -> Any:
    """numpy scalars/arrays → plain Python, recursively (RNG state has them)."""
    import numpy as np

    if isinstance(obj, dict):
        return {k: _to_jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_to_jsonable(v) for v in obj]
    if isinstance(obj, np.ndarray):
        return _to_jsonable(obj.tolist())
    if isinstance(obj, np.generic):
        return obj.item()
    return obj


def namespaced_path(path: str, namespace: Optional[str]) -> str:
    """Insert a per-session namespace into a checkpoint path.

    ``search.json`` + namespace ``tenant-a`` → ``search.tenant-a.json``,
    so concurrent searches sharing one fleet (DISTRIBUTED.md "Multi-tenant
    search sessions") never clobber each other's checkpoints.  The
    namespace is sanitized to filename-safe characters; ``None``/empty
    returns the path unchanged.
    """
    if not namespace:
        return str(path)
    safe = re.sub(r"[^A-Za-z0-9._-]", "_", str(namespace))
    root, ext = os.path.splitext(str(path))
    return f"{root}.{safe}{ext}" if ext else f"{root}.{safe}"


class Checkpointer:
    """Atomic JSON checkpoints, attached to a GA via ``set_checkpointer``.

    ``GeneticAlgorithm.evolve_population`` calls :meth:`save` after every
    generation; :meth:`resume` restores an algorithm to the last saved
    state.  Writes are tmp-file + rename, so a crash mid-write leaves the
    previous checkpoint intact.
    """

    def __init__(self, path: str, keep_history: bool = True,
                 namespace: Optional[str] = None):
        self.path = namespaced_path(path, namespace)
        self.namespace = str(namespace) if namespace else None
        self.keep_history = keep_history

    def save(self, algorithm) -> None:
        state = algorithm.state_dict()
        state["schema_version"] = CHECKPOINT_SCHEMA
        if not self.keep_history:
            state["history"] = state["history"][-1:]
        payload = json.dumps(_to_jsonable(state), separators=(",", ":"))
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".ckpt-", suffix=".json")
        try:
            with os.fdopen(fd, "w") as f:
                f.write(payload)
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def load(self) -> Optional[Dict[str, Any]]:
        if not os.path.exists(self.path):
            return None
        with open(self.path) as f:
            state = json.load(f)
        version = state.get("schema_version", 1)  # pre-versioning files are v1
        if version > CHECKPOINT_SCHEMA:
            raise ValueError(
                f"checkpoint {self.path!r} has schema version {version}, newer "
                f"than this code understands (max {CHECKPOINT_SCHEMA}) — "
                "refusing a partial restore; upgrade gentun_tpu to resume it")
        return state

    def resume(self, algorithm) -> bool:
        """Restore ``algorithm`` from the checkpoint; True if one existed."""
        state = self.load()
        if state is None:
            return False
        algorithm.load_state_dict(state)
        return True


def load_checkpoint(path: str) -> Optional[Dict[str, Any]]:
    return Checkpointer(path).load()
