"""Tracing/profiling hooks (SURVEY.md §5 "Tracing / profiling": absent in
the reference; the rebuild adds cheap, high-value instrumentation).

Two tools:

- :func:`trace` — context manager around ``jax.profiler`` producing a
  TensorBoard-loadable trace of the fitness hot path;
- :class:`EvalTimer` — per-evaluation wall/throughput record keeping, the
  source of the north-star metric (individuals/hour/chip) at finer grain
  than the per-generation log.

Since the telemetry plane landed (``gentun_tpu/telemetry``,
docs/OBSERVABILITY.md), :class:`EvalTimer` is a thin compatibility layer:
each ``measure()`` block ALSO emits an ``eval_timer`` span into the active
telemetry run (when tracing is enabled), so old call sites feed the new
``telemetry.jsonl`` artifact without changes.  New code should open spans
directly (``telemetry.span(...)``).
"""

from __future__ import annotations

import contextlib
import json
import logging
import time
from typing import Any, Dict, List, Optional

from ..telemetry import spans as _tele

__all__ = ["trace", "EvalTimer"]

logger = logging.getLogger("gentun_tpu")


@contextlib.contextmanager
def trace(logdir: str, enabled: bool = True):
    """``with trace('/tmp/tb'): population.evaluate()`` → profiler dump.

    No-ops cleanly when disabled or when jax is unavailable, so call sites
    can leave the hook in place unconditionally.
    """
    if not enabled:
        yield
        return
    try:
        import jax.profiler as jprof
    except ImportError:  # pragma: no cover
        yield
        return
    jprof.start_trace(logdir)
    try:
        yield
    finally:
        jprof.stop_trace()
        logger.info("profiler trace written to %s", logdir)


class EvalTimer:
    """Accumulates per-evaluation timings; reports the north-star metric."""

    def __init__(self, n_chips: int = 1):
        self.n_chips = max(1, int(n_chips))
        self.records: List[Dict[str, Any]] = []

    @contextlib.contextmanager
    def measure(self, n_individuals: int, label: str = ""):
        t0 = time.monotonic()
        yield
        elapsed = max(time.monotonic() - t0, 1e-9)
        rec = {
            "label": label,
            "individuals": int(n_individuals),
            "wall_s": round(elapsed, 4),
            "individuals_per_hour_per_chip": round(
                n_individuals / (elapsed / 3600.0) / self.n_chips, 2
            ),
        }
        self.records.append(rec)
        # Absorbed into the telemetry plane: the measurement doubles as an
        # `eval_timer` span so legacy call sites appear in telemetry.jsonl.
        _tele.record_span(
            "eval_timer", t0, elapsed,
            attrs={"label": label, "individuals": int(n_individuals)},
        )
        logger.info("eval %s", json.dumps(rec))

    @property
    def total_individuals(self) -> int:
        return sum(r["individuals"] for r in self.records)

    def summary(self) -> Dict[str, Any]:
        wall = max(sum(r["wall_s"] for r in self.records), 1e-9)
        n = self.total_individuals
        return {
            "individuals": n,
            "wall_s": round(wall, 3),
            "individuals_per_hour_per_chip": round(n / (wall / 3600.0) / self.n_chips, 2),
        }
