"""Small paired-comparison statistics for experiment scripts.

Shared by ``scripts/search_efficacy.py`` (GA vs random, paired by seed —
SEARCH.md) and ``scripts/stage_exit_conv_study.py`` (paper vs bare-sum
stage exit, paired by genome — docs/STAGE_EXIT_CONV.md).  Pure
numpy + stdlib: scipy is deliberately NOT a dependency of this package
(pyproject), and the exact Binomial(n, 1/2) arithmetic is three lines.
"""

from __future__ import annotations

from math import comb
from typing import Dict, Tuple

import numpy as np

__all__ = ["sign_test_p", "bootstrap_ci", "paired_row", "fmt_paired"]


def sign_test_p(deltas: np.ndarray) -> float:
    """Two-sided exact sign test on the non-zero paired deltas.

    Two-sided p = sum of Binomial(n, 1/2) pmf over all outcomes whose pmf
    is ≤ pmf(observed wins) — the standard minimum-likelihood definition
    (matches ``scipy.stats.binomtest(..., p=0.5)``, verified in tests).
    """
    deltas = np.asarray(deltas, dtype=np.float64)
    nz = deltas[deltas != 0]
    n = len(nz)
    if n == 0:
        return 1.0
    wins = int((nz > 0).sum())
    pmf = [comb(n, j) * 0.5**n for j in range(n + 1)]
    p = sum(pj for pj in pmf if pj <= pmf[wins] * (1 + 1e-12))
    return float(min(1.0, p))


def bootstrap_ci(
    deltas: np.ndarray, n_boot: int = 10_000, alpha: float = 0.05, seed: int = 0
) -> Tuple[float, float]:
    """Seeded percentile bootstrap CI for the mean of paired deltas."""
    deltas = np.asarray(deltas, dtype=np.float64)
    rng = np.random.default_rng(seed)
    idx = rng.integers(0, len(deltas), size=(n_boot, len(deltas)))
    means = deltas[idx].mean(axis=1)
    return (float(np.quantile(means, alpha / 2)), float(np.quantile(means, 1 - alpha / 2)))


def paired_row(deltas: np.ndarray) -> Dict:
    """Full paired summary: mean, bootstrap CI, win rate, exact sign test."""
    deltas = np.asarray(deltas, dtype=np.float64)
    lo, hi = bootstrap_ci(deltas)
    return {
        "mean": float(deltas.mean()),
        "ci": (lo, hi),
        "wins": int((deltas > 0).sum()),
        "ties": int((deltas == 0).sum()),
        "n": int(len(deltas)),
        "p_sign": sign_test_p(deltas),
    }


def fmt_paired(s: Dict) -> str:
    """One markdown-table cell: ``mean [CI] | wins/n | p``."""
    return (
        f"{s['mean']:+.4f} [{s['ci'][0]:+.4f}, {s['ci'][1]:+.4f}] | "
        f"{s['wins']}/{s['n'] - s['ties']}"
        + (f" ({s['ties']} ties)" if s["ties"] else "")
        + f" | {s['p_sign']:.3f}"
    )
