"""Auxiliary subsystems the reference lacks (SURVEY.md §5): checkpoint /
resume, offline-safe dataset loaders, tracing/metrics."""

from .checkpoint import Checkpointer, load_checkpoint
from .profiling import EvalTimer, trace

__all__ = ["Checkpointer", "load_checkpoint", "EvalTimer", "trace"]
