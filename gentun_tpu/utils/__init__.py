"""Auxiliary subsystems the reference lacks (SURVEY.md §5): checkpoint /
resume, cross-run fitness persistence, offline-safe dataset loaders,
tracing/metrics."""

from .checkpoint import CHECKPOINT_SCHEMA, Checkpointer, load_checkpoint
from .fitness_store import fidelity_fingerprint, load_fitness_cache, save_fitness_cache
from .profiling import EvalTimer, trace
from .xla_cache import default_cache_dir, enable_compilation_cache

__all__ = [
    "Checkpointer",
    "load_checkpoint",
    "CHECKPOINT_SCHEMA",
    "load_fitness_cache",
    "save_fitness_cache",
    "fidelity_fingerprint",
    "EvalTimer",
    "trace",
    "enable_compilation_cache",
    "default_cache_dir",
]
