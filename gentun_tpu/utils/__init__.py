"""Auxiliary subsystems the reference lacks (SURVEY.md §5): checkpoint /
resume, offline-safe dataset loaders, tracing/metrics."""

from .checkpoint import Checkpointer, load_checkpoint
from .profiling import EvalTimer, trace
from .xla_cache import default_cache_dir, enable_compilation_cache

__all__ = [
    "Checkpointer",
    "load_checkpoint",
    "EvalTimer",
    "trace",
    "enable_compilation_cache",
    "default_cache_dir",
]
