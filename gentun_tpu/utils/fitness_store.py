"""Cross-run fitness persistence: never train the same architecture twice,
even across separate searches.

``Population.fitness_cache`` already spans generations within one search
and rides checkpoints within one resumed search (``utils/checkpoint.py``).
This module extends the reuse across PROCESSES and EXPERIMENTS, the same
way ``utils/xla_cache.py`` persists compilations: a plain JSON file of
``[cache_key, fitness]`` pairs that any number of runs can load, extend,
and merge.  The reference has no counterpart (its only reuse is in-memory
``get_fitness`` caching [PUB]); repeated experimentation — exactly the
workload a hyperparameter-search tool exists for — retrains everything.

Keys are ``Individual.cache_key()`` values (nested tuples of JSON-native
leaves; architecture-canonical for ``GeneticCnnIndividual``), serialized
with the checkpoint's tuple↔list convention.  Keys that embed non-JSON
values are skipped on save, like the checkpoint does — a dropped entry
only costs a retrain.

Usage::

    cache = load_fitness_cache("digits_s35.fitness.json")   # {} if absent
    pop = Population(GeneticCnnIndividual, ..., fitness_cache=cache)
    GeneticAlgorithm(pop, seed=0).run(50)
    save_fitness_cache(pop.fitness_cache, "digits_s35.fitness.json")

``save_fitness_cache`` MERGES with whatever is already in the file (other
runs may have written since we loaded), and writes atomically.

The cache key embeds ``additional_parameters``, so entries are only ever
reused for identical training configurations; a changed schedule or
dataset size produces disjoint keys.  Changed dataset CONTENT under the
same configuration is the caller's responsibility, exactly as with the
reference's in-memory cache — keep one file per dataset.

**Mixed-version fleets: all writers upgrade together.**  The payload
carries a ``version`` (file schema) besides ``protocol`` (fitness
semantics).  Writers REFUSE files whose version exceeds their own
``STORE_VERSION`` — refusing is the only safe move, because an older
writer's read-merge-write cycle would load a newer file as empty (its
loader ignores unknown protocols) and then rewrite it, silently
destroying every newer-protocol entry under the old stamp.  Readers
likewise ignore newer files rather than guessing at their schema.  The
consequence is operational, not mechanical: when a store file is shared
between machines (workers with ``--fitness-store``, masters with
``fitness_store=``), upgrade every writer to the same code revision
before any of them runs — a mixed fleet degrades to refusals (loud, no
data loss on the new side) but pre-``STORE_VERSION``-aware writers
(version 1) predate this guard and WILL clobber newer files; do not
point them at a shared store.
"""

from __future__ import annotations

import contextlib
import json
import os
import tempfile
from typing import Any, Dict

__all__ = [
    "load_fitness_cache", "save_fitness_cache", "tuplify",
    "is_serializable_key", "fidelity_fingerprint", "key_digest",
    "FITNESS_PROTOCOL", "STORE_VERSION",
]

#: Fitness-measurement RNG protocol.  Bump whenever a model's fitness for
#: the SAME (cache_key, config, seed) changes incompatibly, so persisted
#: values from older protocols are never silently mixed with new
#: measurements (mixed protocols steer a search exactly the way the
#: content-hash purity work exists to prevent).  History:
#:   1 — per-slot PRNG keys (``split(PRNGKey(seed+f), pop)``), rounds 1-4:
#:       fitness depended on batch slot/composition;
#:   2 — content-hash keys (``models/cnn._genome_hashes``), round 5:
#:       fitness is a pure function of (architecture, config, seed);
#:   3 — 64-bit content hashes (blake2b split across two fold_in calls),
#:       round 6: init/dropout streams collision-free at 10k+ genomes.
FITNESS_PROTOCOL = 3

#: File-schema version.  Bump together with any payload change; writers
#: refuse files with a NEWER version (see module docstring — an older
#: writer merging a newer file would load it as empty and clobber it).
#: History: 1 — original payload; 2 — version guard introduced;
#: 3 — entries carry a fidelity fingerprint (``[key, fitness, fp]``) so
#: proxy-rung and full-schedule measurements of the same genome can never
#: be conflated, even if the set of fidelity-relevant knobs changes
#: between code revisions (mismatched fingerprints drop loudly on load).
STORE_VERSION = 3

#: The ``additional_parameters`` knobs that change what a fitness number
#: MEANS (a 1-epoch 2-fold proxy measurement is not the full-schedule
#: fitness of the same genome).  The fingerprint below hashes exactly
#: this subset, so adding a knob here invalidates persisted entries that
#: predate it — loudly, via the v3 load-time cross-check — instead of
#: silently reusing a lower-fidelity number at a higher rung.
FIDELITY_KNOBS = ("kfold", "epochs", "learning_rate", "fitness_reps", "warm_start")


def fidelity_fingerprint(params: Any) -> str:
    """12-hex-char digest of the fidelity-relevant subset of ``params``.

    ``params`` may be a mapping (``additional_parameters`` as configured)
    or its frozen form (a tuple of sorted ``(key, value)`` pairs — the
    third component of a cache key).  Knobs absent from ``params`` are
    omitted from the digest, so configs that never mention a knob keep a
    stable fingerprint when defaults move.  This string is the wire
    ``fidelity.fingerprint`` field and the store's per-entry stamp.
    """
    import hashlib

    if not isinstance(params, dict):
        try:
            params = dict(params or ())
        except (TypeError, ValueError):
            params = {}
    subset = {k: params[k] for k in FIDELITY_KNOBS if k in params}
    blob = json.dumps({"v": 1, "knobs": subset}, sort_keys=True, default=str)
    return hashlib.blake2b(blob.encode(), digest_size=6).hexdigest()


def key_digest(key: Any) -> str:
    """16-hex-char (64-bit) blake2b content address of a cache key.

    The networked fitness service (``distributed/fitness_service.py``)
    addresses entries by this digest instead of shipping whole keys: the
    same width as the genome content hashes of FITNESS_PROTOCOL 3
    (collision-free at 10k+ genomes), computed over the key's canonical
    JSON serialization — so two runs that freeze the same architecture
    and config produce the same address without sharing any state.  The
    caller must hold a JSON-serializable key (``is_serializable_key``);
    tuples serialize as lists, which is fine because BOTH sides of every
    comparison go through the same ``json.dumps``.
    """
    import hashlib

    blob = json.dumps(key, separators=(",", ":"), default=str)
    return hashlib.blake2b(blob.encode(), digest_size=8).hexdigest()


def _key_fingerprint(key: Any) -> str:
    """Fingerprint of a cache key's embedded ``additional_parameters``.

    Every ``Individual.cache_key()`` shape ends with the frozen
    additional_parameters tuple; anything else fingerprints as "no
    fidelity knobs" (the empty-config digest), which is correct for
    synthetic test keys that carry no training config at all.
    """
    if isinstance(key, tuple) and key and isinstance(key[-1], tuple):
        return fidelity_fingerprint(key[-1])
    return fidelity_fingerprint({})


def tuplify(obj: Any) -> Any:
    """Inverse of JSON's tuple→list coercion.

    THE canonical definition of the cache-key serialization convention —
    the checkpoint (``algorithms.state_dict``) and this store share it, so
    a cache saved by either subsystem round-trips through the other.
    """
    if isinstance(obj, list):
        return tuple(tuplify(v) for v in obj)
    return obj


def is_serializable_key(key: Any) -> bool:
    """True when a cache key survives the JSON round trip.

    Keys that embed non-JSON values (bytes from ndarray params, arbitrary
    objects) are skipped by both persistence subsystems — never crash a
    search over a cache entry; a dropped one only costs a retrain.
    """
    try:
        json.dumps(key)
    except (TypeError, ValueError):
        return False
    return True


@contextlib.contextmanager
def _file_lock(path: str):
    """Exclusive advisory lock serializing read-merge-write cycles.

    Uses a sidecar ``<path>.lock`` (flock on the data file itself would be
    lost across the atomic rename).  Best-effort on platforms without
    fcntl — the write itself stays atomic either way.
    """
    try:
        import fcntl
    except ImportError:  # pragma: no cover - non-POSIX
        yield
        return
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path + ".lock", "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            yield
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)


def _read_store(path: str):
    """ONE read of the store file → ``(version, cache)``.

    The shared parse for load and save — the save path used to probe the
    version with its own ``json.load`` and then call the loader, parsing
    the file twice inside the same lock.  Missing file → ``(STORE_VERSION,
    {})``.  A NEWER-versioned file returns its version with an empty cache
    and is left untouched — callers own the refusal messaging (load warns
    and ignores, save errors and aborts).  Protocol mismatch warns here
    (both callers ignore such entries identically); corruption quarantines
    to ``<path>.corrupt`` and reads as version 1, empty.
    """
    if not os.path.exists(path):
        return STORE_VERSION, {}
    try:
        with open(path) as f:
            payload = json.load(f)
        version = payload.get("version", 1)
        if version > STORE_VERSION:
            return version, {}
        proto = payload.get("protocol", 1)
        if proto != FITNESS_PROTOCOL:
            import logging

            logging.getLogger("gentun_tpu").warning(
                "fitness store %s was measured under RNG protocol %s "
                "(current: %s); IGNORING its entries — fitness values are "
                "not comparable across protocols, and mixing them would "
                "silently steer the search.  The file is left untouched; "
                "the next save rewrites it at the current protocol.",
                path, proto, FITNESS_PROTOCOL,
            )
            return version, {}
        cache: Dict[Any, float] = {}
        dropped = 0
        for entry in payload["entries"]:
            if len(entry) >= 3:
                # v3 entry: [key, fitness, fidelity fingerprint].  The
                # stamp was computed from the key at save time; recompute
                # and cross-check so entries written when a DIFFERENT set
                # of knobs counted as fidelity-relevant are dropped (a
                # retrain) instead of reused at the wrong rung.
                k, v, fp = entry[0], entry[1], entry[2]
                key = tuplify(k)
                if fp != _key_fingerprint(key):
                    dropped += 1
                    continue
            else:
                k, v = entry
                key = tuplify(k)
            cache[key] = float(v)
        if dropped:
            import logging

            logging.getLogger("gentun_tpu").warning(
                "fitness store %s: dropped %d entr%s whose fidelity "
                "fingerprint no longer matches this code revision's "
                "FIDELITY_KNOBS — those genomes will retrain rather than "
                "reuse a measurement of unknown fidelity.",
                path, dropped, "y" if dropped == 1 else "ies",
            )
        return version, cache
    except (ValueError, KeyError, TypeError, AttributeError) as e:
        backup = path + ".corrupt"
        try:
            os.replace(path, backup)
        except OSError:
            backup = "<unmovable>"
        import logging

        logging.getLogger("gentun_tpu").warning(
            "fitness store %s is unreadable (%s); starting empty, original "
            "kept at %s", path, e, backup,
        )
        return 1, {}


def load_fitness_cache(path: str) -> Dict[Any, float]:
    """Fitness cache from ``path`` (empty dict when the file doesn't exist).

    The returned dict is a plain ``fitness_cache`` for any Population.
    A corrupt or schema-mismatched file degrades to an empty cache with a
    loud warning (the original is preserved as ``<path>.corrupt``) — per
    this module's convention, a cache must NEVER crash a search, least of
    all at the end-of-run save that would lose the measurements.
    """
    version, cache = _read_store(path)
    if version > STORE_VERSION:
        import logging

        logging.getLogger("gentun_tpu").warning(
            "fitness store %s has file-schema version %s, newer than "
            "this writer's %s; IGNORING it — upgrade this process "
            "before sharing the store (see utils/fitness_store.py).  "
            "The file is left untouched.",
            path, version, STORE_VERSION,
        )
        return {}
    return cache


def save_fitness_cache(cache: Dict[Any, float], path: str) -> int:
    """Merge ``cache`` into ``path`` atomically; returns total entries stored.

    The read-merge-write cycle runs under an exclusive file lock, so
    concurrent savers serialize instead of losing each other's new
    entries; on a key collision the in-memory value wins (it is the most
    recent measurement).  Non-JSON-serializable keys are skipped silently,
    per the checkpoint convention.
    """
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)  # before locking: works with or without fcntl
    with _file_lock(path):
        # A newer-versioned file must not be rewritten: our loader reads it
        # as empty, so the merge below would atomically replace it with only
        # this process's entries — destroying the newer fleet's measurements.
        # ONE read answers both the version guard and the merge base.
        existing_version, merged = _read_store(path)
        if existing_version > STORE_VERSION:
            import logging

            logging.getLogger("gentun_tpu").error(
                "REFUSING to save fitness store %s: its file-schema "
                "version %s is newer than this writer's %s.  Upgrade "
                "this process, or point it at a different store file; "
                "these measurements were NOT persisted.",
                path, existing_version, STORE_VERSION,
            )
            return 0
        for k, v in cache.items():
            if not is_serializable_key(k):
                continue
            merged[k] = float(v)
        payload = {
            "version": STORE_VERSION,
            "protocol": FITNESS_PROTOCOL,
            "entries": [[k, v, _key_fingerprint(k)] for k, v in merged.items()],
        }
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".fitness-", suffix=".json")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(payload, f, separators=(",", ":"))
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
    return len(merged)
