"""Device-mesh helpers: population × data parallelism for fitness training.

The reference's only parallelism is population-level task parallelism over
RabbitMQ workers, each training on a single GPU (SURVEY.md §2.2).  The
rebuild keeps that control-plane parallelism (``distributed/``) and adds the
one new axis the north star asks for: **multi-chip scaling inside a worker**
over a ``jax.sharding.Mesh``.

Two named axes:

- ``pop`` — shards the vmapped population axis of the batched trainer
  (``models/cnn.py``).  Individuals are independent, so this axis needs
  ZERO collectives: pure scale-out, the GA's dominant regime.
- ``data`` — shards the per-step training batch.  Params stay replicated
  along ``data``; XLA's sharding propagation inserts the gradient
  all-reduce over ICI automatically (GSPMD), which is the entire
  data-parallel implementation — no hand-written collectives, per the
  scaling-book recipe: pick a mesh, annotate shardings, let XLA insert
  collectives.

No function here changes the compiled computation: multi-chip execution is
driven purely by the shardings of the input arrays (``shard_cv_args``),
which is what keeps the single-chip and 32-chip paths one and the same
jitted program.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .multihost import place, place_tree

__all__ = ["auto_mesh", "pad_population", "shard_cv_args", "mesh_axis_sizes"]


def _largest_divisor_leq(n: int, cap: int) -> int:
    """Largest divisor of ``n`` that is <= cap (>=1)."""
    for d in range(min(n, cap), 0, -1):
        if n % d == 0:
            return d
    return 1


def auto_mesh(
    pop_size: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    pop_axis: Optional[int] = None,
    data_axis: Optional[int] = None,
) -> Optional[Mesh]:
    """Factor the available devices into a ``(pop, data)`` mesh.

    Preference order: put devices on the communication-free ``pop`` axis
    (up to ``pop_size``); spill the rest onto ``data``.  Returns ``None``
    on a single device — the caller then skips sharding entirely, so the
    one-chip path stays annotation-free.

    Explicit ``pop_axis``/``data_axis`` override the heuristic (their
    product must equal the device count).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if n == 1:
        return None
    if pop_axis is not None or data_axis is not None:
        pop_axis = pop_axis or (n // (data_axis or 1))
        data_axis = data_axis or (n // pop_axis)
        if pop_axis * data_axis != n:
            raise ValueError(f"pop_axis*data_axis = {pop_axis}*{data_axis} != {n} devices")
    else:
        cap = n if pop_size is None else max(1, pop_size)
        pop_axis = _largest_divisor_leq(n, cap)
        data_axis = n // pop_axis
    mesh_devices = np.asarray(devices).reshape(pop_axis, data_axis)
    return Mesh(mesh_devices, axis_names=("pop", "data"))


def mesh_axis_sizes(mesh: Optional[Mesh]) -> Tuple[int, int]:
    if mesh is None:
        return 1, 1
    return mesh.shape["pop"], mesh.shape["data"]


def pad_population(genomes: Sequence[Any], multiple: int) -> Tuple[List[Any], int]:
    """Pad the genome list to a multiple of the pop-axis size.

    Padding repeats the last genome; callers slice the results back to the
    original length.  Returns (padded_list, original_length).
    """
    n = len(genomes)
    if multiple <= 1 or n % multiple == 0:
        return list(genomes), n
    padded = list(genomes) + [genomes[-1]] * (multiple - n % multiple)
    return padded, n


def shard_cv_args(
    mesh: Mesh,
    params,
    masks_stacked: List[Dict[str, Any]],
    fold_keys,
    arrays: Dict[str, Any],
):
    """Place the batched-CV inputs onto the mesh.

    Array layouts after the fold-batched redesign (``models/cnn.py``): the
    fold axis leads ``params (kfold, P, ...)``, ``fold_keys (kfold, P, 2)``,
    ``batch_idx (kfold, steps, batch)``, ``val_idx``/``val_weight
    (kfold, n_val_padded)``; masks keep their ``(P, ...)`` leading axis.

    - ``params`` / ``fold_keys``: ``pop`` shards axis 1 (the population);
      the fold axis and ``data`` are replicated;
    - ``masks``: ``pop`` shards axis 0;
    - ``batch_idx``: batch dim (last) over ``data`` — this is what makes
      each training step data-parallel, because the gathers that consume
      these indices inherit the sharding and the loss/grad reduce over the
      batch becomes an ICI all-reduce;
    - the dataset and val index/weight arrays: replicated.  Workers own
      their whole data shard by design (SURVEY.md §1), so replication here
      is within one worker's slice only.
    """
    pop_spec = NamedSharding(mesh, P("pop"))
    fold_pop_spec = NamedSharding(mesh, P(None, "pop"))
    repl = NamedSharding(mesh, P())
    batch_spec = NamedSharding(mesh, P(None, None, "data"))

    # place/place_tree = device_put single-process; the multi-controller
    # make_array path when the mesh spans several hosts (multihost.py).
    params = place_tree(params, fold_pop_spec)
    masks_stacked = [
        {k: place(v, pop_spec) for k, v in stage.items()}
        for stage in masks_stacked
    ]
    fold_keys = place(fold_keys, fold_pop_spec)
    out = dict(arrays)
    for name in ("x_full", "y_full", "val_idx", "val_weight"):
        out[name] = place(out[name], repl)
    out["batch_idx"] = place(out["batch_idx"], batch_spec)
    return params, masks_stacked, fold_keys, out
